"""High availability: snapshot + WAL replication, leader lease with
fencing, hot-standby failover.

The reference runs CraneCtld as a single process kept available by
Keepalived (PAPER: "CONTROL PLANE — CraneCtld (one process, HA via
Keepalived)") over the embedded DB.  This package is the reproduction's
equivalent, built from four parts:

- :mod:`snapshot` — periodic fsync'd, atomically-renamed snapshots of
  scheduler + meta + accounting state, with WAL segment rotation so
  recovery replays snapshot + tail instead of the full log;
- :mod:`follower` — a standby ctld that pulls a snapshot and streams
  WAL records over the existing gRPC plane into a shadow scheduler
  (no cycles, no dispatch);
- :mod:`lease` — an OS-level file lock on the WAL directory as the
  leader lease, plus a monotonically increasing fencing epoch stamped
  into every craned dispatch/registration so a deposed leader's
  in-flight RPCs are rejected after failover;
- promotion (in :mod:`follower`) — on leader death the standby takes
  the lock, bumps the epoch, rebuilds device-resident scheduler state
  (mask-table class rows, run ledger, timed buckets), re-adopts running
  jobs via craned re-registration, and starts the cycle loop.
"""

from cranesched_tpu.obs.metrics import REGISTRY

# 1 = leader, 0 = standby (labelless; one ctld process = one role)
ROLE_GAUGE = REGISTRY.gauge(
    "crane_ha_role", "HA role of this ctld (1=leader, 0=standby)")
LAG_GAUGE = REGISTRY.gauge(
    "crane_ha_replication_lag_records",
    "standby only: WAL records the shadow state trails the leader by")
FAILOVERS = REGISTRY.counter(
    "crane_ha_failovers_total", "standby->leader promotions")
SNAPSHOTS = REGISTRY.counter(
    "crane_ha_snapshots_total", "durable snapshots written")
WAL_SEQ_GAUGE = REGISTRY.gauge(
    "crane_ha_wal_seq", "last durable WAL sequence number")

from cranesched_tpu.ha.lease import FencingEpoch, LeaderLease  # noqa: E402
from cranesched_tpu.ha.snapshot import (  # noqa: E402
    SnapshotStore,
    Snapshotter,
    capture_snapshot,
    restore_snapshot,
)
from cranesched_tpu.ha.follower import HaFollower  # noqa: E402

__all__ = [
    "ROLE_GAUGE", "LAG_GAUGE", "FAILOVERS", "SNAPSHOTS", "WAL_SEQ_GAUGE",
    "FencingEpoch", "LeaderLease", "SnapshotStore", "Snapshotter",
    "capture_snapshot", "restore_snapshot", "HaFollower",
]
