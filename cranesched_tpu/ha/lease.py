"""Leader lease + fencing epoch.

The lease is an flock on ``<wal>.lock`` — it dies with its holder, so a
SIGKILL'd leader frees it immediately and the standby's next acquisition
attempt succeeds (no TTL to wait out).  The fencing epoch is a counter
persisted beside the WAL (``<wal>.epoch``, atomic-rename updates): every
leadership term bumps it BEFORE the first dispatch, craneds latch the
highest epoch they have seen (register reply or any push), and reject
pushes below it — which is what actually stops a deposed-but-alive
leader whose kill/free RPCs are still in flight.  Epoch 0 means "no HA
configured" and disables the check.
"""

from __future__ import annotations

import os

from cranesched_tpu.utils.filelock import FileLock, FileLockHeld

__all__ = ["FencingEpoch", "LeaderLease", "FileLockHeld"]


class FencingEpoch:
    """Monotonic leadership-term counter persisted next to the WAL."""

    def __init__(self, wal_path: str):
        self.path = wal_path + ".epoch"

    def load(self) -> int:
        try:
            with open(self.path, encoding="utf-8") as fh:
                return int(fh.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def observe(self, epoch: int) -> None:
        """Raise the persisted counter to at least ``epoch``.  A standby
        records the leader's term from every replication reply, so that
        when the ctlds do NOT share a filesystem (separate WAL dirs, so
        separate epoch files) a promotion still bumps strictly past the
        dead leader's term and the fence holds."""
        if epoch > self.load():
            self._write(epoch)

    def bump(self) -> int:
        """Durably advance to the next term and return it (>= 1)."""
        epoch = self.load() + 1
        self._write(epoch)
        return epoch

    def _write(self, epoch: int) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(f"{epoch}\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        d = os.path.dirname(self.path) or "."
        try:
            fd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass


class LeaderLease:
    """The WAL-directory lock + epoch pair a ctld must hold to lead."""

    def __init__(self, wal_path: str):
        self.wal_path = wal_path
        self.lock = FileLock(wal_path + ".lock")
        self.epoch_store = FencingEpoch(wal_path)
        self.epoch = 0

    @property
    def held(self) -> bool:
        return self.lock.held

    def acquire(self, timeout: float | None = None) -> int:
        """Take the lease and start a new term.  Raises
        :class:`FileLockHeld` when another ctld holds it."""
        self.lock.acquire(timeout=timeout)
        try:
            self.epoch = self.epoch_store.bump()
        except BaseException:
            self.lock.release()
            raise
        return self.epoch

    def release(self) -> None:
        self.lock.release()
