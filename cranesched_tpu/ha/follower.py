"""Hot-standby replication + promotion.

The follower is a full ctld process whose server answers queries but
refuses mutations (the leader-only guard in rpc/server.py).  A
background thread here:

1. pulls a full snapshot from the leader (``HaFetchSnapshot``), persists
   it locally, and seeds the shadow state;
2. polls ``HaFetchWal`` with its applied-seq cursor, appends each raw
   record to its OWN local WAL (durability: a standby restart while the
   leader is dead can still promote), and applies it to the shadow
   ``JobScheduler`` — job dicts only, no resources, no cycles, no
   dispatch — so cqueue against the standby shows live state;
3. counts consecutive poll failures; past the miss threshold it tries
   the leader lease.  The lease is an flock that dies with its holder,
   so a SIGKILL'd leader frees it immediately while a live-but-slow
   leader still holds it (the acquisition fails and the standby keeps
   following — no split brain).

Promotion: take the lease (which bumps the fencing epoch), clear the
shadow dicts, run ``scheduler.recover`` over snapshot+replicated state
(re-mallocs resources, rebuilds the run ledger, re-creates implicit
steps, re-sends lost kills), rebuild the device-resident caches
(``rebuild_device_state``), open the local WAL for writing, and flip the
server to leader — its cycle-loop gate opens and craneds re-register
(their ctld address list includes us), learning the new epoch that
fences the deposed leader's in-flight pushes.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

import grpc

from cranesched_tpu.ctld.defs import JobStatus
from cranesched_tpu.ctld.wal import WriteAheadLog, _job_from_dict
from cranesched_tpu.ha.lease import LeaderLease
from cranesched_tpu.ha.snapshot import (
    SnapshotStore,
    restore_snapshot,
    snapshot_to_replay,
)
from cranesched_tpu.utils.filelock import FileLockHeld

log = logging.getLogger("ctld.ha")


class HaFollower(threading.Thread):
    """Replication puller + failover trigger for a standby ctld."""

    def __init__(self, server, leader_address: str, wal_path: str,
                 poll_interval: float = 1.0, miss_threshold: int = 3,
                 fetch_limit: int = 512, token: str = "", tls=None,
                 on_promote=None):
        super().__init__(daemon=True, name="ha-follower")
        self.server = server
        self.scheduler = server.scheduler
        self.leader_address = leader_address
        self.wal_path = wal_path
        self.poll_interval = poll_interval
        self.miss_threshold = miss_threshold
        self.fetch_limit = fetch_limit
        self.token = token
        self.tls = tls
        self.on_promote = on_promote
        self.lease = LeaderLease(wal_path)
        self.store = SnapshotStore(wal_path)

        self.applied_seq = 0
        self.leader_seq = 0
        # monotonic timestamp of the last poll that left us caught up
        # with the leader's durable tail; feeds the bounded-staleness
        # read contract (fed/query.py, rpc/server.py max_staleness)
        self._caught_up_at = 0.0
        self.promoted = threading.Event()
        self._stop = threading.Event()
        self._misses = 0
        self._client = None
        # replay-shaped shadow state: job_id -> (ev, Job); the same Job
        # objects are mirrored into the scheduler dicts for queries
        self._state: dict = {}
        # the leader snapshot's "fed" document — migration state that
        # promotion must fold into the replay (prune_segments already
        # dropped the covered fed_migrate_* records on the leader)
        self._snap_fed: dict | None = None
        self._have_snapshot = False
        self._seed_from_disk()

    # -- local durability --

    def _seed_from_disk(self) -> None:
        """A restarting standby resumes from its local snapshot + WAL
        tail instead of an empty cursor — and can promote even if the
        leader never comes back."""
        doc = self.store.load()
        if doc is not None:
            self._state = snapshot_to_replay(doc)
            self._snap_fed = doc.get("fed")
            self.applied_seq = int(doc.get("seq", 0))
            self._have_snapshot = True
        tail = WriteAheadLog.replay(self.wal_path,
                                    after_seq=self.applied_seq)
        if tail:
            self._state.update(tail)
            self._have_snapshot = True
        self.applied_seq = max(
            self.applied_seq,
            WriteAheadLog._scan_max_seq(self.wal_path))
        if doc is not None:
            with self.server._lock:
                restore_snapshot(self.scheduler, doc)
        self._mirror_all()

    # -- shadow apply --

    def _mirror_job(self, job) -> None:
        s = self.scheduler
        for col in (s.pending, s.running, s.history):
            col.pop(job.job_id, None)
        if job.status.is_terminal:
            s.history[job.job_id] = job
        elif job.status in (JobStatus.RUNNING, JobStatus.SUSPENDED):
            s.running[job.job_id] = job
        else:
            s.pending[job.job_id] = job
        s._next_job_id = max(s._next_job_id, job.job_id + 1)

    def _mirror_all(self) -> None:
        with self.server._lock:
            self.scheduler.pending.clear()
            self.scheduler.running.clear()
            self.scheduler.history.clear()
            for _ev, job in self._state.values():
                self._mirror_job(job)

    def _apply_records(self, records) -> int:
        """Append raw lines to the local WAL and apply to the shadow.
        Records are already durable on the leader; the local append is
        batched-fsync'd (one fsync per fetch, not per record)."""
        if not records:
            return 0
        with open(self.wal_path, "a", encoding="utf-8") as fh:
            for rec in records:
                fh.write(rec.payload + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        with self.server._lock:
            for rec in records:
                parsed = json.loads(rec.payload)
                self.applied_seq = max(self.applied_seq,
                                       int(rec.seq))
                if "job" not in parsed:
                    # federation lease record (fed_reserve/confirm/
                    # release): durable in the local WAL above for a
                    # post-promotion replay_fed, but not shadow state
                    continue
                job = _job_from_dict(parsed["job"])
                self._state[job.job_id] = (parsed["ev"], job)
                self._mirror_job(job)
        return len(records)

    def staleness(self) -> float:
        """Upper bound, in seconds, on how stale this follower's view
        is: time since the last replication poll that left us caught up
        with the leader's durable tail.  ``inf`` before the first full
        sync — a follower that has never caught up must refuse any
        bounded-staleness read."""
        at = self._caught_up_at
        if at <= 0.0:
            return float("inf")
        return max(0.0, time.monotonic() - at)

    # -- leader polling --

    def _dial(self):
        if self._client is None:
            from cranesched_tpu.rpc.client import CtldClient
            self._client = CtldClient(self.leader_address, timeout=5.0,
                                      token=self.token, tls=self.tls)
        return self._client

    def _pull_snapshot(self) -> None:
        rep = self._dial().ha_fetch_snapshot()
        if not rep.ok:
            raise RuntimeError(rep.error or "snapshot refused")
        doc = json.loads(rep.payload)
        # record the leader's term durably: a later promotion must bump
        # strictly past it even when the epoch files aren't shared
        self.lease.epoch_store.observe(rep.fencing_epoch)
        self.store.save(doc)
        # the local WAL restarts at the snapshot: records <= seq are
        # absorbed, the tail re-accumulates from the fetch loop
        with open(self.wal_path, "w", encoding="utf-8") as fh:
            fh.flush()
            os.fsync(fh.fileno())
        with self.server._lock:
            restore_snapshot(self.scheduler, doc)
        self._state = snapshot_to_replay(doc)
        self._snap_fed = doc.get("fed")
        self.applied_seq = int(doc.get("seq", 0))
        self._have_snapshot = True
        self._mirror_all()
        log.info("pulled snapshot @seq=%d (%d jobs)",
                 self.applied_seq, len(self._state))

    def poll_once(self) -> bool:
        """One replication round-trip.  Returns True on success."""
        from cranesched_tpu import ha as _ha
        try:
            if not self._have_snapshot:
                self._pull_snapshot()
            events = self.scheduler.events
            rep = self._dial().ha_fetch_wal(
                self.applied_seq, limit=self.fetch_limit,
                after_event_seq=events.remote_seq)
            if rep.resync:
                log.warning("cursor %d fell off the leader's tail; "
                            "resyncing from snapshot", self.applied_seq)
                self._pull_snapshot()
                return True
            if not rep.ok:
                raise RuntimeError(rep.error or "fetch refused")
            self._apply_records(rep.records)
            # event-ring piggyback: advisory, best-effort, never blocks
            # WAL replication
            for ev in rep.events:
                events.ingest({"seq": ev.seq, "time": ev.time,
                               "type": ev.type, "severity": ev.severity,
                               "node": ev.node, "job_id": ev.job_id,
                               "detail": ev.detail})
            if rep.event_seq > events.remote_seq:
                events.remote_seq = int(rep.event_seq)
            self.lease.epoch_store.observe(rep.fencing_epoch)
            self.leader_seq = int(rep.wal_seq)
            _ha.LAG_GAUGE.set(max(0, self.leader_seq - self.applied_seq))
            self._misses = 0
            if self.applied_seq >= self.leader_seq:
                self._caught_up_at = time.monotonic()
            return True
        except grpc.RpcError as e:
            # only an UNREACHABLE leader is evidence for failover
            self._misses += 1
            log.warning("replication poll failed (%d/%d): %s",
                        self._misses, self.miss_threshold, e)
            # the channel may be wedged on a dead endpoint — redial
            if self._client is not None:
                try:
                    self._client.close()
                except Exception:
                    pass
                self._client = None
            return False
        except (RuntimeError, OSError) as e:
            # a refused fetch or a LOCAL apply/persist error means the
            # leader may well be alive — retry, never promote on it
            log.error("replication apply failed (leader still "
                      "considered alive): %s", e)
            return False

    # -- failover --

    def try_promote(self) -> bool:
        """Attempt to take the lease; promote on success.  A held lease
        means the leader is still alive — keep following."""
        try:
            epoch = self.lease.acquire()
        except FileLockHeld:
            log.info("lease still held; leader alive, not promoting")
            self._misses = 0
            return False
        self.promote(epoch)
        return True

    def promote(self, epoch: int) -> None:
        from cranesched_tpu import ha as _ha
        now = time.time()
        s = self.scheduler
        with self.server._lock:
            # shadow dicts hold bare replicated Jobs; recover() re-adopts
            # them properly (resources, ledger, steps, accounting usage)
            s.pending.clear()
            s.running.clear()
            s.history.clear()
            # nodes that host replicated RUNNING work were alive at the
            # leader's death: mark them so recover() can re-malloc; the
            # real craneds re-register within a ping interval and the
            # ping timeout reaps any that actually died with the leader
            for _ev, job in self._state.values():
                if job.status in (JobStatus.RUNNING,
                                  JobStatus.SUSPENDED):
                    for nid in job.node_ids:
                        node = s.meta.nodes.get(nid)
                        if node is not None and not node.alive:
                            node.alive = True
                            node.last_ping = now
            # migration history first: drop committed handoffs' jobs,
            # rebuild imported node meta, re-seal in-flight partitions
            fed = getattr(s, "fed", None)
            if fed is not None:
                fed.prepare_recovery(self.wal_path, self._state,
                                     snap_fed=self._snap_fed)
            s.recover(self._state, now=now)
            s.rebuild_device_state()
            s.fencing_epoch = epoch
            s.wal = WriteAheadLog(self.wal_path)
            if fed is not None:
                fed.recover(now)
                unresolved = fed.recover_migrations(now)
                if unresolved:
                    log.warning(
                        "%d unresolved migration(s) after promotion "
                        "[%s] — partitions stay sealed until the "
                        "destination's has_import answer settles them",
                        len(unresolved),
                        ", ".join(r["mid"] for r in unresolved))
        self.server.promote_to_leader(epoch)
        self.promoted.set()
        _ha.FAILOVERS.inc()
        _ha.ROLE_GAUGE.set(1)
        _ha.LAG_GAUGE.set(0)
        log.warning("PROMOTED to leader (epoch %d, %d jobs, wal seq %d)",
                    epoch, len(self._state), s.wal.seq)
        if self.on_promote is not None:
            self.on_promote(epoch)

    # -- thread loop --

    def run(self) -> None:
        from cranesched_tpu import ha as _ha
        _ha.ROLE_GAUGE.set(0)
        while not self._stop.wait(self.poll_interval):
            if self.promoted.is_set():
                return
            ok = self.poll_once()
            if not ok and self._misses >= self.miss_threshold:
                if self.try_promote():
                    return

    def stop(self) -> None:
        self._stop.set()
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
