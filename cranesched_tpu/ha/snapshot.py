"""Snapshots: periodic, fsync'd, atomically-renamed captures of
scheduler + meta + accounting state, consistent with a WAL sequence
number.

Recovery (leader boot or standby promotion) loads the snapshot and
replays only the WAL tail (records with seq > snapshot seq) instead of
the full history; after a durable snapshot the leader rotates the active
WAL file into a sealed segment and prunes segments the snapshot covers,
so the log stops growing without ever losing a committed record.

Accounting note: the account/user/QoS *hierarchy* lives in the sqlite
acct store (its own file, shared by both ctlds); the per-user usage
counters are re-derived from the job records themselves during
``JobScheduler.recover`` (restore_submit/restore_run), so the snapshot
carries job + node state and the accounting state follows from it.
"""

from __future__ import annotations

import json
import os
import threading

from cranesched_tpu.ctld.defs import JobStatus
from cranesched_tpu.ctld.wal import _job_from_dict, _job_to_dict

SNAPSHOT_VERSION = 1

# in-RAM history is unbounded; the archive (sqlite) is the authoritative
# terminal-job store, so the snapshot carries only the most recent slice
# for post-failover cacct/cqueue continuity
MAX_HISTORY_JOBS = 2000


def capture_snapshot(scheduler, seq: int | None = None) -> dict:
    """Build the snapshot document.  Caller must hold the server lock —
    the document must be consistent with one WAL position."""
    if seq is None:
        seq = (scheduler.wal.durable_seq
               if scheduler.wal is not None else 0)
    jobs = []
    for col in (scheduler.pending, scheduler.running):
        for job in col.values():
            jobs.append(_job_to_dict(job))
    hist = sorted(scheduler.history.values(),
                  key=lambda j: (j.end_time or 0.0, j.job_id))
    for job in hist[-MAX_HISTORY_JOBS:]:
        jobs.append(_job_to_dict(job))
    nodes = {}
    for node in scheduler.meta.nodes.values():
        nodes[node.name] = {
            "alive": node.alive,
            "drained": node.drained,
            "health_drained": node.health_drained,
            "power_state": node.power_state,
            "address": node.address,
        }
    doc = {
        "version": SNAPSHOT_VERSION,
        "seq": seq,
        "next_job_id": scheduler._next_job_id,
        "jobs": jobs,
        "nodes": nodes,
    }
    # prune_segments deletes fed_migrate_* records along with the
    # covered segments — the snapshot must carry the migration state
    # (imported node meta, replay filter, in-flight begins) itself
    fed = getattr(scheduler, "fed", None)
    if fed is not None:
        doc["fed"] = fed.snapshot_doc()
    return doc


def snapshot_to_replay(doc: dict) -> dict:
    """The snapshot's jobs in ``WriteAheadLog.replay`` shape, ready to
    merge with the WAL tail and feed to ``scheduler.recover``."""
    return {d["job_id"]: ("snap", _job_from_dict(d))
            for d in doc.get("jobs", ())}


def restore_snapshot(scheduler, doc: dict) -> dict:
    """Apply the snapshot's meta/node flags and id counter; returns the
    replay-shaped job dict (caller overlays the WAL tail, then calls
    ``scheduler.recover``)."""
    scheduler._next_job_id = max(scheduler._next_job_id,
                                 int(doc.get("next_job_id", 1)))
    for name, st in (doc.get("nodes") or {}).items():
        node_id = scheduler.meta._name_to_id.get(name)
        if node_id is None:
            continue  # node removed from config since the snapshot
        node = scheduler.meta.nodes[node_id]
        node.drained = bool(st.get("drained", False))
        node.health_drained = bool(st.get("health_drained", False))
        node.power_state = st.get("power_state", "ACTIVE")
        if st.get("address"):
            node.address = st["address"]
    return snapshot_to_replay(doc)


class SnapshotStore:
    """Durable snapshot file beside the WAL (``<wal>.snap``): written to
    a temp file, fsync'd, atomically renamed, directory fsync'd — a
    crash mid-save leaves the previous snapshot intact."""

    def __init__(self, wal_path: str):
        self.path = wal_path + ".snap"

    def save(self, doc: dict) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, separators=(",", ":"))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        d = os.path.dirname(self.path) or "."
        try:
            fd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def load(self) -> dict | None:
        try:
            with open(self.path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None
        if doc.get("version") != SNAPSHOT_VERSION:
            return None
        return doc


def recover_from_snapshot(scheduler, wal_cls, wal_path: str,
                          now: float) -> tuple[int, int]:
    """Boot-time recovery: snapshot + WAL tail when a snapshot exists,
    full replay otherwise.  Returns (#jobs recovered, snapshot seq)."""
    store = SnapshotStore(wal_path)
    doc = store.load()
    snap_seq = 0
    if doc is not None:
        snap_seq = int(doc.get("seq", 0))
        replayed = restore_snapshot(scheduler, doc)
        replayed.update(wal_cls.replay(wal_path, after_seq=snap_seq))
    else:
        replayed = wal_cls.replay(wal_path)
    # migration history rewrites the replay BEFORE recover: committed
    # handoffs' jobs drop out (they live on the dest), imported
    # partitions' node meta rebuilds in adoption order, in-flight
    # begins re-seal.  Requires the plane attached pre-recovery.
    fed = getattr(scheduler, "fed", None)
    if fed is not None:
        fed.prepare_recovery(wal_path, replayed,
                             snap_fed=(doc or {}).get("fed"))
    if replayed:
        scheduler.recover(replayed, now=now)
    return len(replayed), snap_seq


class Snapshotter(threading.Thread):
    """Leader-side periodic snapshot loop: capture under the server
    lock, rotate the WAL, persist durably, then prune covered segments.

    A crash between rotate and save only leaves extra sealed segments —
    replay still covers every record; pruning happens strictly after the
    snapshot hit disk."""

    def __init__(self, scheduler, wal, lock, wal_path: str,
                 interval: float = 60.0, min_records: int = 1):
        super().__init__(daemon=True, name="ha-snapshotter")
        self.scheduler = scheduler
        self.wal = wal
        self.lock = lock
        self.store = SnapshotStore(wal_path)
        self.interval = interval
        self.min_records = min_records
        self.snapshots_taken = 0
        self.last_seq = 0
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.snap_once()
            except Exception:  # never kill the loop; next tick retries
                pass

    def snap_once(self) -> int:
        """One capture+rotate+persist+prune pass.  Returns the snapshot
        seq (0 = skipped, nothing new)."""
        from cranesched_tpu import ha as _ha
        with self.lock:
            seq = self.wal.durable_seq
            if seq - self.last_seq < self.min_records:
                return 0
            doc = capture_snapshot(self.scheduler, seq)
            self.wal.rotate()
        self.store.save(doc)
        self.wal.prune_segments(seq)
        self.last_seq = seq
        self.snapshots_taken += 1
        _ha.SNAPSHOTS.inc()
        _ha.WAL_SEQ_GAUGE.set(seq)
        return seq

    def stop(self) -> None:
        self._stop.set()
