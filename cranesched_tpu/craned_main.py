"""craned: the node daemon entry point (reference src/Craned/Core/
Craned.cpp bootstrap).

    python -m cranesched_tpu.craned_main --name cn01 \\
        --ctld 127.0.0.1:50051 --cpu 16 --memory 64G
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="craned")
    ap.add_argument("--name", required=True)
    ap.add_argument("--ctld", required=True,
                    help="ctld address, or a comma-separated list for "
                         "an HA pair (rotates to the leader)")
    ap.add_argument("--cpu", type=float, default=8.0)
    ap.add_argument("--memory", default="16G")
    ap.add_argument("--partitions", default="default")
    ap.add_argument("--workdir", default="/tmp")
    ap.add_argument("--listen", default="127.0.0.1:0")
    ap.add_argument("--ping-interval", type=float, default=5.0)
    ap.add_argument("--cgroup-root", default="/sys/fs/cgroup")
    ap.add_argument("--health-program", default="")
    ap.add_argument("--health-interval", type=float, default=30.0)
    ap.add_argument("--gres", default="",
                    help="name[:type]:count, comma-separated")
    ap.add_argument("--gres-devices", default="",
                    help="device files backing GRES slots for the "
                         "kernel cgroup ACL: name[:type]=/dev/a;/dev/b"
                         " entries, comma-separated (reference "
                         "config.yaml Gres device files)")
    ap.add_argument("--token", default="",
                    help="cluster secret for auth-enabled ctlds "
                         "(the @craned entry in the token table)")
    ap.add_argument("--token-file", default="",
                    help="read the cluster secret's token from a file")
    ap.add_argument("--prolog", default="",
                    help="task prolog script (bash -c) run before "
                         "every step; failure fails the step and "
                         "drains this node")
    ap.add_argument("--epilog", default="",
                    help="task epilog script run after every step; "
                         "failure drains this node")
    ap.add_argument("--tls-ca", default="",
                    help="cluster CA cert: dial the ctld over TLS "
                         "(requires --tls-cert/--tls-key)")
    ap.add_argument("--tls-cert", default="",
                    help="this node's cert (serves the push surface "
                         "over TLS; presented to mTLS ctlds)")
    ap.add_argument("--tls-key", default="",
                    help="this node's key")
    ap.add_argument("--container-runtime", default=None,
                    help="OCI runtime CLI for container steps "
                         "(default: auto-detect podman/docker; "
                         "'' disables)")
    ap.add_argument("--tls-name",
                    default=os.environ.get("CRANE_TLS_NAME", "ctld"),
                    help="name the ctld's cert is issued under "
                         "(identity pin for the dial; default ctld)")
    ap.add_argument("--log-file", default="",
                    help="rotating log file (32 MiB x 5 by default)")
    ap.add_argument("--log-level", default="info")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="Prometheus /metrics port (0 = ephemeral; "
                         "unset = endpoint off)")
    args = ap.parse_args(argv)
    from cranesched_tpu.utils.logging import setup_logging
    setup_logging("craned", args.log_file, args.log_level)
    if args.tls_ca and not (args.tls_cert and args.tls_key):
        ap.error("--tls-ca requires --tls-cert and --tls-key "
                 "(a CA-only craned would serve a plaintext push "
                 "surface no TLS ctld can dispatch to)")

    token = args.token
    if not token and args.token_file:
        with open(args.token_file, encoding="utf-8") as fh:
            token = fh.read().strip()

    from cranesched_tpu.craned.daemon import CranedDaemon
    from cranesched_tpu.utils.config import parse_mem
    from cranesched_tpu.utils.pki import TlsConfig

    gres = {}
    if args.gres:
        from cranesched_tpu.cli import _parse_gres
        gres = _parse_gres(args.gres)  # daemon normalizes string keys
    gres_devices = {}
    for entry in filter(None, args.gres_devices.split(",")):
        key, _, paths = entry.partition("=")
        gres_devices[key.strip()] = [p for p in paths.split(";") if p]

    daemon = CranedDaemon(
        args.name, args.ctld, cpu=args.cpu,
        mem_bytes=parse_mem(args.memory),
        partitions=tuple(args.partitions.split(",")),
        workdir=args.workdir, ping_interval=args.ping_interval,
        cgroup_root=args.cgroup_root,
        health_program=args.health_program,
        health_interval=args.health_interval,
        gres=gres, gres_devices=gres_devices, token=token,
        prolog=args.prolog, epilog=args.epilog,
        tls=(TlsConfig(ca=args.tls_ca, cert=args.tls_cert,
                       key=args.tls_key)
             if args.tls_ca else None),
        tls_name=args.tls_name,
        container_runtime=args.container_runtime,
        pam_alias=True,
        metrics_port=args.metrics_port)
    port = daemon.start(args.listen)
    print(f"craned {args.name} serving on port {port}, "
          f"registering with {args.ctld}", flush=True)
    if daemon.metrics_port is not None:
        print(f"metrics: http://0.0.0.0:{daemon.metrics_port}/metrics",
              flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    daemon.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
