from cranesched_tpu.ops.resources import (
    CPU_SCALE,
    MEM_UNIT_BYTES,
    DIM_CPU,
    DIM_MEM,
    DIM_MEMSW,
    NUM_BASE_DIMS,
    ResourceLayout,
    fits,
    fit_count,
)

__all__ = [
    "CPU_SCALE",
    "MEM_UNIT_BYTES",
    "DIM_CPU",
    "DIM_MEM",
    "DIM_MEMSW",
    "NUM_BASE_DIMS",
    "ResourceLayout",
    "fits",
    "fit_count",
]
