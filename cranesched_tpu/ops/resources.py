"""Resource algebra as dense integer tensors.

The reference models resources as an object graph (reference:
src/Utilities/PublicHeader/include/crane/PublicHeader.h:555-778 —
``CpuSet``/``ResourceInNodeV3``/``ResourceView`` with fixed-point
``cpu_t = fpm::fixed<int64,int128,8>``).  On TPU the same algebra is a flat
int32 vector per (node|job) with one dimension per resource kind, so that

* feasibility       = elementwise ``req <= avail`` reduced over the last axis
  (reference ``operator<=``, PublicHeader.h:760-765),
* allocation/free   = vector add/sub,
* max-fit count     = ``min_over_dims(avail // req)`` (reference ``operator/``
  semantics: "minimum quotient across all resource dimensions",
  PublicHeader.h:769-772),

all of which vectorize over (jobs x nodes) without data-dependent shapes.

Encoding
--------
dim 0: cpu, fixed point with 8 fractional bits (CPU_SCALE = 256 units per
       core) — matches the reference's fpm scale so host ledgers and device
       tensors agree bit-for-bit on fractional cpus.
dim 1: memory, MiB.
dim 2: memory+swap, MiB.
dim 3+: one dimension per configured GRES (name, type) pair, unit = slots.

int32 bounds: 2**31/256 = 8.3M cores, 2**31 MiB = 2 PiB memory per node —
far beyond any single node, and per-cluster totals are never stored as a
single vector on device.

Slot identity (which core ids / which device slots — reference
``CpuSet.core_ids`` and ``DedicatedResourceInNode.name_type_slots_map``) is
deliberately NOT on device: the solve only needs quantities
(reference ``ResourceView``, "Flat structure for scheduling phase"); concrete
slot ids are chosen host-side at dispatch time (see ctld/dispatch), mirroring
how the reference picks slots in ``GetFeasibleResourceInNode``
(PublicHeader.cpp:519-600) only after scheduling decided quantities.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

import jax.numpy as jnp
import numpy as np

# Fixed-point scale for cpu counts: 8 fractional bits, matching the
# reference's cpu_t (PublicHeader.h:44).
CPU_SCALE = 256
# Memory unit for device tensors.
MEM_UNIT_BYTES = 1 << 20  # 1 MiB

def gres_key_str(pair) -> str:
    """Canonical wire form of a GRES (name, type) pair: "name:type"."""
    name, typ = pair
    return f"{name}:{typ}"


def gres_key_pair(key: str) -> tuple:
    """Inverse of gres_key_str."""
    name, _, typ = key.partition(":")
    return (name, typ)


DIM_CPU = 0
DIM_MEM = 1
DIM_MEMSW = 2
NUM_BASE_DIMS = 3

# A value safely above any real per-dimension quantity, used for "infinite"
# availability in masked comparisons. Kept well under int32 max so sums of a
# few of these cannot overflow.
BIG = np.int32(2**30)


@dataclasses.dataclass(frozen=True)
class ResourceLayout:
    """Static (compile-time) mapping of resource dimensions.

    ``gres_pairs`` is the ordered tuple of GRES ``(name, type)`` pairs — e.g.
    ``("gpu", "a100")`` — whose tensor dimension index is
    ``NUM_BASE_DIMS + position``. Stored as a tuple so the layout is hashable
    and usable as a jit static argument; ``gres_dims`` exposes the dict view
    for lookups. Changing the GRES inventory recompiles, which matches how the
    reference treats device config as cluster topology
    (etc/config.yaml:139-160).
    """

    gres_pairs: tuple[tuple[str, str], ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "gres_pairs", tuple(self.gres_pairs))

    @property
    def gres_dims(self) -> dict[tuple[str, str], int]:
        return {p: NUM_BASE_DIMS + i for i, p in enumerate(self.gres_pairs)}

    @property
    def num_dims(self) -> int:
        return NUM_BASE_DIMS + len(self.gres_pairs)

    @staticmethod
    def from_gres_names(pairs: Sequence[tuple[str, str]]) -> "ResourceLayout":
        return ResourceLayout(tuple(pairs))

    # ---- host-side encoding helpers (NumPy, used by ctld and tests) ----

    def encode(
        self,
        cpu: float = 0.0,
        mem_bytes: int = 0,
        memsw_bytes: int = 0,
        gres: Mapping[tuple[str, str], int] | None = None,
        is_capacity: bool = False,
    ) -> np.ndarray:
        """Encode one resource quantity as an int32 vector.

        cpu is rounded to the nearest 1/256 core (the reference constructs
        cpu_t from doubles the same way).  Memory rounding is direction-aware
        so quantization never admits a job raw bytes would refuse: requests
        round UP to MiB (a request never silently shrinks), while capacities
        (``is_capacity=True`` — node totals/availability) round DOWN (a node
        never advertises more than it has).
        """
        v = np.zeros(self.num_dims, dtype=np.int32)
        v[DIM_CPU] = int(round(cpu * CPU_SCALE))
        if is_capacity:
            v[DIM_MEM] = int(mem_bytes) // MEM_UNIT_BYTES
            v[DIM_MEMSW] = int(memsw_bytes) // MEM_UNIT_BYTES
        else:
            v[DIM_MEM] = -(-int(mem_bytes) // MEM_UNIT_BYTES)
            v[DIM_MEMSW] = -(-int(memsw_bytes) // MEM_UNIT_BYTES)
        gres_dims = self.gres_dims
        for key, count in (gres or {}).items():
            v[gres_dims[key]] = int(count)
        return v

    def decode_cpu(self, v: np.ndarray) -> float:
        return float(v[DIM_CPU]) / CPU_SCALE

    def decode_mem_bytes(self, v: np.ndarray) -> int:
        return int(v[DIM_MEM]) * MEM_UNIT_BYTES


def fits(req, avail):
    """``req <= avail`` over the resource axis.

    req:   [..., R]
    avail: [..., R] (broadcastable)
    -> bool[...]

    Mirrors reference ``operator<=(ResourceView, ResourceInNodeV3)``
    (PublicHeader.cpp): every dimension must fit.
    """
    return jnp.all(req <= avail, axis=-1)


def fit_count(avail, req):
    """How many tasks of ``req`` fit into ``avail`` (elementwise min quotient).

    avail: [..., R], req: [..., R] -> int32[...]

    Mirrors reference ``operator/(ResourceView, ResourceView)``
    (PublicHeader.h:769-772): minimum of avail_d / req_d over dimensions with
    req_d > 0; dimensions the job doesn't request don't constrain it.
    """
    avail = jnp.asarray(avail)
    req = jnp.asarray(req)
    q = jnp.where(req > 0, avail // jnp.maximum(req, 1), BIG)
    return jnp.min(q, axis=-1)
