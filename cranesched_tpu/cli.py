"""Command-line tools: the user surface of the framework.

Mirrors the reference's Go CLI command set (reference docs/en/command/:
cbatch, cqueue, cinfo, ccancel, ccontrol, cacct — SURVEY.md §2.7) as
subcommands of one entry point:

    python -m cranesched_tpu.cli cbatch --cpu 4 --mem 8G --time 3600
    python -m cranesched_tpu.cli cqueue
    python -m cranesched_tpu.cli cinfo
    python -m cranesched_tpu.cli ccancel 42
    python -m cranesched_tpu.cli ccontrol hold 42
    python -m cranesched_tpu.cli cacct

The server address comes from --server or $CRANE_SERVER
(default 127.0.0.1:50051).
"""

from __future__ import annotations

import argparse
import os
import sys


def _parse_mem(text: str) -> int:
    units = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}
    text = text.strip().lower().removesuffix("b")
    if text and text[-1] in units:
        return int(float(text[:-1]) * units[text[-1]])
    return int(text)


def _parse_array(text: str):
    """'0-9', '0-9:2' (stride), '%N' run-limit suffix: '0-9%2'."""
    from cranesched_tpu.rpc import crane_pb2 as pb
    limit = 0
    if "%" in text:
        text, lim = text.split("%", 1)
        limit = int(lim)
    stride = 1
    if ":" in text:
        text, st = text.split(":", 1)
        stride = int(st)
    if "-" in text:
        start, end = text.split("-", 1)
    else:
        start = end = text
    return pb.ArraySpec(start=int(start), end=int(end), stride=stride,
                        max_concurrent=limit)


def _parse_dependency(text: str):
    """'afterok:12', 'after:12+30' (delay), comma-separated."""
    from cranesched_tpu.rpc import crane_pb2 as pb
    deps = []
    for part in text.split(","):
        typ, sep, ref = part.partition(":")
        if not sep or not ref:
            raise SystemExit(
                f"crane: invalid dependency {part!r} "
                "(expected TYPE:JOBID[+delay], e.g. afterok:12)")
        delay = 0.0
        if "+" in ref:
            ref, d = ref.split("+", 1)
            delay = float(d)
        try:
            job_id = int(ref)
        except ValueError:
            raise SystemExit(f"crane: invalid dependency job id {ref!r}")
        deps.append(pb.Dependency(job_id=job_id, type=typ,
                                  delay_seconds=delay))
    return deps


def _token(args) -> str:
    """--token > $CRANE_TOKEN > ~/.crane/token (empty = no auth)."""
    if getattr(args, "token", ""):
        return args.token
    env = os.environ.get("CRANE_TOKEN", "")
    if env:
        return env
    path = os.path.expanduser("~/.crane/token")
    try:
        with open(path, encoding="utf-8") as fh:
            return fh.read().strip()
    except OSError:
        return ""


def _tls(args):
    """--ca > $CRANE_CA > ~/.crane/ca.pem (absent = plaintext dial).

    The dial pins the server identity to the NAME the control-plane
    cert was issued under ($CRANE_TLS_NAME, default "ctld") — any
    other cluster-issued cert, loopback SANs and all, is refused.
    ``--cert``/``--key`` (or $CRANE_CERT/$CRANE_KEY, or
    ~/.crane/cert.pem+key.pem) present this user's cert for
    RequireClientCert (mTLS) clusters."""
    ca = getattr(args, "ca", "") or os.environ.get("CRANE_CA", "")
    if not ca:
        default = os.path.expanduser("~/.crane/ca.pem")
        if os.path.exists(default):
            ca = default
    if not ca:
        return None
    cert = (getattr(args, "cert", "")
            or os.environ.get("CRANE_CERT", ""))
    key = getattr(args, "key", "") or os.environ.get("CRANE_KEY", "")
    if bool(cert) != bool(key):
        raise SystemExit("crane: --cert/$CRANE_CERT and "
                         "--key/$CRANE_KEY go together")
    if not cert:
        dcert = os.path.expanduser("~/.crane/cert.pem")
        dkey = os.path.expanduser("~/.crane/key.pem")
        if os.path.exists(dcert) and os.path.exists(dkey):
            cert, key = dcert, dkey
    from cranesched_tpu.utils.pki import TlsConfig
    return TlsConfig(
        ca=ca, cert=cert, key=key,
        override_authority=os.environ.get("CRANE_TLS_NAME", "ctld"))


def _client(args):
    # a comma-separated --server/$CRANE_SERVER is an HA pair: the
    # client follows the leader across failovers
    from cranesched_tpu.rpc.client import make_client
    return make_client(args.server, token=_token(args), tls=_tls(args))


def cmd_ctoken(args) -> int:
    """Admin: issue (or revoke) a user's bearer token (the reference's
    SignUserCertificate / RevokeCert flow, AccountManager.h:171)."""
    client = _client(args)
    if args.revoke:
        reply = client.revoke_token(args.user)
        if reply.ok:
            print(f"tokens of {args.user} revoked")
            return 0
        print(f"ctoken: {reply.error}", file=sys.stderr)
        return 1
    reply = client.issue_token(args.user)
    if not reply.ok:
        print(f"ctoken: {reply.error}", file=sys.stderr)
        return 1
    if args.save:
        # per-user path: saving another user's token must never
        # clobber the CALLER's own ~/.crane/token (the _token fallback
        # would silently re-identify the admin as that user)
        path = os.path.expanduser(f"~/.crane/token.{args.user}")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            fh.write(reply.token)
        print(f"token for {args.user} saved to {path} "
              f"(move to ~/.crane/token on {args.user}'s account)")
    else:
        print(reply.token)
    return 0


def _fmt_table(rows, headers) -> str:
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              if rows else len(str(h)) for i, h in enumerate(headers)]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def _parse_gres(text: str) -> dict:
    """'gpu:a100:2,fpga::1' -> {"gpu:a100": 2, "fpga:": 1}."""
    out = {}
    for part in text.split(","):
        bits = part.split(":")
        if len(bits) == 3:
            name, typ, count = bits
        elif len(bits) == 2:
            name, count = bits
            typ = ""
        else:
            raise SystemExit(f"crane: bad --gres {part!r} "
                             "(use name[:type]:count)")
        try:
            n = int(count)
        except ValueError:
            raise SystemExit(f"crane: bad --gres count {count!r}")
        if n < 1:
            raise SystemExit(f"crane: --gres count must be >= 1, "
                             f"got {n}")
        out[f"{name}:{typ}"] = n
    return out


def cmd_cbatch(args) -> int:
    from cranesched_tpu.rpc import crane_pb2 as pb
    spec = _build_spec(args)
    spec.held = args.hold
    spec.exclusive = args.exclusive
    spec.include_nodes.extend(
        args.nodelist.split(",") if args.nodelist else [])
    spec.exclude_nodes.extend(
        args.exclude.split(",") if args.exclude else [])
    spec.requeue_if_failed = args.requeue
    spec.deps_is_or = args.dependency_any
    spec.sim_runtime = args.sim_runtime or 0.0
    if args.ntasks:
        spec.ntasks = args.ntasks
        spec.ntasks_per_node_min = args.ntasks_per_node_min
        spec.ntasks_per_node_max = (args.ntasks_per_node_max
                                    or args.ntasks)
        spec.task_res.CopyFrom(pb.ResourceSpec(
            cpu=args.cpus_per_task,
            mem_bytes=_parse_mem(args.mem_per_task)))
    if args.array:
        spec.array.CopyFrom(_parse_array(args.array))
    if args.dependency:
        spec.dependencies.extend(_parse_dependency(args.dependency))
    client = _client(args)
    reply = client.submit(spec)
    if reply.job_id:
        print(f"Submitted batch job {reply.job_id}")
        return 0
    print(f"submit failed: {reply.error}", file=sys.stderr)
    return 1


def _build_spec(args):
    """Shared JobSpec construction for cbatch and crun."""
    from cranesched_tpu.rpc import crane_pb2 as pb
    spec = pb.JobSpec(
        name=args.job_name, user=args.user,
        account=args.account, partition=args.partition,
        res=pb.ResourceSpec(cpu=args.cpu, mem_bytes=_parse_mem(args.mem),
                            memsw_bytes=_parse_mem(args.memsw or args.mem)),
        node_num=args.nodes, time_limit=args.time, qos=args.qos,
        reservation=args.reservation,
        script=getattr(args, "script", "") or "",
        output_path=getattr(args, "output", "") or "")
    if args.gres:
        for key, count in _parse_gres(args.gres).items():
            spec.res.gres[key] = count
    if getattr(args, "image", ""):
        spec.container_image = args.image
        spec.container_mounts.extend(getattr(args, "mount", []) or [])
    return spec


def cmd_calloc(args) -> int:
    """Allocate resources WITHOUT running anything (reference calloc):
    the allocation sits until `crun --jobid` steps run in it and `cfree`
    releases it (or the time limit expires)."""
    import time as _time
    spec = _build_spec(args)
    spec.alloc_only = True
    client = _client(args)
    reply = client.submit(spec)
    if not reply.job_id:
        print(f"calloc: submit failed: {reply.error}", file=sys.stderr)
        return 1
    job_id = reply.job_id
    deadline = _time.time() + args.wait
    while _time.time() < deadline:
        jobs = client.query_jobs(job_ids=[job_id]).jobs
        if jobs and jobs[0].status == "Running":
            print(f"Granted allocation {job_id} on "
                  f"{','.join(jobs[0].node_names)}")
            return 0
        if jobs and jobs[0].status not in ("Pending", "Running"):
            print(f"calloc: allocation {job_id} ended "
                  f"({jobs[0].status})", file=sys.stderr)
            return 1
        _time.sleep(args.poll)
    print(f"calloc: allocation {job_id} still pending after "
          f"{args.wait:.0f}s (it stays queued; ccancel {job_id} to "
          "drop it)", file=sys.stderr)
    return 1


def cmd_cfree(args) -> int:
    """Release a calloc allocation."""
    client = _client(args)
    reply = client.free_allocation(args.job_id)
    if reply.ok:
        print(f"Allocation {args.job_id} released")
        return 0
    print(f"cfree: {reply.error}", file=sys.stderr)
    return 1


def cmd_cstep(args) -> int:
    """List a job's steps (reference cqueue --steps)."""
    client = _client(args)
    reply = client.query_steps(args.job_id)
    rows = []
    for s in reply.steps:
        rows.append((f"{s.job_id}.{s.step_id}", s.name[:20], s.status,
                     s.exit_code,
                     ",".join(s.node_names) or "-"))
    print(_fmt_table(rows, ("STEPID", "NAME", "STATE", "EXIT",
                            "NODES")))
    return 0


def _stream_session(sess, cancel, status_poll=None) -> int:
    """Pump a StepIO session to this terminal: output chunks to
    stdout/stderr as they arrive, local stdin forwarded to the step,
    Ctrl-C -> cancel intent -> drain remaining output -> cancelled code.
    Output is structurally drained before the exit status arrives
    (reference CforedClient.h:60-63).

    ``status_poll`` (-> (terminal, exit_code) from the ctld) is the
    liveness fallback: if the job/step dies before any supervisor ever
    connects (dispatch failure, cancel while pending, node death), no
    stream will end the session — the watchdog aborts it with the
    recorded exit code instead of hanging forever."""
    import threading

    def watchdog():
        import time as _time
        grace_until = None
        while not sess.exited.wait(1.0):
            try:
                terminal, code = status_poll()
            except Exception:
                continue
            if not terminal:
                grace_until = None
                continue
            # terminal at ctld: give an in-flight exited chunk a
            # moment to land, then abort the wait
            if grace_until is None:
                grace_until = _time.monotonic() + 3.0
            elif _time.monotonic() > grace_until:
                sess.abort(code if code is not None else 1)
                return

    if status_poll is not None:
        threading.Thread(target=watchdog, daemon=True).start()

    def stdin_pump():
        try:
            while True:
                data = sys.stdin.buffer.readline()
                if not data:
                    sess.close_stdin()
                    return
                sess.send_stdin(data)
        except (OSError, ValueError):
            pass

    threading.Thread(target=stdin_pump, daemon=True).start()

    def drain():
        for name, data in sess.read():
            stream = sys.stdout if name == "out" else sys.stderr
            stream.buffer.write(data)
            stream.flush()

    try:
        drain()
    except KeyboardInterrupt:
        cancel()
        try:
            drain()
        except KeyboardInterrupt:
            pass  # second ^C: stop draining
        print("\ncrun: cancelled", file=sys.stderr)
        return sess.exit_code if sess.exit_code is not None else 130
    return sess.exit_code if sess.exit_code is not None else 1


def _run_step_in_alloc(args, client, cfored) -> int:
    """crun --jobid: an interactive STEP inside a live allocation,
    streaming over the embedded CraneFored service."""
    from cranesched_tpu.rpc import crane_pb2 as pb
    # -N maps 1:1 onto the step's node span (0 = every allocation node);
    # the default -N 1 therefore means exactly one node, matching the
    # standalone crun semantics
    spec = pb.StepSpec(name=args.job_name, script=args.script,
                       node_num=args.nodes,
                       time_limit=args.time,
                       interactive_address=cfored.address,
                       interactive_token=cfored.secret,
                       pty=args.pty,
                       overlap=getattr(args, "overlap", False))
    if getattr(args, "x11", False):
        spec.x11 = True
        spec.x11_cookie = _x11_cookie()
    if getattr(args, "follow_step", None) is not None:
        spec.follow_step = args.follow_step
    if getattr(args, "image", ""):
        spec.container_image = args.image
        spec.container_mounts.extend(getattr(args, "mount", []) or [])
    if args.cpu or args.mem != "0":
        spec.res.CopyFrom(pb.ResourceSpec(
            cpu=args.cpu, mem_bytes=_parse_mem(args.mem)))
    reply = client.submit_step(args.jobid, spec)
    if reply.step_id < 0:
        print(f"crun: step rejected: {reply.error}", file=sys.stderr)
        return 1
    step_id = reply.step_id
    sess = cfored.expect(args.jobid, step_id)

    def status_poll():
        steps = [s for s in client.query_steps(args.jobid).steps
                 if s.step_id == step_id]
        if not steps:
            return True, 1
        s = steps[0]
        return s.status not in ("Pending", "Running"), s.exit_code

    return _stream_session(
        sess, cancel=lambda: client.cancel_step(args.jobid, step_id),
        status_poll=status_poll)


def cmd_ccon(args) -> int:
    """Container jobs (reference ccon, ContainerInstance): ``ccon run
    IMAGE SCRIPT`` submits a batch job whose step runs inside IMAGE on
    the node's OCI runtime, with the job's GRES/env crossing the
    boundary."""
    args.image = args.image_name
    spec = _build_spec(args)
    client = _client(args)
    reply = client.submit(spec)
    if reply.job_id:
        print(f"Submitted container job {reply.job_id} "
              f"({args.image_name})")
        return 0
    print(f"ccon: submit failed: {reply.error}", file=sys.stderr)
    return 1


def cmd_cattach(args) -> int:
    """Attach interactively to a RUNNING container step (reference
    cattach): runs ``$CRANE_CONTAINER_RUNTIME attach <name>`` as a new
    step inside the job's allocation, streaming through the embedded
    CraneFored hub — stdin/stdout reach the primary container."""
    from cranesched_tpu.rpc.cfored import CforedServer
    client = _client(args)
    cfored = CforedServer()
    cfored.start(host_for_clients=args.bind_host)
    try:
        args.jobid = args.job_id
        args.script = (f'exec "$CRANE_CONTAINER_RUNTIME" attach '
                       f'crane-j{args.job_id}-s{args.step}')
        args.job_name = f"cattach-s{args.step}"
        args.nodes = 1
        args.time = 0
        args.cpu = 0.0
        args.mem = "0"
        args.pty = True
        args.overlap = True   # observation channel: holds no share
        args.image = ""       # the attach runs on the HOST runtime
        args.follow_step = args.step  # land on the container's node
        return _run_step_in_alloc(args, client, cfored)
    finally:
        cfored.stop()
        client.close()


def _x11_cookie() -> str:
    """The user's magic cookie for $DISPLAY (best effort — an open X
    server needs none)."""
    import shutil
    import subprocess as _sp
    display = os.environ.get("DISPLAY", "")
    if not display or shutil.which("xauth") is None:
        return ""
    try:
        out = _sp.run(["xauth", "list", display], capture_output=True,
                      text=True, timeout=10)
        line = out.stdout.strip().splitlines()
        return line[0] if line else ""
    except (OSError, _sp.SubprocessError):
        return ""


def cmd_crun(args) -> int:
    """Interactive run with REAL bidi streaming: the client hosts an
    embedded CraneFored service; the supervisor connects back and
    streams stdout/stderr while accepting stdin -- no shared storage
    (reference cfored protocol, Crane.proto:794-900,1679).  With
    ``--jobid`` the command becomes a STEP inside an existing calloc
    allocation (reference crun within calloc)."""
    from cranesched_tpu.rpc.cfored import CforedServer
    client = _client(args)
    hub_tls = None
    if args.io_cert or args.io_key:
        if not (args.io_cert and args.io_key):
            # half a keypair must not silently downgrade to plaintext
            print("crun: --io-cert and --io-key go together",
                  file=sys.stderr)
            return 2
        base = _tls(args)
        if base is None:
            print("crun: --io-cert needs a cluster CA (--ca)",
                  file=sys.stderr)
            return 2
        import dataclasses as _dc
        hub_tls = _dc.replace(base, cert=args.io_cert, key=args.io_key,
                              override_authority="")
    cfored = CforedServer(tls=hub_tls)
    cfored.start(host_for_clients=args.bind_host)
    try:
        if args.jobid:
            return _run_step_in_alloc(args, client, cfored)
        spec = _build_spec(args)
        spec.interactive_address = cfored.address
        spec.interactive_token = cfored.secret
        spec.pty = args.pty
        if args.x11:
            spec.x11 = True
            spec.x11_cookie = _x11_cookie()
        reply = client.submit(spec)
        if not reply.job_id:
            print(f"crun: submit failed: {reply.error}",
                  file=sys.stderr)
            return 1
        job_id = reply.job_id
        sess = cfored.expect(job_id, 0)

        def status_poll():
            jobs = client.query_jobs(job_ids=[job_id],
                                     include_history=True).jobs
            if not jobs:
                return True, 1
            j = jobs[0]
            return (j.status not in ("Pending", "Running", "Suspended"),
                    j.exit_code)

        return _stream_session(sess,
                               cancel=lambda: client.cancel(job_id),
                               status_poll=status_poll)
    finally:
        cfored.stop()


def _fed_flags(p) -> None:
    """Bounded-staleness + fan-out flags shared by every read verb."""
    p.add_argument("--max-staleness", type=float, default=0.0,
                   metavar="SECONDS",
                   help="bounded-staleness read: a follower older than "
                        "this many seconds refuses and the query falls "
                        "through to the leader (0 = any replica)")
    p.add_argument("--federation", action="store_true",
                   help="fan the query out to every shard and label "
                        "rows with their shard of origin")


def _fed_connect(args):
    """Build the scatter-gather client for --federation commands, or
    None (with a diagnostic) when the cluster has no shard map."""
    from cranesched_tpu.fed.query import FederatedClient
    fed = FederatedClient.connect(args.server, token=_token(args),
                                  tls=_tls(args))
    if fed is None:
        print("not a federated cluster (QueryShardMap returned no "
              "shards)", file=sys.stderr)
    return fed


def _fed_footer(res) -> None:
    """Per-shard provenance lines: which replica answered (and how
    durable its view was), and which shards failed to answer."""
    for shard, reply in res:
        seq = getattr(reply, "durable_seq", 0)
        print(f"# shard {shard}: durable_seq={seq}")
    for shard, err in sorted(res.errors.items()):
        print(f"# shard {shard}: UNAVAILABLE ({err})", file=sys.stderr)


def cmd_cqueue(args) -> int:
    from cranesched_tpu.rpc.client import StreamResult
    if getattr(args, "federation", False):
        fed = _fed_connect(args)
        if fed is None:
            return 1
        res = fed.jobs(max_staleness=args.max_staleness,
                       user=args.user, partition=args.partition,
                       include_history=args.history, limit=args.limit,
                       after_job_id=args.after)
        rows = [(shard, j.job_id, j.name[:20], j.user, j.partition,
                 j.status, j.pending_reason or "-",
                 ",".join(j.node_names) or "-")
                for shard, reply in res for j in reply.jobs]
        print(_fmt_table(rows, ("SHARD", "JOBID", "NAME", "USER",
                                "PARTITION", "STATE", "REASON",
                                "NODES")))
        _fed_footer(res)
        fed.close()
        return 1 if res.errors else 0
    client = _client(args)
    rows = []
    res = StreamResult()
    # server-streaming: chunks arrive as they convert, so a 100k-job
    # queue neither builds one giant message nor stalls the cycle
    for j in client.query_jobs_stream(
            user=args.user, partition=args.partition,
            include_history=args.history, limit=args.limit,
            after_job_id=args.after, result=res,
            max_staleness=args.max_staleness):
        rows.append((j.job_id, j.name[:20], j.user, j.partition,
                     j.status, j.pending_reason or "-",
                     ",".join(j.node_names) or "-"))
    print(_fmt_table(rows, ("JOBID", "NAME", "USER", "PARTITION",
                            "STATE", "REASON", "NODES")))
    if res.truncated and rows:
        print(f"# limited to {args.limit}; continue with "
              f"--after {rows[-1][0]}")
    return 0


def _cinfo_topo(client) -> int:
    """Interconnect tree view from the QueryStats topology section."""
    import json as _json
    doc = _json.loads(client.query_stats().json)
    topo = doc.get("topology")
    if not topo:
        print("cinfo: no topology configured", file=sys.stderr)
        return 1
    levels = topo.get("levels") or []
    leaf = levels[0] if levels else {"groups": []}
    frag = leaf.get("fragmentation")
    frag_s = "-" if frag is None else f"{frag:.3f}"
    print(f"cluster  {topo.get('num_nodes')} nodes  "
          f"{topo.get('num_blocks')} blocks  frag={frag_s}")

    def _leaf_line(grp, indent):
        free = grp.get("free")
        free_s = "-" if free is None else str(free)
        print(f"{indent}├─ {grp['name']}  {grp['size']} nodes  "
              f"free={free_s}")

    if len(levels) > 1:
        for upper in levels[1]["groups"]:
            ufree = upper.get("free")
            print(f"└─ {levels[1]['name']} {upper['name']}  "
                  f"{upper['size']} nodes  "
                  f"free={'-' if ufree is None else ufree}")
            for grp in leaf["groups"]:
                if grp.get("parent") == upper["name"]:
                    _leaf_line(grp, "   ")
        orphans = [g for g in leaf["groups"] if g.get("parent") is None]
        if orphans:
            print("└─ (no switch)")
            for grp in orphans:
                _leaf_line(grp, "   ")
    else:
        for grp in leaf["groups"]:
            _leaf_line(grp, "")
    return 0


def cmd_cinfo(args) -> int:
    if getattr(args, "federation", False):
        fed = _fed_connect(args)
        if fed is None:
            return 1
        res = fed.cluster(max_staleness=args.max_staleness)
        # per-shard map epochs: a skew across the column is a live
        # migration mid-flip (the lagging shard re-learns on its next
        # stamped reply)
        epochs = fed.map_epochs()
        rows = [(shard, epochs.get(shard, "-"), n.name,
                 ",".join(n.partitions), n.state,
                 f"{n.cpu_avail:g}/{n.cpu_total:g}",
                 f"{n.mem_avail >> 30}G/{n.mem_total >> 30}G",
                 n.running_jobs)
                for shard, reply in res for n in reply.nodes]
        print(_fmt_table(rows, ("SHARD", "EPOCH", "NODE", "PARTITIONS",
                                "STATE", "CPU(A/T)", "MEM(A/T)",
                                "JOBS")))
        _fed_footer(res)
        fed.close()
        return 1 if res.errors else 0
    client = _client(args)
    if getattr(args, "topo", False):
        return _cinfo_topo(client)
    reply = client.query_cluster(max_staleness=args.max_staleness)
    rows = []
    for n in reply.nodes:
        rows.append((n.name, ",".join(n.partitions), n.state,
                     f"{n.cpu_avail:g}/{n.cpu_total:g}",
                     f"{n.mem_avail >> 30}G/{n.mem_total >> 30}G",
                     n.running_jobs))
    print(_fmt_table(rows, ("NODE", "PARTITIONS", "STATE", "CPU(A/T)",
                            "MEM(A/T)", "JOBS")))
    return 0


def cmd_cfed(args) -> int:
    """Federation admin (``cfed``): the routing map with per-shard map
    epochs, cluster-wide usage gossip, and live partition migration."""
    import json as _json
    fed = _fed_connect(args)
    if fed is None:
        return 1
    try:
        action = getattr(args, "fed_cmd", None) or "map"
        if action == "migrate":
            reply = fed.migrate(args.partition, args.dest)
            if not reply.ok:
                print(f"cfed migrate: {reply.error}", file=sys.stderr)
                return 1
            print(f"migrated {args.partition} -> {args.dest}  "
                  f"mid={reply.mid}  jobs={reply.jobs_moved}  "
                  f"map_epoch={reply.map_epoch}")
            return 0
        if action == "usage":
            res = fed.usage()
            rows = []
            for shard, reply in res:
                if not reply.ok:
                    print(f"# shard {shard}: {reply.error}",
                          file=sys.stderr)
                    continue
                doc = _json.loads(reply.payload)
                for kind, table in (("user", doc.get("user", {})),
                                    ("acct", doc.get("acct", {}))):
                    for name, c in sorted(table.items()):
                        rows.append((shard, kind, name,
                                     c.get("jobs", 0),
                                     c.get("submit_jobs", 0),
                                     reply.durable_seq))
            print(_fmt_table(rows, ("SHARD", "KIND", "NAME", "RUNNING",
                                    "SUBMITTED", "DURABLE_SEQ")))
            return 1 if res.errors else 0
        # default: the map, one row per shard, with its own epoch
        res = fed.shard_maps()
        rows = []
        for shard, reply in res:
            own = next((s for s in reply.shards if s.name == shard),
                       None)
            rows.append((shard, reply.map_epoch,
                         ",".join(own.partitions) if own else "-",
                         own.address if own else "-"))
        print(_fmt_table(rows, ("SHARD", "MAP_EPOCH", "PARTITIONS",
                                "ADDRESS")))
        for shard, err in sorted(res.errors.items()):
            print(f"# shard {shard}: UNAVAILABLE ({err})",
                  file=sys.stderr)
        return 1 if res.errors else 0
    except ValueError as exc:
        print(f"cfed: {exc}", file=sys.stderr)
        return 1
    finally:
        fed.close()


def cmd_ccancel(args) -> int:
    client = _client(args)
    rc = 0
    for job_id in args.job_ids:
        reply = client.cancel(job_id)
        if not reply.ok:
            print(f"ccancel {job_id}: {reply.error}", file=sys.stderr)
            rc = 1
    return rc


def cmd_crequeue(args) -> int:
    """Stop a running job and put it back in the queue (the reference's
    RequeueJob surface, Crane.proto:1407)."""
    client = _client(args)
    rc = 0
    for job_id in args.job_ids:
        reply = client.requeue(job_id)
        if not reply.ok:
            print(f"crequeue {job_id}: {reply.error}", file=sys.stderr)
            rc = 1
    return rc


def cmd_csummary(args) -> int:
    """Aggregated per-state job counts (the reference's
    QueryJobSummary, Crane.proto:1588) — one small reply instead of
    streaming the whole queue."""
    if getattr(args, "federation", False):
        fed = _fed_connect(args)
        if fed is None:
            return 1
        res = fed.summary(max_staleness=args.max_staleness,
                          user=args.user, partition=args.partition)
        counts: dict[str, int] = {}
        total = 0
        for _shard, reply in res:
            total += reply.total
            for s in reply.states:
                counts[s.status] = counts.get(s.status, 0) + s.count
        rows = [(st, counts[st]) for st in sorted(counts)]
        print(_fmt_table(rows, ("STATE", "COUNT")))
        print(f"# total {total} across "
              f"{len(res.replies)} shard(s)")
        _fed_footer(res)
        fed.close()
        return 1 if res.errors else 0
    client = _client(args)
    reply = client.query_job_summary(user=args.user,
                                     partition=args.partition,
                                     max_staleness=args.max_staleness)
    rows = [(s.status, s.count) for s in reply.states]
    print(_fmt_table(rows, ("STATE", "COUNT")))
    print(f"# total {reply.total}")
    return 0


def cmd_cnode(args) -> int:
    client = _client(args)
    reply = client.modify_node(args.node, args.action)
    if not reply.ok:
        print(f"cnode: {reply.error}", file=sys.stderr)
        return 1
    return 0


def _cstats_stalled(doc) -> str | None:
    """Client-side stall detection: the last completed cycle is older
    than a few cycle intervals of server wall clock (tick_mode servers
    only cycle on demand, so they never count as stalled)."""
    wd = doc.get("watchdog") or {}
    if wd.get("tick_mode") or not wd.get("last_cycle_walltime"):
        return None
    age = float(wd.get("now", 0.0)) - float(wd["last_cycle_walltime"])
    # an event-driven leader may legitimately sleep up to idle_sleep
    # between (skipped) cycles — don't call that a stall
    limit = max(3.0 * float(wd.get("cycle_interval", 1.0)),
                2.0 * float(wd.get("idle_sleep", 0.0)), 5.0)
    if age > limit:
        return (f"scheduler stalled: last completed cycle {age:.1f}s "
                f"ago (cycle interval {wd.get('cycle_interval')}s)")
    return None


def _slo_table_rows(tag: str, table) -> list:
    """One shard's (or the merged CLUSTER's) SLO table -> display rows
    under a leading SHARD column, same shape as the cqueue merge."""
    out = []
    for slo in table or ():
        for win, w in sorted(slo.get("windows", {}).items(),
                             key=lambda kv: int(kv[0])):
            out.append((
                tag, slo.get("name"),
                f"{slo.get('from')}->{slo.get('to')}",
                f"p{slo.get('p'):g}<={slo.get('target_seconds')}s",
                f"{int(win)}s", w.get("count"),
                round(float(w.get("observed", 0.0)), 4),
                w.get("burn_rate"),
                "BREACH" if w.get("breaching") else "ok"))
    return out


def cmd_cstats(args) -> int:
    import json as _json
    if getattr(args, "federation", False):
        fed = _fed_connect(args)
        if fed is None:
            return 1
        if getattr(args, "job", 0):
            # the owner shard is whichever one recorded the timeline —
            # fan the summary out and render EVERY hit: shards number
            # jobs independently, so one id can name different jobs on
            # different shards (a forwarded submit's waterfall lives on
            # the owner, not the shard the client happened to dial)
            res = fed.summary(max_staleness=args.max_staleness,
                              job_id=args.job)
            hits = 0
            for shard, reply in res:
                if reply.timeline_json:
                    from cranesched_tpu.obs.jobtrace import \
                        render_waterfall
                    hits += 1
                    print(f"# shard {shard}")
                    for line in render_waterfall(
                            _json.loads(reply.timeline_json)):
                        print(line)
            fed.close()
            if not hits:
                print(f"no timeline recorded for job {args.job} on "
                      f"any shard", file=sys.stderr)
                return 1
            return 0
        res = fed.stats(max_staleness=args.max_staleness)
        shard_docs = {}
        for shard, reply in res:
            try:
                shard_docs[shard] = _json.loads(reply.json)
            except ValueError:
                res.errors[shard] = "unparseable stats reply"
        if getattr(args, "slo", False):
            # satellite fix (ISSUE 16): --federation used to dump the
            # raw per-shard JSON and silently drop --slo.  Now: each
            # shard's burn-rate rows shard-labeled like cqueue, plus
            # the exact CLUSTER merge (obs/fedobs.py) the storm drills
            # assert on.
            from cranesched_tpu.obs.fedobs import merge_slo_tables
            tables = {s: d.get("slo") or [] for s, d in
                      shard_docs.items() if d.get("slo") is not None}
            if not any(tables.values()):
                print("no SLOs configured on any shard "
                      "(Observability: SLO: in the cluster YAML)",
                      file=sys.stderr)
                fed.close()
                return 1
            rows = []
            for shard in sorted(tables):
                rows.extend(_slo_table_rows(shard, tables[shard]))
            rows.extend(_slo_table_rows("CLUSTER",
                                        merge_slo_tables(tables)))
            print(_fmt_table(rows, ("SHARD", "SLO", "EDGE", "TARGET",
                                    "WINDOW", "COUNT", "OBSERVED",
                                    "BURN", "STATE")))
            _fed_footer(res)
            fed.close()
            return 1 if res.errors else 0
        prefix = getattr(args, "metrics", None)
        if prefix is not None:
            # cluster-wide scrape: counters/histograms summed across
            # shards, gauges kept per-shard under a shard= label
            from cranesched_tpu.obs.fedobs import merge_metric_snapshots
            merged = merge_metric_snapshots(
                {s: d.get("metrics") or {} for s, d in
                 shard_docs.items()})
            rows = []
            for name, m in sorted(merged.items()):
                if not name.startswith(prefix):
                    continue
                for labels, v in sorted(m.get("values", {}).items()):
                    if isinstance(v, dict):
                        val = (f"count={v.get('count')} sum="
                               f"{round(float(v.get('sum', 0.0)), 6)}")
                    else:
                        val = v
                    rows.append((name + labels, m.get("type"), val))
            if not rows and prefix:
                print(f"no metric family starts with {prefix!r}",
                      file=sys.stderr)
                fed.close()
                return 1
            print(_fmt_table(rows, ("METRIC", "TYPE", "VALUE")))
            _fed_footer(res)
            fed.close()
            return 1 if res.errors else 0
        doc = dict(shard_docs)
        for shard, sub in doc.items():
            sub["_durable_seq"] = getattr(
                res.replies[shard], "durable_seq", 0)
        for shard, err in sorted(res.errors.items()):
            doc[shard] = {"_error": err}
        print(_json.dumps(doc))
        fed.close()
        return 1 if res.errors else 0
    client = _client(args)
    if getattr(args, "job", 0):
        # the timeline rides QueryJobSummary (standby-servable) — no
        # need to pull the full stats doc
        reply = client.query_job_summary(job_id=args.job)
        if not reply.timeline_json:
            print(f"no timeline recorded for job {args.job}",
                  file=sys.stderr)
            return 1
        from cranesched_tpu.obs.jobtrace import render_waterfall
        for line in render_waterfall(_json.loads(reply.timeline_json)):
            print(line)
        return 0
    doc = _json.loads(client.query_stats(
        max_staleness=getattr(args, "max_staleness", 0.0)).json)
    stalled = _cstats_stalled(doc)
    if stalled:
        print(f"WARNING: {stalled}", file=sys.stderr)
    if doc.get("cycle_crashes_total"):
        crash = (doc.get("last_crash") or {})
        print(f"WARNING: {doc['cycle_crashes_total']} scheduler cycle "
              f"crash(es); last at t={crash.get('time')}",
              file=sys.stderr)
    if getattr(args, "ha", False):
        h = doc.get("ha") or {}
        rows = [("role", h.get("role", "leader")),
                ("fencing_epoch", h.get("fencing_epoch", 0)),
                ("wal_seq", h.get("wal_seq", 0)),
                ("replication_lag", h.get("replication_lag", 0)),
                ("failovers_total", h.get("failovers_total", 0)),
                ("peer", h.get("peer") or "-")]
        print(_fmt_table(rows, ("HA", "VALUE")))
        return 0
    if getattr(args, "cycles", False):
        rows = [(t.get("now"), t.get("solver"),
                 # MESH: solve span as procs x local devices ("1x8" =
                 # single process over 8 chips); "-" for host solvers
                 t.get("mesh", "-"),
                 t.get("queue_depth"),
                 t.get("candidates"), t.get("placed"),
                 t.get("backfilled"), t.get("preempted"),
                 # SKIP: coalesced short-circuit count (+ reason);
                 # DIRTY: jobs/nodes patched since the last cycle
                 (f"{t.get('skips')}:{t.get('skip_reason')}"
                  if t.get("skips") else "-"),
                 (f"{t.get('dirty_jobs')}/{t.get('dirty_nodes')}"
                  if t.get("dirty_jobs") is not None else "-"),
                 t.get("prelude_ms"), t.get("solve_ms"),
                 t.get("commit_ms"), t.get("dispatch_ms"),
                 t.get("lock_held_ms"), t.get("total_ms"),
                 t.get("wal_fsyncs"), t.get("topo_frag", "-"))
                for t in doc.get("cycle_trace", [])]
        print(_fmt_table(rows, (
            "NOW", "SOLVER", "MESH", "QUEUE", "CAND", "PLACED",
            "BACKFILL", "PREEMPT", "SKIP", "DIRTY", "PRELUDE_MS",
            "SOLVE_MS", "COMMIT_MS", "DISPATCH_MS", "LOCK_MS",
            "TOTAL_MS", "FSYNC", "FRAG")))
        return 0
    if getattr(args, "slo", False):
        rows = []
        for slo in doc.get("slo") or ():
            for win, w in sorted(slo.get("windows", {}).items(),
                                 key=lambda kv: int(kv[0])):
                rows.append((
                    slo.get("name"),
                    f"{slo.get('from')}->{slo.get('to')}",
                    f"p{slo.get('p'):g}<={slo.get('target_seconds')}s",
                    f"{int(win)}s", w.get("count"),
                    round(float(w.get("observed", 0.0)), 4),
                    w.get("burn_rate"),
                    "BREACH" if w.get("breaching") else "ok"))
        if not rows:
            print("no SLOs configured (Observability: SLO: in the "
                  "cluster YAML)", file=sys.stderr)
            return 1
        print(_fmt_table(rows, ("SLO", "EDGE", "TARGET", "WINDOW",
                                "COUNT", "OBSERVED", "BURN", "STATE")))
        return 0
    prefix = getattr(args, "metrics", None)
    if prefix is not None:
        rows = []
        for name, m in sorted((doc.get("metrics") or {}).items()):
            if not name.startswith(prefix):
                continue
            for labels, v in sorted(m.get("values", {}).items()):
                if isinstance(v, dict):   # histogram series
                    val = (f"count={v.get('count')} "
                           f"sum={round(float(v.get('sum', 0.0)), 6)}")
                else:
                    val = v
                rows.append((name + labels, m.get("type"), val))
        if not rows and prefix:
            print(f"no metric family starts with {prefix!r}",
                  file=sys.stderr)
            return 1
        print(_fmt_table(rows, ("METRIC", "TYPE", "VALUE")))
        return 0
    print(_json.dumps(doc))
    return 0


def cmd_cevents(args) -> int:
    """Structured cluster-event ring (standby-servable): node flaps,
    fencing rejections, watchdog crashes, failovers, SLO breaches,
    preemptions, requeues, steady-state recompiles."""
    if getattr(args, "federation", False):
        fed = _fed_connect(args)
        if fed is None:
            return 1
        res = fed.events(severity=args.severity, since=args.since,
                         after_seq=args.after, limit=args.limit,
                         type=args.type,
                         max_staleness=args.max_staleness)
        rows = []
        for shard, reply in res:
            rows.extend(
                (f"{e.time:.3f}", shard, e.seq, e.severity.upper(),
                 e.type, e.node or "-", e.job_id or "-",
                 e.detail or "-")
                for e in reply.events)
        rows.sort(key=lambda r: float(r[0]))
        if rows:
            print(_fmt_table(rows, ("TIME", "SHARD", "SEQ", "SEV",
                                    "TYPE", "NODE", "JOB", "DETAIL")))
        else:
            print("no matching events", file=sys.stderr)
        _fed_footer(res)
        fed.close()
        return 1 if (res.errors or not rows) else 0
    client = _client(args)
    reply = client.query_events(severity=args.severity,
                                since=args.since,
                                after_seq=args.after,
                                limit=args.limit,
                                type=args.type,
                                max_staleness=args.max_staleness)
    if not reply.events:
        print("no matching events", file=sys.stderr)
        return 1
    rows = [(e.seq, f"{e.time:.3f}", e.severity.upper(), e.type,
             e.node or "-", e.job_id or "-", e.detail or "-")
            for e in reply.events]
    print(_fmt_table(rows, ("SEQ", "TIME", "SEV", "TYPE", "NODE",
                            "JOB", "DETAIL")))
    return 0


def cmd_cexplain(args) -> int:
    """Why is this job not running?  First-failing-gate decomposition
    of the scheduler's feasibility pipeline for one pending job."""
    import json as _json
    client = _client(args)
    reply = client.query_job_summary(job_id=args.job_id)
    if not reply.explain_json:
        print(f"no explanation for job {args.job_id}", file=sys.stderr)
        return 1
    doc = _json.loads(reply.explain_json)
    if args.json:
        print(_json.dumps(doc, indent=2))
        return 0
    head = f"job {doc['job_id']}"
    if doc.get("state"):
        head += f" [{doc['state']}]"
    if doc.get("reason"):
        head += f" pending_reason={doc['reason']}"
    print(head)
    print(f"  blocked at: {doc.get('gate') or '-'}"
          + (f" — {doc['detail']}" if doc.get("detail") else ""))
    checks = doc.get("checks") or ()
    if checks:
        rows = [("PASS" if c["ok"] else ">>>", c["gate"],
                 c.get("detail") or "-") for c in checks]
        print(_fmt_table(rows, ("", "GATE", "DETAIL")))
    return 0


def cmd_cprofile(args) -> int:
    """Arm an on-demand jax.profiler capture spanning the next N
    scheduling cycles; the trace lands under profiles/ on the leader."""
    client = _client(args)
    reply = client.capture_profile(cycles=args.cycles, dir=args.dir)
    if not reply.ok:
        print(f"cprofile: {reply.error}", file=sys.stderr)
        return 1
    print(f"profiling armed for {args.cycles} cycle(s) -> {reply.dir}")
    return 0


def _render_flight(fl: dict, tail: int = 32) -> list[str]:
    """Flight-recorder report -> display lines: recent phase timeline,
    then the last stall's ring tail + all-thread stacks."""
    out = []
    phases = (fl.get("phases") or [])[-tail:]
    if phases:
        t0 = phases[0].get("t", 0.0)
        rows = [(f"{p.get('t', 0.0) - t0:+9.3f}s", p.get("phase"),
                 p.get("detail") or "-") for p in phases]
        out.append(_fmt_table(rows, ("T", "PHASE", "DETAIL")))
    else:
        out.append("(no phase stamps recorded)")
    out.append(f"# stalls_total={fl.get('stalls_total', 0)} "
               f"armed={fl.get('armed', False)} "
               f"self_time_s={fl.get('self_time_s', 0.0)}")
    stall = fl.get("last_stall")
    if stall:
        out.append(f"LAST STALL label={stall.get('label')!r} "
                   f"t={stall.get('time')}")
        for p in stall.get("phases") or ():
            out.append(f"  phase {p.get('phase')} t={p.get('t')} "
                       f"{p.get('detail', '')}")
        for thread, frames in sorted(
                (stall.get("stacks") or {}).items()):
            out.append(f"  -- thread {thread}")
            for frame in frames:
                for ln in frame.splitlines():
                    out.append("    " + ln)
    return out


def cmd_cflight(args) -> int:
    """Stall forensics viewer: the flight recorder's recent cycle-phase
    timeline plus the last stall's all-thread stack capture — from a
    live ctld, every shard of a federation, or a BENCH_*.json probe
    diagnosis (``--file``)."""
    import json as _json
    if getattr(args, "file", ""):
        with open(args.file, encoding="utf-8") as fh:
            doc = _json.load(fh)
        # accept the probe dict itself, a bench.py output doc, or the
        # committed BENCH_rNN.json wrapper ({"parsed": <bench doc>})
        acq = doc if isinstance(doc, dict) else {}
        for path in (("device_acquisition",),
                     ("detail", "device_acquisition"),
                     ("parsed", "detail", "device_acquisition")):
            node = doc
            for key in path:
                node = node.get(key) if isinstance(node, dict) else None
            if node:
                acq = node
                break
        phases = acq.get("phases") or []
        print(f"probe acquired={acq.get('acquired', '?')} "
              f"phases={'->'.join(str(p) for p in phases) or '(none)'}")
        # the handshake's heartbeat stamps: where the wall-clock went
        # inside acquisition (the gap after the LAST stamp is the
        # wedged phase on a timeout)
        stamps = acq.get("phase_stamps") or []
        if stamps:
            t0 = float(stamps[0].get("t") or 0.0)
            for s in stamps:
                print(f"  stamp {str(s.get('phase')):<14} "
                      f"+{float(s.get('t') or 0.0) - t0:.3f}s")
        if acq.get("diagnosis"):
            print(f"diagnosis: {acq['diagnosis']}")
        if acq.get("stacks"):
            print("-- harvested probe stacks --")
            print(acq["stacks"])
        return 0 if acq.get("acquired") else 1
    if getattr(args, "federation", False):
        fed = _fed_connect(args)
        if fed is None:
            return 1
        res = fed.stats(max_staleness=args.max_staleness)
        rc = 1 if res.errors else 0
        for shard, reply in res:
            try:
                fl = _json.loads(reply.json).get("flight") or {}
            except ValueError:
                res.errors[shard] = "unparseable stats reply"
                rc = 1
                continue
            print(f"== shard {shard} ==")
            for line in _render_flight(fl, tail=args.tail):
                print(line)
            if fl.get("last_stall"):
                rc = max(rc, 2)
        _fed_footer(res)
        fed.close()
        return rc
    client = _client(args)
    doc = _json.loads(client.query_stats(
        max_staleness=getattr(args, "max_staleness", 0.0)).json)
    fl = doc.get("flight") or {}
    for line in _render_flight(fl, tail=args.tail):
        print(line)
    # a recorded stall is the signal the operator came for: nonzero
    # exit so drills can assert "no stalls" without parsing the text
    return 2 if fl.get("last_stall") else 0


def cmd_ccontrol(args) -> int:
    client = _client(args)
    if args.action in ("hold", "release"):
        reply = client.hold(args.job_id, held=args.action == "hold")
    elif args.action == "suspend":
        reply = client.suspend(args.job_id)
    elif args.action == "resume":
        reply = client.resume(args.job_id)
    elif args.action == "modify":
        # ccontrol modify JOBID time_limit=7200 priority=50
        # partition=gpu  (reference ModifyJob / ccontrol update)
        kw = {}
        for kv in args.fields:
            key, sep, value = kv.partition("=")
            if not sep or key not in ("time_limit", "priority",
                                      "partition"):
                print(f"ccontrol: bad field {kv!r} (use time_limit=, "
                      "priority=, partition=)", file=sys.stderr)
                return 2
            try:
                kw[key] = (value if key == "partition"
                           else float(value) if key == "time_limit"
                           else int(value))
            except ValueError:
                print(f"ccontrol: bad value in {kv!r} "
                      f"({key} must be a number)", file=sys.stderr)
                return 2
        if not kw:
            print("ccontrol: modify needs at least one key=value",
                  file=sys.stderr)
            return 2
        reply = client.modify_job(args.job_id, **kw)
    else:
        print(f"unknown action {args.action}", file=sys.stderr)
        return 2
    if not reply.ok:
        print(f"ccontrol: {reply.error}", file=sys.stderr)
        return 1
    return 0


def cmd_cacct(args) -> int:
    from cranesched_tpu.rpc.client import StreamResult
    client = _client(args)
    rows = []
    res = StreamResult()
    last_id = 0
    for j in client.query_jobs_stream(user=args.user,
                                      include_history=True,
                                      limit=args.limit,
                                      after_job_id=args.after,
                                      result=res):
        # the cursor advances over EVERY streamed id — the live-job
        # filter below must not hide pages (a limit full of running
        # jobs would otherwise read as "no history")
        last_id = j.job_id
        if j.status in ("Pending", "Running", "Suspended"):
            continue
        wall = (j.end_time - j.start_time
                if j.end_time and j.start_time else 0.0)
        rows.append((j.job_id, j.name[:20], j.user, j.status,
                     j.exit_code, f"{wall:.0f}s"))
    print(_fmt_table(rows, ("JOBID", "NAME", "USER", "STATE",
                            "EXIT", "WALL")))
    if res.truncated and last_id:
        print(f"# limited to {args.limit}; continue with "
              f"--after {last_id}")
    return 0


def cmd_ceff(args) -> int:
    """Job efficiency report (reference ceff via
    PluginQueryService::QueryJobEfficiency, Crane.proto:1615-1617):
    allocated vs consumed CPU and memory from the per-step usage
    samples the supervisors reported."""
    client = _client(args)
    jobs = client.query_jobs(job_ids=[args.job_id],
                             include_history=True).jobs
    if not jobs:
        print(f"ceff: no such job {args.job_id}", file=sys.stderr)
        return 1
    j = jobs[0]
    wall = (j.end_time - j.start_time
            if j.end_time and j.start_time else 0.0)
    steps = client.query_steps(args.job_id).steps
    print(f"Job {j.job_id} ({j.name}) user={j.user} state={j.status}")
    print(f"  nodes: {','.join(j.node_names) or '-'}")
    print(f"  wall time: {wall:.1f}s")
    print(f"  cpu used: {j.cpu_seconds:.1f} core-seconds")
    # allocated core-seconds: per-node cpu share x nodes x wall
    # (cpu_total from the cluster query is not needed — the job info
    # itself doesn't carry the request, so derive from usage when
    # possible and report what is known)
    if wall > 0 and j.cpu_seconds > 0:
        n_nodes = max(len(j.node_names), 1)
        print(f"  cpu efficiency: "
              f"{100.0 * j.cpu_seconds / (wall * n_nodes):.1f}% "
              f"(vs {n_nodes} node-cores-seconds; multiply by the "
              f"per-node core count for absolute efficiency)")
    if j.max_rss_bytes:
        print(f"  peak RSS: {j.max_rss_bytes / (1 << 20):.1f} MiB")
    for s in steps:
        if s.cpu_seconds or s.max_rss_bytes:
            print(f"  step {s.step_id}: cpu={s.cpu_seconds:.1f}s "
                  f"rss={s.max_rss_bytes / (1 << 20):.1f}MiB "
                  f"({s.status})")
    return 0


def cmd_cacctmgr(args) -> int:
    import json as _json
    client = _client(args)
    payload = {}
    for kv in args.set or []:
        key, _, value = kv.partition("=")
        if not _:
            print(f"cacctmgr: bad --set {kv!r} (use key=value)",
                  file=sys.stderr)
            return 2
        try:
            payload[key] = _json.loads(value)
        except _json.JSONDecodeError:
            payload[key] = value
    if args.name:
        payload.setdefault("name", args.name)
    reply = client.acct_mgr(args.actor, args.action, payload)
    if not reply.ok:
        print(f"cacctmgr: {reply.error}", file=sys.stderr)
        return 1
    if reply.json:
        print(_json.dumps(_json.loads(reply.json), indent=2))
    return 0


def cmd_cresv(args) -> int:
    client = _client(args)
    if args.action == "create":
        if not args.nodelist:
            print("cresv create: --nodelist is required",
                  file=sys.stderr)
            return 2
        if args.end <= args.start:
            print("cresv create: --end must be after --start",
                  file=sys.stderr)
            return 2
        reply = client.create_reservation(
            args.resv_name, args.partition, args.nodelist.split(","),
            args.start, args.end,
            allowed_accounts=(args.accounts.split(",")
                              if args.accounts else ()))
    else:
        reply = client.delete_reservation(args.resv_name)
    if not reply.ok:
        print(f"cresv: {reply.error}", file=sys.stderr)
        return 1
    return 0


def cmd_cpki(args) -> int:
    """Cluster PKI admin (the VaultClient role, VaultClient.h:39):
    ``cpki init`` creates the cluster CA; ``cpki issue NAME`` signs an
    endpoint cert with SANs for its hostnames/IPs."""
    from cranesched_tpu.utils import pki
    if args.action == "init":
        ca, key = pki.create_ca(args.dir)
        print(f"cluster CA created: {ca}\nCA key (keep private): {key}")
        print("distribute ca.pem to clients (~/.crane/ca.pem) and "
              "craneds (--tls-ca)")
        return 0
    if not args.name:
        print("cpki issue requires a NAME", file=sys.stderr)
        return 2
    ca = os.path.join(args.dir, "ca.pem")
    ca_key = os.path.join(args.dir, "ca.key")
    if not (os.path.exists(ca_key) and os.path.exists(ca)):
        print(f"no CA at {args.dir} (run cpki init first)",
              file=sys.stderr)
        return 2
    dns = tuple(d for d in args.dns.split(",") if d)
    ips = tuple(i for i in args.ip.split(",") if i)
    cert, key = pki.issue_cert(args.dir, args.name, ca, ca_key,
                               dns=dns, ips=ips)
    print(f"issued: {cert}\nkey: {key}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    top = argparse.ArgumentParser(prog="crane")
    top.add_argument("--server",
                     default=os.environ.get("CRANE_SERVER",
                                            "127.0.0.1:50051"),
                     help="ctld address, or a comma-separated HA pair "
                          "(the client follows the leader)")
    top.add_argument("--token", default="",
                     help="bearer token (default: $CRANE_TOKEN or "
                          "~/.crane/token)")
    top.add_argument("--ca", default="",
                     help="cluster CA cert for TLS (default: $CRANE_CA "
                          "or ~/.crane/ca.pem if present)")
    top.add_argument("--cert", default="",
                     help="client cert for mTLS clusters (default: "
                          "$CRANE_CERT or ~/.crane/cert.pem)")
    top.add_argument("--key", default="",
                     help="client key for mTLS clusters (default: "
                          "$CRANE_KEY or ~/.crane/key.pem)")
    sub = top.add_subparsers(dest="command", required=True)

    p = sub.add_parser("cbatch", help="submit a batch job")
    p.add_argument("--job-name", "-J", default="job")
    p.add_argument("--user", default=os.environ.get("USER", "user"))
    p.add_argument("--account", "-A", default="default")
    p.add_argument("--partition", "-p", default="default")
    p.add_argument("--cpu", "-c", type=float, default=1.0)
    p.add_argument("--mem", default="0")
    p.add_argument("--memsw", default="")
    p.add_argument("--nodes", "-N", type=int, default=1)
    p.add_argument("--gres", default="",
                   help="name[:type]:count, comma-separated")
    p.add_argument("--time", "-t", type=int, default=3600)
    p.add_argument("--qos", "-q", default="")
    p.add_argument("--hold", action="store_true")
    p.add_argument("--exclusive", action="store_true")
    p.add_argument("--reservation", default="")
    p.add_argument("--nodelist", "-w", default="")
    p.add_argument("--exclude", "-x", default="")
    p.add_argument("--requeue", action="store_true")
    p.add_argument("--array", "-a", default="")
    p.add_argument("--dependency", "-d", default="")
    p.add_argument("--dependency-any", action="store_true")
    p.add_argument("--ntasks", "-n", type=int, default=0)
    p.add_argument("--ntasks-per-node-min", type=int, default=1)
    p.add_argument("--ntasks-per-node-max", type=int, default=0)
    p.add_argument("--cpus-per-task", type=float, default=1.0)
    p.add_argument("--mem-per-task", default="0")
    p.add_argument("--sim-runtime", type=float, default=0.0)
    p.add_argument("--script", default="",
                   help="batch script (bash -c) for real node planes")
    p.add_argument("--output", "-o", default="",
                   help="output file pattern (%%j = job id)")
    p.add_argument("--image", default="",
                   help="run the batch step inside this OCI image")
    p.add_argument("--mount", action="append", default=[],
                   help="host:ctr[:ro] bind for --image (repeatable)")
    p.set_defaults(func=cmd_cbatch)

    p = sub.add_parser("crun", help="run a command and stream output")
    p.add_argument("script", help="command to run (bash -c)")
    p.add_argument("--job-name", "-J", default="crun")
    p.add_argument("--user", default=os.environ.get("USER", "user"))
    p.add_argument("--account", "-A", default="default")
    p.add_argument("--partition", "-p", default="default")
    p.add_argument("--cpu", "-c", type=float, default=1.0)
    p.add_argument("--mem", default="0")
    p.add_argument("--memsw", default="")
    p.add_argument("--nodes", "-N", type=int, default=1)
    p.add_argument("--gres", default="")
    p.add_argument("--time", "-t", type=int, default=3600)
    p.add_argument("--qos", "-q", default="")
    p.add_argument("--reservation", default="")
    p.add_argument("--jobid", type=int, default=0,
                   help="run as a STEP inside this calloc allocation")
    p.add_argument("--pty", action="store_true",
                   help="run the command on a pseudo-terminal")
    p.add_argument("--bind-host", default="127.0.0.1",
                   help="address craneds use to reach this client's "
                        "I/O stream (set to a routable IP/hostname on "
                        "multi-host clusters)")
    p.add_argument("--io-cert", default="",
                   help="serve the I/O stream over TLS with this cert "
                        "(issue one with cpki issue <user>; on "
                        "multi-host clusters issue it with "
                        "--ip <bind-host> so supervisors can verify "
                        "the advertised address)")
    p.add_argument("--io-key", default="",
                   help="key for --io-cert")
    p.add_argument("--image", default="",
                   help="run the command inside this OCI image "
                        "(node's podman/docker)")
    p.add_argument("--mount", action="append", default=[],
                   help="host:ctr[:ro] bind for --image (repeatable)")
    p.add_argument("--overlap", action="store_true",
                   help="hold no share of the allocation "
                        "(observation steps)")
    p.add_argument("--x11", action="store_true",
                   help="forward X11: the step gets a DISPLAY relayed "
                        "to this client's X server")
    p.set_defaults(func=cmd_crun)

    p = sub.add_parser("ccon", help="container jobs (ccon run IMAGE "
                                    "SCRIPT)")
    ccon_sub = p.add_subparsers(dest="ccon_action", required=True)
    pr = ccon_sub.add_parser("run", help="submit a container batch job")
    pr.add_argument("image_name", metavar="IMAGE")
    pr.add_argument("script", help="command run inside the container "
                                   "(bash -c)")
    pr.add_argument("--job-name", "-J", default="ccon")
    pr.add_argument("--user", default=os.environ.get("USER", "user"))
    pr.add_argument("--account", "-A", default="default")
    pr.add_argument("--partition", "-p", default="default")
    pr.add_argument("--cpu", "-c", type=float, default=1.0)
    pr.add_argument("--mem", default="0")
    pr.add_argument("--memsw", default="")
    pr.add_argument("--nodes", "-N", type=int, default=1)
    pr.add_argument("--gres", default="")
    pr.add_argument("--time", "-t", type=int, default=3600)
    pr.add_argument("--qos", "-q", default="")
    pr.add_argument("--reservation", default="")
    pr.add_argument("--mount", action="append", default=[],
                    help="host:ctr[:ro] bind mount (repeatable)")
    pr.add_argument("--output", "-o", default="",
                    help="output file pattern (%%j = job id)")
    pr.set_defaults(func=cmd_ccon)

    p = sub.add_parser("cattach",
                       help="attach to a running container step")
    p.add_argument("job_id", type=int)
    p.add_argument("--step", type=int, default=0,
                   help="step whose container to attach (default 0)")
    p.add_argument("--bind-host", default="127.0.0.1")
    p.set_defaults(func=cmd_cattach)

    p = sub.add_parser("calloc",
                       help="allocate resources (steps run via "
                            "crun --jobid; release with cfree)")
    p.add_argument("--job-name", "-J", default="calloc")
    p.add_argument("--user", default=os.environ.get("USER", "user"))
    p.add_argument("--account", "-A", default="default")
    p.add_argument("--partition", "-p", default="default")
    p.add_argument("--cpu", "-c", type=float, default=1.0)
    p.add_argument("--mem", default="0")
    p.add_argument("--memsw", default="")
    p.add_argument("--nodes", "-N", type=int, default=1)
    p.add_argument("--gres", default="")
    p.add_argument("--time", "-t", type=int, default=3600)
    p.add_argument("--qos", "-q", default="")
    p.add_argument("--reservation", default="")
    p.add_argument("--wait", type=float, default=30.0,
                   help="seconds to wait for the allocation to start")
    p.add_argument("--poll", type=float, default=0.3)
    p.set_defaults(func=cmd_calloc)

    p = sub.add_parser("cfree", help="release a calloc allocation")
    p.add_argument("job_id", type=int)
    p.set_defaults(func=cmd_cfree)

    p = sub.add_parser("ctoken",
                       help="issue/revoke user tokens (admin)")
    p.add_argument("user")
    p.add_argument("--revoke", action="store_true")
    p.add_argument("--save", action="store_true",
                   help="write the issued token to ~/.crane/token.<user>")
    p.set_defaults(func=cmd_ctoken)

    p = sub.add_parser("cstep", help="list a job's steps")
    p.add_argument("job_id", type=int)
    p.set_defaults(func=cmd_cstep)

    p = sub.add_parser("cqueue", help="show the job queue")
    p.add_argument("--user", "-u", default="")
    p.add_argument("--partition", "-p", default="")
    p.add_argument("--history", action="store_true")
    p.add_argument("--limit", "-L", type=int, default=0,
                   help="page size (0 = everything)")
    p.add_argument("--after", type=int, default=0,
                   help="resume after this job id (keyset cursor)")
    _fed_flags(p)
    p.set_defaults(func=cmd_cqueue)

    p = sub.add_parser("cinfo", help="show cluster nodes")
    p.add_argument("--topo", action="store_true",
                   help="render the interconnect topology tree "
                        "(blocks/switches, free nodes, fragmentation)")
    _fed_flags(p)
    p.set_defaults(func=cmd_cinfo)

    p = sub.add_parser("cfed",
                       help="federation admin: shard map + map epochs, "
                            "usage gossip, live partition migration")
    fed_sub = p.add_subparsers(dest="fed_cmd")
    pm = fed_sub.add_parser("map", help="routing table with per-shard "
                                        "map epochs (the default)")
    pm.set_defaults(func=cmd_cfed)
    pu = fed_sub.add_parser("usage",
                            help="cluster-wide usage gossip summaries")
    pu.set_defaults(func=cmd_cfed)
    pg = fed_sub.add_parser(
        "migrate",
        help="live-migrate a partition to another shard (drains, "
             "hands off pending+running jobs, flips the map epoch)")
    pg.add_argument("partition")
    pg.add_argument("dest")
    pg.set_defaults(func=cmd_cfed)
    p.set_defaults(func=cmd_cfed)

    p = sub.add_parser("ccancel", help="cancel jobs")
    p.add_argument("job_ids", nargs="+", type=int)
    p.set_defaults(func=cmd_ccancel)

    p = sub.add_parser("ccontrol",
                       help="hold/release/suspend/resume/modify")
    p.add_argument("action",
                   choices=["hold", "release", "suspend", "resume",
                            "modify"])
    p.add_argument("job_id", type=int)
    p.add_argument("fields", nargs="*", metavar="key=value",
                   help="modify only: time_limit=SECONDS "
                        "priority=N partition=NAME")
    p.set_defaults(func=cmd_ccontrol)

    p = sub.add_parser("cacct", help="show accounting history")
    p.add_argument("--user", "-u", default="")
    p.add_argument("--limit", "-L", type=int, default=0,
                   help="page size (0 = everything)")
    p.add_argument("--after", type=int, default=0,
                   help="resume after this job id (keyset cursor)")
    p.set_defaults(func=cmd_cacct)

    p = sub.add_parser("ceff", help="job efficiency (cpu/memory)")
    p.add_argument("job_id", type=int)
    p.set_defaults(func=cmd_ceff)

    p = sub.add_parser("cnode", help="node control (drain/resume/...)")
    p.add_argument("action",
                   choices=["drain", "resume", "poweroff", "wake"])
    p.add_argument("node")
    p.set_defaults(func=cmd_cnode)

    p = sub.add_parser("cstats", help="scheduler cycle statistics")
    p.add_argument("--cycles", action="store_true",
                   help="print the last-N cycle trace ring as a table")
    p.add_argument("--metrics", nargs="?", const="", default=None,
                   metavar="PREFIX",
                   help="print the metric registry snapshot as a table; "
                        "optional PREFIX keeps only metric families "
                        "whose name starts with it")
    p.add_argument("--ha", action="store_true",
                   help="print HA role / fencing epoch / replication "
                        "lag as a table")
    p.add_argument("--job", type=int, default=0, metavar="JOB_ID",
                   help="print the job's lifecycle timeline as an "
                        "ASCII waterfall (per-job tracing)")
    p.add_argument("--slo", action="store_true",
                   help="print the live SLO table (per-window "
                        "percentile + burn rate)")
    _fed_flags(p)
    p.set_defaults(func=cmd_cstats)

    p = sub.add_parser("cevents",
                       help="structured cluster events (flaps, fencing, "
                            "breaches, ...)")
    p.add_argument("--severity", "-s", default="",
                   choices=["", "debug", "info", "warning", "error",
                            "critical"],
                   help="minimum severity to show")
    p.add_argument("--since", type=float, default=0.0,
                   help="only events at/after this epoch time")
    p.add_argument("--after", type=int, default=0, metavar="SEQ",
                   help="only events with seq > SEQ (cursor)")
    p.add_argument("--type", "-t", default="",
                   help="exact event type (e.g. node_flap, slo_breach)")
    p.add_argument("--limit", "-L", type=int, default=0,
                   help="newest N matches (0 = all)")
    _fed_flags(p)
    p.set_defaults(func=cmd_cevents)

    p = sub.add_parser("cexplain",
                       help="why is this job pending? first failing "
                            "feasibility gate")
    p.add_argument("job_id", type=int)
    p.add_argument("--json", action="store_true",
                   help="print the raw decomposition document")
    p.set_defaults(func=cmd_cexplain)

    p = sub.add_parser("cprofile",
                       help="capture a jax.profiler trace of the next "
                            "N scheduling cycles")
    p.add_argument("--cycles", "-n", type=int, default=3)
    p.add_argument("--dir", default="",
                   help="output directory (default profiles/capture-*)")
    p.set_defaults(func=cmd_cprofile)

    p = sub.add_parser("cflight",
                       help="stall forensics: recent cycle-phase "
                            "timeline + the last stall's thread stacks")
    p.add_argument("--tail", type=int, default=32, metavar="N",
                   help="phase stamps to show (newest N)")
    p.add_argument("--file", default="", metavar="PATH",
                   help="render a BENCH_*.json probe diagnosis instead "
                        "of querying a server")
    _fed_flags(p)
    p.set_defaults(func=cmd_cflight)

    p = sub.add_parser("crequeue",
                       help="stop running jobs and requeue them")
    p.add_argument("job_ids", nargs="+", type=int)
    p.set_defaults(func=cmd_crequeue)

    p = sub.add_parser("csummary",
                       help="per-state job counts (cheap aggregate)")
    p.add_argument("--user", "-u", default="")
    p.add_argument("--partition", "-p", default="")
    _fed_flags(p)
    p.set_defaults(func=cmd_csummary)

    p = sub.add_parser("cacctmgr", help="accounts/users/QoS admin")
    p.add_argument("action",
                   choices=["add_qos", "add_account", "add_user",
                            "block_user", "block_account",
                            "set_admin_level", "show"])
    p.add_argument("name", nargs="?", default="")
    p.add_argument("--actor", default=os.environ.get("USER", "root"))
    p.add_argument("--set", action="append", metavar="KEY=VALUE",
                   help="payload fields (JSON values accepted)")
    p.set_defaults(func=cmd_cacctmgr)

    p = sub.add_parser("cpki",
                       help="cluster PKI: init the CA / issue certs")
    p.add_argument("action", choices=["init", "issue"])
    p.add_argument("name", nargs="?", default="",
                   help="endpoint name for issue (e.g. ctld, cn01)")
    p.add_argument("--dir", default=os.path.expanduser("~/.crane/pki"),
                   help="PKI directory (CA + issued certs)")
    p.add_argument("--dns", default="",
                   help="extra DNS SANs, comma-separated")
    p.add_argument("--ip", default="",
                   help="extra IP SANs, comma-separated")
    p.set_defaults(func=cmd_cpki)

    p = sub.add_parser("cresv", help="manage reservations")
    p.add_argument("action", choices=["create", "delete"])
    p.add_argument("resv_name")
    p.add_argument("--partition", "-p", default="default")
    p.add_argument("--nodelist", "-w", default="")
    p.add_argument("--start", type=float, default=0.0)
    p.add_argument("--end", type=float, default=0.0)
    p.add_argument("--accounts", default="")
    p.set_defaults(func=cmd_cresv)

    return top


def main(argv=None) -> int:
    import grpc
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except grpc.RpcError as exc:
        code = exc.code().name if hasattr(exc, "code") else "RPC_ERROR"
        print(f"crane: cannot reach ctld at {args.server} ({code})",
              file=sys.stderr)
        return 3


if __name__ == "__main__":
    sys.exit(main())
