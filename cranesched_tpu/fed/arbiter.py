"""Placement arbiter: the one thin coordinator in the federation.

Everything partition-local schedules on its shard with zero cross-shard
traffic.  The arbiter owns exactly one job class — cross-partition
gangs, which need nodes from partitions living on different shards —
and commits them with a two-phase protocol over the shards' WALs:

1. **Reserve**: lease concrete nodes from each involved shard
   (``LeaseNodes`` → a durable ``fed_reserve`` record under the
   shard's fencing epoch; the nodes vanish from that shard's local
   scheduling while leased).
2. **Confirm**: turn each lease into a RUNNING shard-local gang member
   (``ConfirmGang`` → ``fed_confirm`` + the member's job records in
   ONE WAL group).  Only the confirm creates a job, so a shard crash
   between the phases leaves a bare reserve that the shard's recovery
   releases — never a double placement, never a half-placed gang that
   survives as state.

If any confirm fails (shard died, fencing epoch moved), the arbiter
*aborts*: already-confirmed members are cancelled through the normal
cancel path, unconfirmed leases are released, and the gang goes back in
the queue for a later pump.  The abort is idempotent against a crashed
shard — its recovery drops the reserve on its own.

Member sizing mirrors the topology solver's best-fit-block discipline
one level up, with shards as the blocks: a gang is first tried whole in
the single partition with the tightest fit, and only split across
partitions (fewest first) when no single one can host it — the same
"smallest sufficient block, least fragmentation" rule
``topo/place.py`` applies to switch blocks.
"""

from __future__ import annotations

import dataclasses
import itertools

from cranesched_tpu.ctld.defs import JobSpec
from cranesched_tpu.obs import REGISTRY as _OBS
from cranesched_tpu.obs.events import EventLog

_MET_COMMITS = _OBS.counter(
    "crane_fed_arbiter_commits_total",
    "cross-partition gangs fully confirmed by the arbiter")
_MET_ABORTS = _OBS.counter(
    "crane_fed_arbiter_aborts_total",
    "cross-partition gang commits undone after a partial confirm")


@dataclasses.dataclass
class GangRequest:
    """A cross-partition gang: ``node_num`` nodes total, drawn from any
    of ``partitions`` (each possibly on a different shard)."""

    name: str
    node_num: int
    partitions: tuple[str, ...]
    spec: JobSpec  # template: res/user/account/time_limit/sim knobs
    gang_id: str = ""
    attempts: int = 0


class PlacementArbiter:
    """Coordinates gang placement across shard handles.

    ``handles``: shard name -> an object with the shard-plane surface
    (``free_count`` / ``lease`` / ``confirm`` / ``release`` /
    ``cancel``) — in-process wrappers in fed/sim.py, RPC clients in a
    real deploy.  The arbiter itself is synchronous and stateless
    between pumps except for its retry queue: all durable state lives
    in the shards' WALs.
    """

    #: leases self-expire on the shard this many (virtual) seconds
    #: after reserve — a dead arbiter never strands capacity
    LEASE_TTL = 120.0
    #: give up on a gang after this many failed pumps
    MAX_ATTEMPTS = 100

    def __init__(self, shard_map, handles: dict, events=None):
        self.shard_map = shard_map
        self.handles = handles
        self.events = events if events is not None else EventLog()
        self.queue: list[GangRequest] = []
        self._ids = itertools.count(1)
        self.committed: dict[str, dict[str, list[int]]] = {}
        self.stats = {"commits": 0, "aborts": 0, "failed": 0}

    def submit_gang(self, gang: GangRequest) -> str:
        gang.gang_id = gang.gang_id or f"gang-{next(self._ids)}"
        self.queue.append(gang)
        return gang.gang_id

    # -- placement --

    def _plan(self, gang: GangRequest, now: float
              ) -> list[tuple[str, str, int]] | None:
        """-> [(shard, partition, count)] or None when nothing fits.
        Best-fit-block over shards: whole-gang in the single partition
        with the least leftover, else split across partitions taking
        the fullest-fitting first."""
        free: list[tuple[str, str, int]] = []
        for part in gang.partitions:
            shard = self.shard_map.shard_for_partition(part)
            handle = self.handles.get(shard)
            if handle is None:
                continue
            try:
                n = handle.free_count(part, gang.spec)
            except Exception:
                continue  # shard unreachable — plan around it
            if n > 0:
                free.append((shard, part, n))
        whole = [(n, shard, part) for shard, part, n in free
                 if n >= gang.node_num]
        if whole:
            _n, shard, part = min(whole)  # tightest fit
            return [(shard, part, gang.node_num)]
        plan, remaining = [], gang.node_num
        for shard, part, n in sorted(free, key=lambda t: -t[2]):
            take = min(remaining, n)
            plan.append((shard, part, take))
            remaining -= take
            if remaining == 0:
                return plan
        return None

    def _member_spec(self, gang: GangRequest, partition: str,
                     count: int) -> JobSpec:
        return dataclasses.replace(
            gang.spec, name=f"{gang.name}@{partition}",
            partition=partition, node_num=count)

    def pump(self, now: float) -> list[str]:
        """One arbiter round: try every queued gang once.  Returns the
        gang ids committed this round."""
        done: list[str] = []
        retry: list[GangRequest] = []
        for gang in self.queue:
            if self._try_place(gang, now):
                done.append(gang.gang_id)
            else:
                gang.attempts += 1
                if gang.attempts >= self.MAX_ATTEMPTS:
                    self.stats["failed"] += 1
                else:
                    retry.append(gang)
        self.queue = retry
        return done

    def _try_place(self, gang: GangRequest, now: float) -> bool:
        plan = self._plan(gang, now)
        if plan is None:
            return False
        # phase one: reserve every member's nodes
        leases: list[tuple[str, str, str, int, list, int]] = []
        for i, (shard, part, count) in enumerate(plan):
            lease_id = f"{gang.gang_id}.{i}"
            try:
                names, epoch, _seq = self.handles[shard].lease(
                    lease_id, part, count,
                    self._member_spec(gang, part, count),
                    self.LEASE_TTL, now)
            except Exception:
                for sh, lid, *_ in leases:
                    self._release(sh, lid, now)
                return False
            leases.append((shard, lease_id, part, count, names, epoch))
        # phase two: confirm member by member
        confirmed: list[tuple[str, int]] = []
        for shard, lease_id, part, count, names, epoch in leases:
            spec = self._member_spec(gang, part, count)
            try:
                job_id = self.handles[shard].confirm(
                    lease_id, gang.gang_id, spec, names, now, epoch)
            except Exception as e:
                # abort: cancel what committed, release what didn't.
                # A dead shard's reserve is dropped by its own recovery;
                # both calls below tolerate an unreachable handle.
                for sh, jid in confirmed:
                    self._cancel(sh, jid, now)
                # release everything — a no-op for leases already
                # consumed by a successful confirm
                for sh, lid, *_ in leases:
                    self._release(sh, lid, now)
                self.events.emit(
                    "fed_arbiter_abort", "warning", time=now,
                    detail=f"gang={gang.gang_id} shard={shard}: {e}")
                _MET_ABORTS.inc()
                self.stats["aborts"] += 1
                return False
            confirmed.append((shard, job_id))
        self.committed[gang.gang_id] = {
            sh: [] for sh in {s for s, _ in confirmed}}
        for sh, jid in confirmed:
            self.committed[gang.gang_id][sh].append(jid)
        self.events.emit(
            "fed_arbiter_commit", "info", time=now,
            detail=f"gang={gang.gang_id} members="
                   f"{','.join(f'{s}:{j}' for s, j in confirmed)}")
        _MET_COMMITS.inc()
        self.stats["commits"] += 1
        return True

    def _release(self, shard: str, lease_id: str, now: float) -> None:
        try:
            self.handles[shard].release(lease_id, now)
        except Exception:
            pass  # dead shard: its recovery drops the reserve

    def _cancel(self, shard: str, job_id: int, now: float) -> None:
        try:
            self.handles[shard].cancel(job_id, now)
        except Exception:
            pass  # dead shard: the member's records replay, but its
            # gang siblings were never confirmed — the caller re-places
