"""Federated query plane: scatter-gather reads across every shard.

``cqueue``/``cinfo``/``cstats``/``csummary``/``cevents`` against a
federation must show the WHOLE cluster, but no single controller holds
it — each shard owns its partitions' jobs and nodes outright.  The
:class:`FederatedClient` fans a read out to all shards in parallel,
merges the answers, and labels each row with its shard of origin plus
the ``durable_seq`` the answering replica had applied — the caller can
see exactly how fresh each slice is.

Bounded staleness: every fan-out takes ``max_staleness`` (seconds).
Each shard's client dials FOLLOWERS FIRST (leader last): a follower
that has been caught up within the bound serves the read locally and
the leader never sees it; a follower past the bound refuses with
FAILED_PRECONDITION and the client rotation falls through to the
leader.  ``max_staleness=0`` is the legacy contract — any replica
answers with whatever it has.

A dead shard degrades, never blocks: its slice is reported in
``errors`` and the merge carries on with the shards that answered.
"""

from __future__ import annotations

from concurrent import futures

from cranesched_tpu.fed.shardmap import ShardMap


class FanoutResult:
    """One scatter-gather round: per-shard replies + per-shard errors
    (a shard appears in exactly one of the two)."""

    def __init__(self):
        self.replies: dict[str, object] = {}
        self.errors: dict[str, str] = {}

    def __iter__(self):
        return iter(sorted(self.replies.items()))


def _read_addresses(spec) -> list[str]:
    """Follower-first dial order for the bounded-staleness read plane
    (the leader stays the write path and the freshness fallback)."""
    out = list(spec.followers)
    if spec.address:
        out.append(spec.address)
    return out


class FederatedClient:
    """One read client per shard, fanned out in parallel."""

    def __init__(self, shard_map: ShardMap, token: str = "",
                 tls=None, timeout: float = 30.0):
        from cranesched_tpu.rpc.client import make_client
        self.shard_map = shard_map
        self._clients = {
            name: make_client(_read_addresses(shard_map.spec(name)),
                              token=token, tls=tls, timeout=timeout)
            for name in shard_map.names()}
        self._pool = futures.ThreadPoolExecutor(
            max_workers=max(2, len(self._clients)))

    @classmethod
    def connect(cls, address, token: str = "", tls=None,
                timeout: float = 30.0) -> "FederatedClient | None":
        """Learn the shard map from any reachable ctld and build the
        fan-out client; None when the cluster is not federated."""
        from cranesched_tpu.rpc.client import make_client
        seed = make_client(address, token=token, tls=tls,
                           timeout=timeout)
        try:
            reply = seed.query_shard_map()
        finally:
            seed.close()
        if reply.error or not reply.shards:
            return None
        shard_map = ShardMap.from_doc([
            {"name": s.name, "partitions": list(s.partitions),
             "address": s.address, "followers": list(s.followers)}
            for s in reply.shards], epoch=reply.map_epoch)
        return cls(shard_map, token=token, tls=tls, timeout=timeout)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        for cli in self._clients.values():
            cli.close()

    # -- the fan-out core --

    def _each(self, fn) -> FanoutResult:
        res = FanoutResult()
        pending = {self._pool.submit(fn, cli): name
                   for name, cli in self._clients.items()}
        for fut in futures.as_completed(pending):
            name = pending[fut]
            try:
                res.replies[name] = fut.result()
            except Exception as exc:
                res.errors[name] = str(exc)
        return res

    # -- the read surface, one fan-out per CLI verb --

    def jobs(self, max_staleness: float = 0.0, **kw) -> FanoutResult:
        return self._each(
            lambda c: c.query_jobs(max_staleness=max_staleness, **kw))

    def cluster(self, max_staleness: float = 0.0) -> FanoutResult:
        return self._each(
            lambda c: c.query_cluster(max_staleness=max_staleness))

    def stats(self, max_staleness: float = 0.0) -> FanoutResult:
        return self._each(
            lambda c: c.query_stats(max_staleness=max_staleness))

    def summary(self, max_staleness: float = 0.0, **kw) -> FanoutResult:
        return self._each(
            lambda c: c.query_job_summary(max_staleness=max_staleness,
                                          **kw))

    def events(self, max_staleness: float = 0.0, **kw) -> FanoutResult:
        return self._each(
            lambda c: c.query_events(max_staleness=max_staleness, **kw))

    # -- elastic federation: map epochs, usage gossip, migration --

    def shard_maps(self) -> FanoutResult:
        """Each shard's OWN view of the routing table.  During a live
        migration the per-shard ``map_epoch`` values skew for a moment;
        cfed/cinfo surface them so an operator can see a flip settle."""
        return self._each(lambda c: c.query_shard_map())

    def map_epochs(self) -> dict[str, int]:
        """shard -> the map epoch it currently routes by (absent shards
        were unreachable)."""
        return {shard: reply.map_epoch
                for shard, reply in self.shard_maps()}

    def usage(self) -> FanoutResult:
        """Every shard's usage-gossip summary (cluster-wide accounting)."""
        return self._each(lambda c: c.fetch_usage())

    def migrate(self, partition: str, dest: str):
        """Drive a live migration: dial the partition's SOURCE shard —
        the source owns the four-phase protocol end to end."""
        source = self.shard_map.shard_for_partition(partition)
        if not source:
            raise ValueError(f"partition {partition!r} not in the "
                             f"shard map")
        if source not in self._clients:
            raise ValueError(f"no client for source shard {source!r}")
        return self._clients[source].migrate_partition(partition, dest)
