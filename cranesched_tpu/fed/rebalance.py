"""Elastic federation: hot-shard detection and the live partition
migration coordinator.

A federation's partition->shard assignment is chosen once, at config
time, against a guess about load.  When the guess goes stale one shard
saturates — submit latency climbs, its server lock stays held, SLO
budget burns — while its peers idle.  This module closes that loop:

:class:`HotShardDetector`
    Watches per-shard signals from the obs plane (submit p99, lock-held
    share, SLO burn rate) and latches a shard *hot* only after the
    signal sustains — with a hysteresis band and a post-migration
    cooldown so a flapping signal can never drive a migration storm.

:class:`MigrationCoordinator`
    Drives one partition handoff end to end over the four-phase WAL
    protocol on :class:`~cranesched_tpu.fed.shard.FedShardPlane`:

    1. **seal** the partition on the source (submits refuse, arbiter
       leases release, ``fed_migrate_begin`` durable),
    2. **export** the partition payload (nodes and placements by NAME),
    3. **import** on the destination — one WAL group creates every job
       under a fresh dest-local id (``fed_migrate_import``),
    4. **flip** the shard map: the successor map (epoch + 1) installs
       at the arbiter/routing layer; servers stamp the new epoch on
       replies and clients re-learn via the existing redirect-hint /
       ``learn_shard_map`` path,
    5. **commit** on the source (``fed_migrate_commit``): migrated jobs
       drop with no terminal stamps and the partition's nodes go dead.

    A source SIGKILL anywhere in flight is safe: recovery surfaces the
    bare ``fed_migrate_begin`` and :meth:`MigrationCoordinator.resolve`
    asks the destination ``has_import(mid)`` — adopted means commit,
    not adopted means abort.  Exactly one shard owns every job either
    way; the jobtrace ledger stays zero-lost / zero-doubled.

Endpoints are duck-typed "shard handles" (name -> object), the same
registry the :class:`~cranesched_tpu.fed.arbiter.PlacementArbiter`
uses: in-process wrappers in fed/sim.py, RPC clients in a deploy.  The
coordinator needs ``seal`` / ``export`` / ``import_`` / ``commit`` /
``abort`` / ``has_import`` / ``unresolved`` on them.
"""

from __future__ import annotations

import dataclasses

from cranesched_tpu.obs import REGISTRY as _OBS

_MET_MIGRATIONS = _OBS.counter(
    "crane_fed_migrations_total",
    "live partition migrations committed (source handed off)")
_MET_MIG_ABORTS = _OBS.counter(
    "crane_fed_migration_aborts_total",
    "live partition migrations aborted (handoff never adopted)")
_MET_MAP_EPOCH = _OBS.gauge(
    "crane_fed_map_epoch",
    "shard-map epoch this process currently routes by")
_MET_HOT = _OBS.gauge(
    "crane_fed_hot_shards",
    "shards currently latched hot by the rebalance detector")


@dataclasses.dataclass(frozen=True)
class DetectorConfig:
    """Thresholds and damping for :class:`HotShardDetector`.

    A shard samples *hot* when ANY signal crosses its hot threshold,
    and *cool* only when EVERY signal drops below ``cool_ratio`` times
    its threshold — the band between is the hysteresis dead zone:
    samples there neither extend a hot streak nor unlatch a hot shard.
    """

    submit_p99_hot_ms: float = 50.0   # submit latency p99
    lock_share_hot: float = 0.5       # fraction of wall time lock held
    slo_burn_hot: float = 1.0         # SLO burn rate (1.0 = at budget)
    cool_ratio: float = 0.6           # cool when below ratio*threshold
    sustain: int = 3                  # consecutive hot samples to latch
    cooldown_s: float = 300.0         # quiet period after a migration


class HotShardDetector:
    """Hysteresis-latched hot-shard detection over obs-plane samples.

    Push samples with :meth:`observe`; ask :meth:`decide` which shard
    (if any) warrants a migration.  Damping, in order:

    * **sustain**: ``sustain`` consecutive hot samples latch a shard —
      one spike never moves a partition;
    * **hysteresis**: once latched, only a genuinely *cool* sample
      unlatches; a flapping signal that dips into the dead zone and
      back keeps resetting the streak and never latches at all;
    * **cooldown**: after any migration the detector answers None for
      ``cooldown_s`` — back-to-back moves (thrash) are impossible by
      construction.

    Cold start (no samples) and a single-shard federation both decide
    None: there is nowhere to move load, so nothing is ever hot.
    """

    def __init__(self, config: DetectorConfig | None = None):
        self.config = config or DetectorConfig()
        self._streak: dict[str, int] = {}
        self._latched: dict[str, float] = {}  # shard -> latch time
        self._cooldown_until = float("-inf")

    def observe(self, shard: str, now: float,
                submit_p99_ms: float = 0.0,
                lock_held_share: float = 0.0,
                slo_burn: float = 0.0) -> bool:
        """Feed one sample; returns whether ``shard`` is latched hot."""
        cfg = self.config
        pairs = ((submit_p99_ms, cfg.submit_p99_hot_ms),
                 (lock_held_share, cfg.lock_share_hot),
                 (slo_burn, cfg.slo_burn_hot))
        hot = any(v >= lim for v, lim in pairs)
        cool = all(v < lim * cfg.cool_ratio for v, lim in pairs)
        if hot:
            self._streak[shard] = self._streak.get(shard, 0) + 1
            if (self._streak[shard] >= cfg.sustain
                    and shard not in self._latched):
                self._latched[shard] = now
                _MET_HOT.set(len(self._latched))
        else:
            self._streak[shard] = 0
            if cool and shard in self._latched:
                del self._latched[shard]
                _MET_HOT.set(len(self._latched))
        return shard in self._latched

    def decide(self, now: float, shards: list[str]) -> str | None:
        """The shard to unload, or None (cold start, single shard,
        cooldown, or nothing latched).  Ties break to the longest-hot
        shard — it has waited longest for relief."""
        if len(shards) < 2 or now < self._cooldown_until:
            return None
        latched = [s for s in shards if s in self._latched]
        if not latched:
            return None
        return min(latched, key=lambda s: (self._latched[s], s))

    def migrated(self, now: float) -> None:
        """A migration just ran: start the cooldown and drop every
        latch/streak — post-move load is a different regime and must
        re-earn its sustain from scratch."""
        self._cooldown_until = now + self.config.cooldown_s
        self._streak.clear()
        self._latched.clear()
        _MET_HOT.set(0)

    def stats(self) -> dict:
        return {"latched": sorted(self._latched),
                "cooldown_until": self._cooldown_until,
                "streaks": dict(self._streak)}


class MigrationCoordinator:
    """Drives live partition migrations over duck-typed shard handles.

    Holds the federation's current :class:`ShardMap` and installs
    successors through ``flip_map(new_map)`` — the caller's hook into
    wherever routing state lives (the sim's FederatedCluster, a real
    deployment's arbiter + servers).
    """

    def __init__(self, shard_map, handles: dict, flip_map):
        self.shard_map = shard_map
        self.handles = handles
        self.flip_map = flip_map
        #: migrations whose source died before acknowledging commit —
        #: :meth:`resolve` settles them after the source restarts
        self.pending_resolution: list[dict] = []
        _MET_MAP_EPOCH.set(shard_map.epoch)

    def migrate(self, partition: str, dest: str, now: float,
                on_exported=None) -> dict:
        """One full handoff of ``partition`` to shard ``dest``.

        ``on_exported(payload)`` is the chaos seam: it runs after the
        source's export, exactly where a source SIGKILL mid-handoff
        lands in the drills.  Returns a result doc; ``committed`` False
        means the source went down after the dest adopted — the jobs
        are safe on the dest and :meth:`resolve` finishes the paperwork
        when the source returns.
        """
        source = self.shard_map.shard_for_partition(partition)
        if not source:
            raise ValueError(f"partition {partition!r} not in the map")
        if dest == source:
            raise ValueError(f"partition {partition!r} already on "
                             f"{dest!r}")
        if dest not in self.shard_map.shards:
            raise ValueError(f"unknown destination shard {dest!r}")
        src_h = self.handles[source]
        dst_h = self.handles[dest]
        mid = (f"mig:{partition}:{self.shard_map.epoch}"
               f":{source}->{dest}")
        job_ids = src_h.seal(mid, partition, dest, now)
        payload = src_h.export(mid, partition)
        if on_exported is not None:
            on_exported(payload)
        jobs_imported = 0
        try:
            imported, _nodes = dst_h.import_(payload, now)
            jobs_imported = len(imported)
        except ValueError:
            # a structured refusal: the dest's two-phase import
            # validates and mallocs everything BEFORE its first WAL
            # write, so this genuinely means "not adopted" — annul
            # durably and re-open the partition where it is
            src_h.abort(mid, partition, now)
            _MET_MIG_ABORTS.inc()
            raise
        except Exception as exc:
            # the call died in flight — AMBIGUOUS: the dest may hold
            # the jobs durably (and a retried handle call may have
            # been the one that landed).  A blind abort here would
            # leave BOTH shards owning the jobs; ask the dest instead.
            try:
                adopted = bool(dst_h.has_import(mid))
            except Exception:
                adopted = None
            if adopted is None:
                # dest unreachable: the only safe move is none — the
                # partition stays sealed (no admits, no duplicates on
                # either side) and resolve() settles the begin later
                self.pending_resolution.append(
                    {"mid": mid, "partition": partition,
                     "source": source, "dest": dest,
                     "job_ids": list(job_ids)})
                raise RuntimeError(
                    f"dest {dest!r} unreachable after import ({exc}); "
                    f"partition {partition!r} stays sealed pending "
                    "resolution") from exc
            if not adopted:
                src_h.abort(mid, partition, now)
                _MET_MIG_ABORTS.inc()
                raise
            # adopted after all: fall through to flip + commit (the
            # exact dest-local ids live on the dest; the source only
            # needs the fact of adoption)
            jobs_imported = len(job_ids)
        # dest holds the jobs durably — the map may flip.  Flip BEFORE
        # the source commit: if the source dies in between, routing
        # already points at the shard that has the jobs, and resolve()
        # settles the source's begin record later.
        new_map = self.shard_map.with_partition_moved(partition, dest)
        self.flip_map(new_map)
        self.shard_map = new_map
        _MET_MAP_EPOCH.set(new_map.epoch)
        committed = True
        try:
            src_h.commit(mid, partition, now)
        except Exception:
            committed = False
            self.pending_resolution.append(
                {"mid": mid, "partition": partition, "source": source,
                 "dest": dest})
        _MET_MIGRATIONS.inc()
        return {"mid": mid, "partition": partition, "source": source,
                "dest": dest, "epoch": new_map.epoch,
                "jobs_sealed": len(job_ids),
                "jobs_imported": jobs_imported,
                "committed": committed}

    def resolve(self, source: str, now: float) -> list[dict]:
        """Settle ``source``'s unresolved begins (surfaced by its
        recovery, or queued here after an ambiguous import call): for
        each, ask the recorded dest whether the import happened —
        commit (the jobs live there; drop the source copies, and make
        sure the map routes to the dest first) or abort (they never
        left; unseal).  A dest that cannot ANSWER leaves its begin
        pending and the partition sealed — never guess: a blind abort
        against a dest that did adopt doubles every job."""
        src_h = self.handles[source]
        queued = [r for r in self.pending_resolution
                  if r["source"] == source]
        self.pending_resolution = [
            r for r in self.pending_resolution if r["source"] != source]
        seen = set()
        records = []
        for rec in list(src_h.unresolved()) + queued:
            if rec["mid"] in seen:
                continue
            seen.add(rec["mid"])
            records.append(rec)
        out = []
        for rec in records:
            dst_h = self.handles.get(rec.get("dest", ""))
            adopted = None
            if dst_h is not None:
                try:
                    adopted = bool(dst_h.has_import(rec["mid"]))
                except Exception:
                    adopted = None
            if adopted is True:
                if (self.shard_map.shard_for_partition(rec["partition"])
                        != rec["dest"]):
                    new_map = self.shard_map.with_partition_moved(
                        rec["partition"], rec["dest"])
                    self.flip_map(new_map)
                    self.shard_map = new_map
                    _MET_MAP_EPOCH.set(new_map.epoch)
                src_h.commit(rec["mid"], rec["partition"], now)
                out.append(dict(rec, resolution="commit"))
            elif adopted is False:
                src_h.abort(rec["mid"], rec["partition"], now)
                _MET_MIG_ABORTS.inc()
                out.append(dict(rec, resolution="abort"))
            else:
                self.pending_resolution.append(
                    dict(rec, source=source))
                out.append(dict(rec, resolution="pending"))
        return out
