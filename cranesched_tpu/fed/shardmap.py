"""Shard map: the static partition -> controller-shard routing table.

The federation is declared in the cluster YAML::

    Federation:
      ShardName: east            # identity of THIS controller process
      Shards:
        - name: east
          partitions: [batch, debug]
          address: 127.0.0.1:50051
          followers: [127.0.0.1:50061]
        - name: west
          partitions: [gpu]
          address: 127.0.0.1:50052

Partitions are owned by exactly one shard (disjoint by construction —
a partition listed twice is a config error).  The map is immutable at
runtime: resharding is a config change + rolling restart, exactly like
the node inventory.  Routing is therefore a pure dict lookup on both
the client and the server; a submit that lands on the wrong shard is
forwarded once and answered with a redirect hint so the client learns
(see rpc/server.py SubmitBatchJob and client.HaCtldClient).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """One controller shard: a name, its partitions, and where it
    listens (leader address first, then any HA followers that may
    serve bounded-staleness reads)."""

    name: str
    partitions: tuple[str, ...]
    address: str = ""
    followers: tuple[str, ...] = ()

    @property
    def addresses(self) -> tuple[str, ...]:
        """Leader address followed by follower addresses."""
        out = (self.address,) if self.address else ()
        return out + tuple(self.followers)


class ShardMap:
    """Immutable partition -> shard routing table."""

    def __init__(self, shards: list[ShardSpec]):
        if not shards:
            raise ValueError("Federation declared with no shards")
        self.shards: dict[str, ShardSpec] = {}
        self._by_partition: dict[str, str] = {}
        for spec in shards:
            if spec.name in self.shards:
                raise ValueError(f"duplicate shard {spec.name!r}")
            self.shards[spec.name] = spec
            for part in spec.partitions:
                owner = self._by_partition.setdefault(part, spec.name)
                if owner != spec.name:
                    raise ValueError(
                        f"partition {part!r} owned by both {owner!r} "
                        f"and {spec.name!r} (shards must be disjoint)")

    @classmethod
    def from_config(cls, section: dict) -> "ShardMap":
        """Parse the YAML ``Federation:`` section."""
        shards = []
        for entry in section.get("Shards", []) or []:
            shards.append(ShardSpec(
                name=str(entry["name"]),
                partitions=tuple(str(p) for p in
                                 entry.get("partitions", [])),
                address=str(entry.get("address", "") or ""),
                followers=tuple(str(a) for a in
                                entry.get("followers", []) or [])))
        return cls(shards)

    def shard_for_partition(self, partition: str) -> str:
        """Owning shard name, or '' for an unknown partition (the local
        scheduler then rejects it with its normal diagnostics)."""
        return self._by_partition.get(partition, "")

    def spec(self, name: str) -> ShardSpec | None:
        return self.shards.get(name)

    def names(self) -> list[str]:
        return sorted(self.shards)

    def partitions_of(self, name: str) -> tuple[str, ...]:
        spec = self.shards.get(name)
        return spec.partitions if spec else ()

    # -- wire form (QueryShardMap / ShardInfo) --

    def doc(self) -> list[dict]:
        """JSON-serializable shard list for the wire/CLI."""
        return [{"name": s.name, "partitions": list(s.partitions),
                 "address": s.address, "followers": list(s.followers)}
                for s in (self.shards[n] for n in self.names())]

    @classmethod
    def from_doc(cls, doc: list[dict]) -> "ShardMap":
        return cls([ShardSpec(
            name=str(e["name"]),
            partitions=tuple(str(p) for p in e.get("partitions", [])),
            address=str(e.get("address", "") or ""),
            followers=tuple(str(a) for a in e.get("followers", []) or []))
            for e in doc])

    def __len__(self) -> int:
        return len(self.shards)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"ShardMap({', '.join(f'{n}:{list(s.partitions)}' for n, s in sorted(self.shards.items()))})")
