"""Shard map: the versioned partition -> controller-shard routing table.

The federation is declared in the cluster YAML::

    Federation:
      ShardName: east            # identity of THIS controller process
      Shards:
        - name: east
          partitions: [batch, debug]
          address: 127.0.0.1:50051
          followers: [127.0.0.1:50061]
        - name: west
          partitions: [gpu]
          address: 127.0.0.1:50052

Partitions are owned by exactly one shard (disjoint by construction —
a partition listed twice is a config error, and so is a configured
partition no shard owns).  Each ShardMap *object* is immutable; the
table as a whole is versioned by ``epoch``: live partition migration
(fed/rebalance.py) produces a successor map via
:meth:`with_partition_moved` with ``epoch + 1`` and swaps it in
atomically at the arbiter.  Routing stays a pure dict lookup on both
the client and the server; a submit that lands on the wrong shard is
forwarded once and answered with a redirect hint so the client learns
(see rpc/server.py SubmitBatchJob and client.HaCtldClient).  Two
shards holding maps of different epochs redirect-bounce the client to
whichever shard the *owner's* map names — the one-hop-only rule keeps
a skewed pair from building a forwarding loop, exactly as it did when
the map was static.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """One controller shard: a name, its partitions, and where it
    listens (leader address first, then any HA followers that may
    serve bounded-staleness reads)."""

    name: str
    partitions: tuple[str, ...]
    address: str = ""
    followers: tuple[str, ...] = ()

    @property
    def addresses(self) -> tuple[str, ...]:
        """Leader address followed by follower addresses."""
        out = (self.address,) if self.address else ()
        return out + tuple(self.followers)


class ShardMap:
    """Immutable partition -> shard routing table, versioned by epoch.

    ``configured_partitions`` is the cluster's full partition inventory
    (the YAML ``Partitions:`` section): when given, a partition no
    shard owns is a config error — a federation that silently drops a
    partition routes its submits nowhere.
    """

    def __init__(self, shards: list[ShardSpec], epoch: int = 0,
                 configured_partitions: Iterable[str] | None = None):
        if not shards:
            raise ValueError("Federation declared with no shards")
        self.epoch = int(epoch)
        self.shards: dict[str, ShardSpec] = {}
        self._by_partition: dict[str, str] = {}
        for spec in shards:
            if spec.name in self.shards:
                raise ValueError(f"duplicate shard {spec.name!r}")
            self.shards[spec.name] = spec
            for part in spec.partitions:
                owner = self._by_partition.setdefault(part, spec.name)
                if owner != spec.name:
                    raise ValueError(
                        f"partition {part!r} owned by both {owner!r} "
                        f"and {spec.name!r} (shards must be disjoint)")
        if configured_partitions is not None:
            for part in sorted(set(configured_partitions)):
                if part not in self._by_partition:
                    raise ValueError(
                        f"partition {part!r} is configured but owned "
                        "by no shard (every partition needs exactly "
                        "one owner)")

    @classmethod
    def from_config(cls, section: dict,
                    configured_partitions: Iterable[str] | None = None
                    ) -> "ShardMap":
        """Parse the YAML ``Federation:`` section."""
        shards = []
        for entry in section.get("Shards", []) or []:
            shards.append(ShardSpec(
                name=str(entry["name"]),
                partitions=tuple(str(p) for p in
                                 entry.get("partitions", [])),
                address=str(entry.get("address", "") or ""),
                followers=tuple(str(a) for a in
                                entry.get("followers", []) or [])))
        return cls(shards, epoch=int(section.get("Epoch", 0) or 0),
                   configured_partitions=configured_partitions)

    def shard_for_partition(self, partition: str) -> str:
        """Owning shard name, or '' for an unknown partition (the local
        scheduler then rejects it with its normal diagnostics)."""
        return self._by_partition.get(partition, "")

    def spec(self, name: str) -> ShardSpec | None:
        return self.shards.get(name)

    def names(self) -> list[str]:
        return sorted(self.shards)

    def partitions_of(self, name: str) -> tuple[str, ...]:
        spec = self.shards.get(name)
        return spec.partitions if spec else ()

    # -- successor maps (live migration, fed/rebalance.py) --

    def with_partition_moved(self, partition: str,
                             to_shard: str) -> "ShardMap":
        """The successor map after migrating ``partition`` to
        ``to_shard``: same shards, ownership moved, ``epoch + 1``.
        Raises ValueError on an unknown partition/shard or a move to
        the current owner (a no-op migration must not burn an epoch)."""
        owner = self._by_partition.get(partition, "")
        if not owner:
            raise ValueError(f"partition {partition!r} not in the map")
        if to_shard not in self.shards:
            raise ValueError(f"unknown destination shard {to_shard!r}")
        if owner == to_shard:
            raise ValueError(
                f"partition {partition!r} already owned by {to_shard!r}")
        shards = []
        for name in self.names():
            spec = self.shards[name]
            parts = tuple(p for p in spec.partitions if p != partition)
            if name == to_shard:
                parts = parts + (partition,)
            shards.append(dataclasses.replace(spec, partitions=parts))
        return ShardMap(shards, epoch=self.epoch + 1)

    # -- wire form (QueryShardMap / ShardInfo) --

    def doc(self) -> list[dict]:
        """JSON-serializable shard list for the wire/CLI.  The map
        epoch travels beside this list (QueryShardMapReply.map_epoch,
        QueryStats ``fed.map_epoch``), not inside it — the list shape
        predates versioning and older readers must keep parsing it."""
        return [{"name": s.name, "partitions": list(s.partitions),
                 "address": s.address, "followers": list(s.followers)}
                for s in (self.shards[n] for n in self.names())]

    @classmethod
    def from_doc(cls, doc: list[dict], epoch: int = 0) -> "ShardMap":
        return cls([ShardSpec(
            name=str(e["name"]),
            partitions=tuple(str(p) for p in e.get("partitions", [])),
            address=str(e.get("address", "") or ""),
            followers=tuple(str(a) for a in e.get("followers", []) or []))
            for e in doc], epoch=epoch)

    def __len__(self) -> int:
        return len(self.shards)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"ShardMap(epoch={self.epoch}, "
                f"{', '.join(f'{n}:{list(s.partitions)}' for n, s in sorted(self.shards.items()))})")
