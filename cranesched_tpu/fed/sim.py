"""In-process federation harness: N controller shards + the placement
arbiter on one virtual clock.

Each :class:`SimShard` is a COMPLETE shard — its own MetaContainer,
JobScheduler, WAL, simulated node plane, and
:class:`~cranesched_tpu.fed.shard.FedShardPlane` — isolated exactly as
a separate ctld process would be: shards share nothing but the arbiter
handles and the shard map.  A lock per shard stands in for its RPC
server's; :class:`ShardHandle` takes it around every arbiter call.

Failure injection mirrors a SIGKILL, not a clean shutdown:
:meth:`SimShard.kill` abandons the scheduler mid-flight (the WAL file
keeps whatever was fsync'd, nothing is flushed on the way out) and
every subsequent handle call raises.  :meth:`SimShard.recover` rebuilds
the shard from its WAL alone — the same replay a restarted ctld runs —
then :meth:`FedShardPlane.recover` drops reserved-but-unconfirmed
leases.  Tests and the ``--federation`` replay assert the two-phase
invariant on top: a kill between reserve and confirm never loses a
placed job and never places one twice.
"""

from __future__ import annotations

import threading

from cranesched_tpu.craned.sim import SimCluster
from cranesched_tpu.ctld.defs import JobSpec
from cranesched_tpu.ctld.meta import MetaContainer
from cranesched_tpu.ctld.scheduler import JobScheduler, SchedulerConfig
from cranesched_tpu.ctld.wal import WriteAheadLog
from cranesched_tpu.fed.arbiter import GangRequest, PlacementArbiter
from cranesched_tpu.fed.rebalance import MigrationCoordinator
from cranesched_tpu.fed.shard import FedShardPlane
from cranesched_tpu.fed.shardmap import ShardMap, ShardSpec
from cranesched_tpu.fed.usage import UsageBook
from cranesched_tpu.ops.resources import ResourceLayout


class SimShard:
    """One in-process controller shard over disjoint partitions."""

    def __init__(self, name: str, partitions: dict[str, int],
                 cpu: float = 16.0, mem_gb: int = 64,
                 wal_path: str | None = None, config_kw=None,
                 global_limits=None, n_shards: int = 1,
                 publish_slack: int = 1, peers=()):
        self.name = name
        self.partitions = dict(partitions)
        self.cpu = cpu
        self.mem_gb = mem_gb
        self.wal_path = wal_path
        self.config_kw = dict(config_kw or {})
        self.global_limits = global_limits
        self.n_shards = n_shards
        self.publish_slack = publish_slack
        self.peers = tuple(peers)
        self.lock = threading.Lock()
        self.alive = True
        #: failure injection: die immediately after the NEXT successful
        #: lease (reserve durable, confirm never answered) — the
        #: arbiter's phase-two then hits a dead shard mid-gang
        self.crash_after_lease = False
        #: bare fed_migrate_begin records found by the last recovery —
        #: MigrationCoordinator.resolve settles them against the dest
        self.unresolved_migrations: list[dict] = []
        self._fresh_wal = True
        self._build(now=0.0, replayed=None)

    # -- construction / recovery --

    def _build(self, now: float, replayed, snap_fed=None) -> None:
        self.meta = MetaContainer(ResourceLayout())
        nid = 0
        # native partitions build in sorted order, ALWAYS — including
        # ones migrated away (their nodes go dead below, never absent),
        # so shard-local node ids stay stable across every recovery
        for part in sorted(self.partitions):
            for i in range(self.partitions[part]):
                self.meta.add_node(
                    f"{self.name}-{part}-n{i:04d}",
                    self.meta.layout.encode(
                        cpu=self.cpu, mem_bytes=self.mem_gb << 30,
                        memsw_bytes=self.mem_gb << 30,
                        is_capacity=True),
                    partitions=(part,))
                self.meta.craned_up(nid)
                nid += 1
        kw = dict(self.config_kw)
        kw.setdefault("job_trace", True)
        kw.setdefault("job_trace_capacity", 65536)
        self.scheduler = JobScheduler(self.meta, SchedulerConfig(**kw))
        # the fed plane attaches BEFORE recovery — prepare_recovery is
        # what rebuilds imported partitions' meta (in original adoption
        # order, so node ids renumber identically), filters committed
        # migrations' jobs out of the replay, and re-seals in-flight
        # partitions.  The production boot (ctld_main + ha/snapshot)
        # runs the same sequence.
        self.fed = FedShardPlane(self.scheduler, self.name)
        if self.global_limits is not None:
            # before recover: restored jobs must re-take their global
            # submit slots (fed/usage.py)
            self.scheduler.global_usage = UsageBook(
                self.name, self.global_limits, n_shards=self.n_shards,
                publish_slack=self.publish_slack,
                seq_source=lambda: (self.scheduler.wal.durable_seq
                                    if self.scheduler.wal is not None
                                    else 0),
                peers=self.peers)
        if replayed is not None:
            self.fed.prepare_recovery(self.wal_path, replayed,
                                      snap_fed=snap_fed)
            self.scheduler.recover(replayed, now)
        if self.wal_path is not None:
            if self._fresh_wal:
                open(self.wal_path, "w").close()
                self._fresh_wal = False
            self.scheduler.wal = WriteAheadLog(self.wal_path)
        self.sim = SimCluster(self.scheduler)
        self.sim.now = now
        self.sim.wire(self.scheduler)
        self.unresolved_migrations = []
        if replayed is not None:
            self.fed.recover(now)
            self.unresolved_migrations = self.fed.recover_migrations(now)
            # the craneds of a real shard still run the re-adopted
            # jobs; the simulated plane re-dispatches them instead
            for job in self.scheduler.running.values():
                self.sim.dispatch(job, job.node_ids)

    def kill(self) -> None:
        """SIGKILL analog: nothing is flushed or released — only what
        the WAL already fsync'd survives into :meth:`recover`."""
        self.alive = False

    def recover(self, now: float) -> None:
        """Restart from the local snapshot (if one exists beside the
        WAL — the same ``<wal>.snap`` the HA snapshotter writes) plus
        the WAL tail, or a full WAL replay otherwise.  The snapshot's
        ``fed`` document stands in for fed_migrate_* records that
        segment pruning dropped."""
        if self.wal_path is None:
            raise RuntimeError("recover needs a WAL-backed shard")
        from cranesched_tpu.ha.snapshot import (
            SnapshotStore,
            snapshot_to_replay,
        )
        doc = SnapshotStore(self.wal_path).load()
        snap_fed = None
        if doc is not None:
            replayed = snapshot_to_replay(doc)
            replayed.update(WriteAheadLog.replay(
                self.wal_path, after_seq=int(doc.get("seq", 0))))
            snap_fed = doc.get("fed")
        else:
            replayed = WriteAheadLog.replay(self.wal_path)
        self._build(now=now, replayed=replayed, snap_fed=snap_fed)
        if doc is not None:
            self.scheduler._next_job_id = max(
                self.scheduler._next_job_id,
                int(doc.get("next_job_id", 1)))
        self.alive = True

    # -- the local control surface (what the RPC handlers would do) --

    def submit(self, spec: JobSpec, now: float) -> int:
        if not self.alive:
            raise RuntimeError(f"shard {self.name} is down")
        with self.lock:
            return self.scheduler.submit(spec, now)

    def tick(self, now: float) -> list[int]:
        """One scheduling cycle at virtual time ``now``."""
        if not self.alive:
            return []
        with self.lock:
            self.sim.advance_to(now)
            self.fed.expire(now)
            return self.scheduler.schedule_cycle(now)

    def drained(self) -> bool:
        return (not self.alive
                or (not self.scheduler.pending
                    and not self.scheduler.running))


class ShardHandle:
    """Arbiter-side handle over one :class:`SimShard` — the in-process
    equivalent of the LeaseNodes/ConfirmGang/ReleaseLease RPC client,
    including its failure mode (a dead shard raises)."""

    def __init__(self, shard: SimShard):
        self.shard = shard

    def _check(self) -> None:
        if not self.shard.alive:
            raise RuntimeError(f"shard {self.shard.name} unreachable")

    def _req(self, spec: JobSpec):
        return spec.res.encode(self.shard.meta.layout)

    def free_count(self, partition: str, spec: JobSpec) -> int:
        self._check()
        with self.shard.lock:
            return self.shard.fed.free_count(partition, self._req(spec))

    def lease(self, lease_id: str, partition: str, count: int,
              spec: JobSpec, ttl: float, now: float):
        self._check()
        with self.shard.lock:
            out = self.shard.fed.lease_nodes(
                lease_id, partition, count, self._req(spec), ttl, now)
        if self.shard.crash_after_lease:
            # one-shot: the reserve IS durable — the kill lands after
            # the WAL fsync but before any confirm can be served
            self.shard.crash_after_lease = False
            self.shard.kill()
        return out

    def confirm(self, lease_id: str, gang_id: str, spec: JobSpec,
                node_names, now: float, epoch: int = 0) -> int:
        self._check()
        with self.shard.lock:
            return self.shard.fed.confirm_gang(
                lease_id, gang_id, spec, list(node_names), now,
                epoch=epoch)

    def release(self, lease_id: str, now: float) -> bool:
        self._check()
        with self.shard.lock:
            return self.shard.fed.release_lease(lease_id, now)

    def cancel(self, job_id: int, now: float) -> bool:
        self._check()
        with self.shard.lock:
            return self.shard.scheduler.cancel(job_id, now)

    # -- the migration surface (MigrationCoordinator endpoints) --

    def seal(self, mid: str, partition: str, dest: str,
             now: float) -> list[int]:
        self._check()
        with self.shard.lock:
            return self.shard.fed.seal_partition(mid, partition, dest,
                                                 now)

    def export(self, mid: str, partition: str) -> dict:
        self._check()
        with self.shard.lock:
            return self.shard.fed.export_partition(mid, partition)

    def import_(self, payload: dict, now: float):
        self._check()
        with self.shard.lock:
            imported, new_nodes = self.shard.fed.import_partition(
                payload, now)
            # the simulated node plane must mirror the adopted meta:
            # craneds for the new nodes, re-dispatch for the running
            # jobs (their physical tasks never stopped — a real craned
            # re-registers; the sim re-arms their completions)
            from cranesched_tpu.craned.sim import SimCraned
            for nid in new_nodes:
                self.shard.sim.craneds.setdefault(nid, SimCraned(nid))
            for jid in imported:
                job = self.shard.scheduler.running.get(jid)
                if job is not None:
                    self.shard.sim.dispatch(job, job.node_ids)
            return imported, new_nodes

    def commit(self, mid: str, partition: str, now: float) -> list[int]:
        self._check()
        with self.shard.lock:
            return self.shard.fed.commit_migration(mid, partition, now)

    def abort(self, mid: str, partition: str, now: float) -> None:
        self._check()
        with self.shard.lock:
            self.shard.fed.abort_migration(mid, partition, now)

    def has_import(self, mid: str) -> bool:
        self._check()
        with self.shard.lock:
            return self.shard.fed.has_import(mid)

    def unresolved(self) -> list[dict]:
        self._check()
        out = self.shard.unresolved_migrations
        self.shard.unresolved_migrations = []
        return out


class FederatedCluster:
    """N shards + one arbiter on a shared virtual clock.

    ``shards`` maps shard name -> {partition -> node count}.  Submits
    route by partition through the shard map (exactly the lookup the
    RPC layer does); cross-partition gangs go through the arbiter."""

    def __init__(self, shards: dict[str, dict[str, int]],
                 cpu: float = 16.0, mem_gb: int = 64,
                 wal_dir: str | None = None, config_kw=None,
                 global_limits=None, publish_slack: int = 1):
        self.shards: dict[str, SimShard] = {}
        specs = []
        for name in sorted(shards):
            wal_path = (f"{wal_dir}/{name}.wal"
                        if wal_dir is not None else None)
            self.shards[name] = SimShard(
                name, shards[name], cpu=cpu, mem_gb=mem_gb,
                wal_path=wal_path, config_kw=config_kw,
                global_limits=global_limits, n_shards=len(shards),
                publish_slack=publish_slack,
                peers=tuple(p for p in sorted(shards) if p != name))
            specs.append(ShardSpec(
                name=name,
                partitions=tuple(sorted(shards[name]))))
        self.shard_map = ShardMap(specs)
        self.handles = {name: ShardHandle(s)
                        for name, s in self.shards.items()}
        self.arbiter = PlacementArbiter(self.shard_map, self.handles)
        self.coordinator = MigrationCoordinator(
            self.shard_map, self.handles, self._install_map)
        self.now = 0.0

    # -- routing --

    def shard_for(self, partition: str) -> SimShard | None:
        name = self.shard_map.shard_for_partition(partition)
        return self.shards.get(name)

    def submit(self, spec: JobSpec, now: float | None = None
               ) -> tuple[str, int]:
        """Route a partition-local submit; returns (shard, job_id)."""
        shard = self.shard_for(spec.partition)
        if shard is None:
            raise ValueError(f"no shard owns partition "
                             f"{spec.partition!r}")
        return shard.name, shard.submit(
            spec, self.now if now is None else now)

    def submit_gang(self, gang: GangRequest) -> str:
        return self.arbiter.submit_gang(gang)

    # -- the clock --

    def tick(self, now: float | None = None) -> int:
        """Advance every live shard one cycle, then pump the arbiter.
        Returns the number of jobs started across the federation."""
        self.now = self.now + 1.0 if now is None else now
        started = 0
        for shard in self.shards.values():
            started += len(shard.tick(self.now))
        started += sum(
            len(self.arbiter.committed[gid])
            for gid in self.arbiter.pump(self.now))
        return started

    def run_until_drained(self, max_cycles: int = 100_000) -> float:
        """Alternate ticks until every live shard drained and the
        arbiter queue is empty (virtual clock, like the single-cluster
        ``SimCluster.run_until_drained``)."""
        for _ in range(max_cycles):
            self.tick()
            if self.arbiter.queue:
                continue
            if all(s.drained() for s in self.shards.values()):
                return self.now
        return self.now

    # -- live partition migration / cluster-wide accounting --

    def _install_map(self, new_map: ShardMap) -> None:
        """The coordinator's flip hook: routing and the arbiter adopt
        the successor map in one assignment each — every later lookup
        (submit routing, gang planning) sees the new owner."""
        self.shard_map = new_map
        self.arbiter.shard_map = new_map

    def migrate(self, partition: str, dest: str,
                on_exported=None) -> dict:
        """Drive one live partition migration at the current virtual
        time (see MigrationCoordinator.migrate; ``on_exported`` is the
        chaos seam where a source SIGKILL lands)."""
        return self.coordinator.migrate(partition, dest, self.now,
                                        on_exported=on_exported)

    def resolve_migrations(self, source: str) -> list[dict]:
        """Settle a restarted source's in-flight handoffs."""
        return self.coordinator.resolve(source, self.now)

    def pump_usage(self, now: float | None = None) -> int:
        """One gossip round: every live shard PULLS every live peer's
        summary, exactly as the RPC loop does — each pull publishes
        with the puller's name, so the publisher marks its counters
        delivered to that peer (per-peer acks are what release the
        publish-slack throttle; a dead peer withholds its ack and the
        publisher's own admissions tighten instead of overshooting).
        Returns the number of documents exchanged.  Call cadence IS
        the staleness bound — every tick approximates staleness 0,
        sparser pumping exercises the conservative slack
        (fed/usage.py)."""
        now = self.now if now is None else now
        exchanged = 0
        names = sorted(self.shards)
        for dst_name in names:
            dst = self.shards[dst_name]
            dbook = dst.scheduler.global_usage
            if not dst.alive or dbook is None:
                continue
            for src_name in names:
                if src_name == dst_name:
                    continue
                src = self.shards[src_name]
                sbook = src.scheduler.global_usage
                if not src.alive or sbook is None:
                    continue
                with src.lock:
                    doc = sbook.publish(now, peer=dst_name)
                with dst.lock:
                    dbook.ingest(doc, now)
                exchanged += 1
        return exchanged

    # -- failure injection / audit --

    def kill(self, name: str) -> None:
        self.shards[name].kill()

    def recover(self, name: str, now: float | None = None) -> None:
        self.shards[name].recover(self.now if now is None else now)
        # the rebuilt FedShardPlane is a new object — rebind the handle
        self.handles[name].shard = self.shards[name]

    def ledger(self) -> dict:
        """Cross-shard lost/doubled audit from each shard's jobtrace
        ledger, keyed by shard."""
        out = {"lost": 0, "doubled": 0, "checked": 0, "shards": {}}
        for name, shard in self.shards.items():
            sched = shard.scheduler
            ids = sorted(set(sched.history) | set(sched.running)
                         | set(sched.pending))
            doc = (sched.jobtrace.ledger(ids)
                   if sched.jobtrace is not None else
                   {"lost": 0, "doubled": 0, "checked": 0})
            out["shards"][name] = doc
            out["lost"] += (doc["lost"] if isinstance(doc["lost"], int)
                            else len(doc["lost"]))
            out["doubled"] += (doc["doubled"]
                               if isinstance(doc["doubled"], int)
                               else len(doc["doubled"]))
            out["checked"] += doc["checked"]
        return out

    def ledger_by_name(self, names) -> dict:
        """Exactly-once audit ACROSS shards, keyed by job NAME (ids are
        shard-local and change when a job migrates): every submitted
        name must reach exactly one terminal state federation-wide.
        ``lost`` = names with no terminal anywhere, ``doubled`` = names
        terminal on more than one job."""
        ends: dict[str, int] = {}
        live: dict[str, int] = {}
        for shard in self.shards.values():
            sched = shard.scheduler
            for job in sched.history.values():
                if job.status.is_terminal:
                    ends[job.spec.name] = ends.get(job.spec.name, 0) + 1
            for store in (sched.pending, sched.running):
                for job in store.values():
                    live[job.spec.name] = live.get(job.spec.name, 0) + 1
        names = list(names)
        return {
            "checked": len(names),
            "lost": [n for n in names
                     if not ends.get(n) and not live.get(n)],
            "doubled": [n for n in names
                        if ends.get(n, 0) + live.get(n, 0) > 1],
            "still_live": [n for n in names if live.get(n)],
        }

    def stats(self) -> dict:
        return {
            "now": self.now,
            "arbiter": dict(self.arbiter.stats),
            "shards": {
                name: {
                    "alive": s.alive,
                    "pending": len(s.scheduler.pending),
                    "running": len(s.scheduler.running),
                    "finished": len(s.scheduler.history),
                    "leases": len(s.fed.leases),
                } for name, s in self.shards.items()},
        }
