"""Cluster-wide accounting: per-user/per-account usage summaries
gossiped between shards so global MaxJobs/MaxSubmitJobs and fair-share
hold across the federation under a bounded-staleness contract.

The reference enforces these limits in ONE AccountMetaContainer behind
striped locks (AccountMetaContainer.h:70-265) — trivially globally
consistent, trivially a scaling wall.  Sharded, each controller owns
only its partitions' jobs, so a per-user limit needs the *other*
shards' counts.  This module is the shard-local half of that exchange:

:class:`UsageBook`
    One per shard.  Counts the shard's own live jobs (running) and
    submit slots (pending + running) per user and per account,
    publishes them as a ``durable_seq``-stamped document
    (FetchUsage / the sim's gossip pump), ingests the other shards'
    documents, and answers the conservative admission question.

**The soundness contract.**  Remote counts are stale by up to the
gossip interval; a naive ``local + remote < L`` check would overshoot
L by however many admissions every other shard performed since it last
published.  The book therefore enforces two rules:

1. *Publish throttle*: a shard that has admitted ``publish_slack``
   (B) jobs beyond what its slowest peer has CONFIRMED receiving
   stops admitting until that peer pulls again.  Delivery is what
   counts, not the act of building a summary document: each admission
   bumps a monotone ``_admitted`` counter, and a peer's successful
   pull (FetchUsage with its shard name / the sim's pump) records an
   ack at the current counter.  The throttle gates on
   ``_admitted - min(peer acks)`` — so for ANY observer, at every
   instant, ``true_remote <= known_remote + (S-1)*B``.  (A book built
   without a ``peers`` roster falls back to acking on publish itself —
   the single-consumer sim/unit-test shape.)
2. *Conservative gate*: admit only while
   ``local + known_remote + 1 <= L - (S-1)*B``.

Together: the cluster-wide count can NEVER exceed L — the documented
overshoot bound is zero; staleness converts into early (conservative)
denials of at most ``(S-1)*B`` slots, never into an overshoot.  A peer
that stops pulling freezes its ack, so this shard stops admitting at
B-beyond-acked instead of silently outrunning what that peer knows.
Decrements (job finish) travelling late only make ``known_remote`` an
over-estimate, which again errs toward denial.  With ``B = 0`` the
operator promises synchronous publishing (publish after every
admission before the next admission anywhere — staleness 0); the gate
then has zero slack and admits exactly the set a single controller
would: bit-exact against the single-container oracle.

Fair-share rides the same documents: per-account running-job counts
feed the priority model's service sum (models/priority.py
``extra_service``) so an account burning capacity on another shard
sinks in the local queue too.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from cranesched_tpu.ctld.accounting import UNLIMITED
from cranesched_tpu.obs import REGISTRY as _OBS

_MET_STALENESS = _OBS.gauge(
    "crane_fed_usage_staleness_seconds",
    "age of the oldest remote usage summary this shard holds")
_MET_DENIED = _OBS.counter(
    "crane_fed_usage_denied_total",
    "submissions denied by the conservative global-limit gate")
_MET_PUBLISH = _OBS.counter(
    "crane_fed_usage_publish_total",
    "usage summaries published by this shard")


@dataclasses.dataclass(frozen=True)
class GlobalLimits:
    """Federation-wide limits (YAML ``Federation: Limits:``).  These
    bound the CLUSTER total per user/account — the per-shard QoS
    limits (ctld/accounting.py) still apply on top, per shard."""

    max_jobs_per_user: int = UNLIMITED
    max_submit_jobs_per_user: int = UNLIMITED
    max_jobs_per_account: int = UNLIMITED
    max_submit_jobs_per_account: int = UNLIMITED

    @classmethod
    def from_config(cls, section: dict) -> "GlobalLimits":
        def _lim(key):
            v = section.get(key)
            return UNLIMITED if v in (None, "", 0) else int(v)
        return cls(
            max_jobs_per_user=_lim("MaxJobsPerUser"),
            max_submit_jobs_per_user=_lim("MaxSubmitJobsPerUser"),
            max_jobs_per_account=_lim("MaxJobsPerAccount"),
            max_submit_jobs_per_account=_lim("MaxSubmitJobsPerAccount"))

    @property
    def any_set(self) -> bool:
        return any(v != UNLIMITED for v in (
            self.max_jobs_per_user, self.max_submit_jobs_per_user,
            self.max_jobs_per_account,
            self.max_submit_jobs_per_account))


@dataclasses.dataclass
class _Counts:
    jobs: int = 0          # running
    submit_jobs: int = 0   # pending + running
    # run slots admitted this cycle but not yet in the running dict:
    # the scheduler's batch commit checks every candidate BEFORE any
    # insert, so without reservations one cycle could blow through the
    # global cap (N admissions each seeing jobs=0)
    reserved: int = 0


class UsageBook:
    """One shard's view of federation-wide usage.

    ``seq_source`` supplies the shard's WAL ``durable_seq`` for
    stamping published documents — a reader can order two summaries
    from the same shard and a bounded-staleness client can refuse one
    that is too old, mirroring the query plane's contract.
    """

    def __init__(self, shard: str, limits: GlobalLimits | None = None,
                 n_shards: int = 1, publish_slack: int = 1,
                 seq_source: Callable[[], int] | None = None,
                 peers: tuple = ()):
        self.shard = shard
        self.limits = limits or GlobalLimits()
        self.n_shards = max(int(n_shards), 1)
        if publish_slack < 0:
            raise ValueError("publish_slack must be >= 0")
        self.publish_slack = int(publish_slack)
        self.seq_source = seq_source
        self._user: dict[str, _Counts] = {}
        self._acct: dict[str, _Counts] = {}
        # shard -> its last published doc (ingested verbatim)
        self._remote: dict[str, dict] = {}
        self._remote_at: dict[str, float] = {}  # local receive time
        # delivery-confirmed throttle state: admissions are a monotone
        # counter, each peer acks the counter value it has seen (its
        # last successful pull), and the throttle gates on the SLOWEST
        # peer's lag — never on the act of building a document
        self.peers = tuple(p for p in peers if p and p != shard)
        self._admitted = 0
        self._peer_acked: dict[str, int] = {p: 0 for p in self.peers}
        # no-roster fallback (direct construction in unit tests / the
        # single-consumer sim): publish() itself counts as delivery
        self._published_floor = 0
        self.denied = 0

    # ---- local bookkeeping (scheduler hooks) ----

    def _c(self, table: dict, key: str) -> _Counts:
        c = table.get(key)
        if c is None:
            c = table[key] = _Counts()
        return c

    def note_submit(self, user: str, account: str) -> None:
        """A submit slot was taken locally (admission already passed —
        recovery/migration restores call this without re-checking)."""
        self._c(self._user, user).submit_jobs += 1
        if account:
            self._c(self._acct, account).submit_jobs += 1
        self._admitted += 1

    def note_release_submit(self, user: str, account: str) -> None:
        u = self._user.get(user)
        if u is not None and u.submit_jobs > 0:
            u.submit_jobs -= 1
        a = self._acct.get(account) if account else None
        if a is not None and a.submit_jobs > 0:
            a.submit_jobs -= 1

    def note_run(self, user: str, account: str, delta: int) -> None:
        """A job entered (+1) or left (-1) the running set locally."""
        u = self._c(self._user, user)
        u.jobs = max(u.jobs + delta, 0)
        if account:
            a = self._c(self._acct, account)
            a.jobs = max(a.jobs + delta, 0)
        if delta > 0:
            self._admitted += delta

    def reserve_run(self, user: str, account: str) -> None:
        """Hold a run slot between admission and the running-dict
        insert (same cycle, same lock).  The insert converts it via
        :meth:`unreserve_run` + :meth:`note_run`; an admission that
        fails to commit releases it through the scheduler's symmetric
        free path."""
        self._c(self._user, user).reserved += 1
        if account:
            self._c(self._acct, account).reserved += 1

    def unreserve_run(self, user: str, account: str) -> None:
        u = self._user.get(user)
        if u is not None and u.reserved > 0:
            u.reserved -= 1
        a = self._acct.get(account) if account else None
        if a is not None and a.reserved > 0:
            a.reserved -= 1

    # ---- the conservative admission gate ----

    def _slack(self) -> int:
        return (self.n_shards - 1) * self.publish_slack

    def unconfirmed(self) -> int:
        """Admissions the slowest consumer has NOT confirmed seeing —
        the quantity the publish throttle bounds at ``publish_slack``.
        With a peer roster this is the monotone admission counter minus
        the minimum per-peer ack; without one (no ``peers`` given),
        admissions since the last :meth:`publish`."""
        if self._peer_acked:
            return self._admitted - min(self._peer_acked.values())
        return self._admitted - self._published_floor

    def _remote_sum(self, table: str, key: str, field: str) -> int:
        total = 0
        for doc in self._remote.values():
            entry = doc.get(table, {}).get(key)
            if entry:
                total += int(entry.get(field, 0))
        return total

    def check_submit(self, user: str, account: str) -> str:
        """'' when a new submit may be admitted under the global
        MaxSubmitJobs limits, else the refusal reason.  Does NOT take
        the slot — call :meth:`note_submit` after the local admission
        actually happens (the caller holds the shard lock, so the
        check-then-take pair cannot race locally)."""
        lim = self.limits
        if not lim.any_set:
            return ""
        if (self.publish_slack > 0
                and self.unconfirmed() >= self.publish_slack):
            # rule 1: our own count is about to outrun what the
            # slowest peer has CONFIRMED knowing about us — hold
            # admissions until it pulls again
            self.denied += 1
            _MET_DENIED.inc()
            return ("global limit gate: usage publish overdue "
                    f"({self.unconfirmed()} unconfirmed admissions)")
        slack = self._slack()
        checks = [("user", user, lim.max_submit_jobs_per_user,
                   "global MaxSubmitJobsPerUser")]
        if account:
            checks.append(("acct", account,
                           lim.max_submit_jobs_per_account,
                           "global MaxSubmitJobsPerAccount"))
        for table, key, limit, label in checks:
            if limit == UNLIMITED:
                continue
            local = (self._user if table == "user" else
                     self._acct).get(key)
            known = ((local.submit_jobs if local else 0)
                     + self._remote_sum(table, key, "submit_jobs"))
            if known + 1 > limit - slack:
                self.denied += 1
                _MET_DENIED.inc()
                return (f"{label} reached "
                        f"({known}/{limit}, staleness slack {slack})")
        return ""

    def check_run(self, user: str, account: str) -> str:
        """'' when one more RUNNING job fits under the global MaxJobs
        limits (the schedule-commit gate), else the reason."""
        lim = self.limits
        if not lim.any_set:
            return ""
        if (self.publish_slack > 0
                and self.unconfirmed() >= self.publish_slack):
            self.denied += 1
            _MET_DENIED.inc()
            return "global limit gate: usage publish overdue"
        slack = self._slack()
        checks = [("user", user, lim.max_jobs_per_user,
                   "global MaxJobsPerUser")]
        if account:
            checks.append(("acct", account, lim.max_jobs_per_account,
                           "global MaxJobsPerAccount"))
        for table, key, limit, label in checks:
            if limit == UNLIMITED:
                continue
            local = (self._user if table == "user" else
                     self._acct).get(key)
            known = ((local.jobs + local.reserved if local else 0)
                     + self._remote_sum(table, key, "jobs"))
            if known + 1 > limit - slack:
                self.denied += 1
                _MET_DENIED.inc()
                return (f"{label} reached "
                        f"({known}/{limit}, staleness slack {slack})")
        return ""

    # ---- the gossip wire (FetchUsage / the sim's pump) ----

    def publish(self, now: float, peer: str = "") -> dict:
        """This shard's usage summary, durable_seq-stamped.

        ``peer`` names the shard this document is being DELIVERED to
        (the FetchUsage handler passes the puller's shard name, under
        the same lock that built the document): that peer's throttle
        ack advances to the current admission counter — the counts
        below are exactly what it will know about us.  An anonymous
        publish (CLI inspection, ``peer=""``) releases nothing, unless
        the book has no peer roster at all (the no-roster fallback
        treats any publish as the one consumer's delivery)."""
        doc = {
            "shard": self.shard,
            "time": now,
            "durable_seq": (self.seq_source() if self.seq_source
                            else 0),
            "user": {u: {"jobs": c.jobs, "submit_jobs": c.submit_jobs}
                     for u, c in sorted(self._user.items())
                     if c.jobs or c.submit_jobs},
            "acct": {a: {"jobs": c.jobs, "submit_jobs": c.submit_jobs}
                     for a, c in sorted(self._acct.items())
                     if c.jobs or c.submit_jobs},
        }
        if peer and peer in self._peer_acked:
            self._peer_acked[peer] = self._admitted
        elif not self._peer_acked:
            self._published_floor = self._admitted
        _MET_PUBLISH.inc()
        return doc

    def ingest(self, doc: dict, now: float) -> None:
        """Adopt another shard's summary.  Last-writer-wins per shard,
        ordered by durable_seq — a re-delivered older summary must not
        roll the view backwards."""
        shard = str(doc.get("shard", ""))
        if not shard or shard == self.shard:
            return
        prev = self._remote.get(shard)
        if prev is not None and int(prev.get("durable_seq", 0)) > int(
                doc.get("durable_seq", 0)):
            return
        self._remote[shard] = doc
        self._remote_at[shard] = now
        _MET_STALENESS.set(self.staleness(now), shard=self.shard)

    def forget(self, shard: str) -> None:
        """Drop a departed shard's summary (map shrink) — and its
        throttle ack, so a removed peer cannot freeze admissions
        forever."""
        self._remote.pop(shard, None)
        self._remote_at.pop(shard, None)
        self._peer_acked.pop(shard, None)

    def staleness(self, now: float) -> float:
        """Age of the OLDEST remote summary held; 0 with no remotes
        (single shard == nothing to be stale about)."""
        if not self._remote_at:
            return 0.0
        return max(0.0, now - min(self._remote_at.values()))

    # ---- fair-share input (models/priority.py extra_service) ----

    def remote_account_jobs(self) -> dict[str, int]:
        """Per-account running-job counts summed over the remote
        summaries — the cluster-wide service signal for the fair-share
        factor.  Counts, not TRES-seconds: a cross-shard approximation
        that is monotone in remote load, which is all the normalized
        fair-share factor consumes."""
        out: dict[str, int] = {}
        for doc in self._remote.values():
            for acct, entry in doc.get("acct", {}).items():
                jobs = int(entry.get("jobs", 0))
                if jobs:
                    out[acct] = out.get(acct, 0) + jobs
        return out

    def stats(self) -> dict:
        return {
            "shard": self.shard,
            "unpublished": self.unconfirmed(),
            "admitted": self._admitted,
            "peer_acked": dict(self._peer_acked),
            "remotes": sorted(self._remote),
            "denied": self.denied,
            "users": {u: dataclasses.asdict(c)
                      for u, c in sorted(self._user.items())},
        }


def effective_publish_slack(limits: GlobalLimits, n_shards: int,
                            slack: int) -> tuple[int, int]:
    """Clamp ``slack`` so the conservative gate stays satisfiable.

    The gate admits only while ``known + 1 <= L - (S-1)*B``; a
    configured B with ``(S-1)*B >= L`` for any finite global limit L
    would deny EVERY admission forever, even on an idle cluster.  The
    largest satisfiable B leaves at least one admissible slot under
    the smallest limit: ``B <= (L_min - 1) // (S - 1)``.

    Returns ``(effective, configured)`` — ``effective < configured``
    means the caller should warn loudly that staleness tolerance was
    reduced to keep the limits reachable."""
    slack = max(int(slack), 0)
    finite = [v for v in (limits.max_jobs_per_user,
                          limits.max_submit_jobs_per_user,
                          limits.max_jobs_per_account,
                          limits.max_submit_jobs_per_account)
              if v != UNLIMITED]
    if not finite or n_shards < 2 or slack == 0:
        return slack, slack
    max_ok = max((min(finite) - 1) // (n_shards - 1), 0)
    return min(slack, max_ok), slack
