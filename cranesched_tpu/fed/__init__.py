"""Federated control plane: partition-sharded schedulers, a placement
arbiter for cross-partition gangs, and a bounded-staleness scatter-
gather query plane (ISSUE 15, ROADMAP open item #2).

One logical cluster is split across controller *shards*.  Each shard is
a full ctld — its own :class:`~cranesched_tpu.ctld.scheduler.JobScheduler`,
pending table, and WAL — over a disjoint set of partitions, so submit
ingest, accounting checks, and WAL fsyncs scale horizontally.  The only
cross-shard authority is the :class:`~cranesched_tpu.fed.arbiter.
PlacementArbiter`, which owns cross-partition gang jobs and commits
them through two-phase reserve/confirm records in each shard's WAL
under that shard's fencing epoch.

Modules:

* :mod:`.shardmap`  — the static partition→shard routing table (YAML
  ``Federation:`` section).
* :mod:`.shard`     — the per-shard lease plane grafted onto a local
  JobScheduler (reserve / confirm / release / expire / recover).
* :mod:`.arbiter`   — the cross-partition gang coordinator.
* :mod:`.query`     — scatter-gather fan-out with the ``max_staleness``
  read contract.
* :mod:`.sim`       — an in-process federated cluster harness for the
  replay drill and the fed test lane.
"""

from cranesched_tpu.fed.shardmap import ShardMap, ShardSpec

__all__ = ["ShardMap", "ShardSpec"]
