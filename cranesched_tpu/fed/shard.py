"""Per-shard lease plane: the shard-side half of the arbiter's
two-phase cross-partition gang commit.

A shard owns its partitions outright — local jobs schedule through the
normal cycle with no federation awareness.  The only cross-shard
authority is the placement arbiter, and its entire contract with a
shard is three operations grafted onto the local ``JobScheduler`` here:

``lease_nodes``
    Reserve concrete nodes for an arbiter solve.  The reservation is a
    durable ``fed_reserve`` WAL record plus ``NodeMeta.fed_leased``
    flags; the flag folds into ``schedulable``, so leased nodes vanish
    from the local snapshot AND fail local mallocs — a shard cycle can
    never race the arbiter onto a leased node.  The lease carries the
    shard's CURRENT fencing epoch and a TTL: a dead arbiter's leases
    self-expire, and a confirm under a stale epoch is refused (the
    dispatch-ring fencing discipline, reused).

``confirm_gang``
    Turn one lease into a RUNNING local gang member.  The member is a
    normal local job (submitted, committed, WAL'd, dispatched through
    the ordinary dispatch ring) created inside one WAL group together
    with the ``fed_confirm`` record — the ONLY record that creates a
    job.  A crash before the group's fsync leaves a bare reserve, which
    recovery drops; a crash after leaves the job durable exactly once.
    Never double-placed, never half-placed.

``release_lease``
    Drop an unconfirmed reservation (arbiter abort, TTL expiry, or
    recovery finding a reserve without a confirm).

Recovery: :meth:`recover` replays ``fed_*`` records after the normal
job replay.  Leases whose last record is ``fed_reserve`` are released
(durable ``fed_release`` tombstone) — their gang was never committed
here, and the arbiter's own retry logic re-places it from scratch.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cranesched_tpu.ctld.defs import JobSpec, JobStatus, PendingReason
from cranesched_tpu.ctld.meta import ResReduceEvent
from cranesched_tpu.obs import REGISTRY as _OBS

_MET_LEASES = _OBS.counter(
    "crane_fed_leases_granted_total",
    "arbiter node leases granted by this shard")
_MET_REVOKED = _OBS.counter(
    "crane_fed_leases_revoked_total",
    "arbiter node leases released, expired, or dropped by recovery")


@dataclasses.dataclass
class Lease:
    """One live reservation: which nodes, under which fencing epoch,
    and when the shard self-expires it."""

    lease_id: str
    partition: str
    node_ids: list[int]
    epoch: int
    deadline: float
    seq: int = 0  # the fed_reserve record's WAL seq
    reserved_at: float = 0.0  # when granted (the arbiter_reserve span)


class FedShardPlane:
    """Lease/confirm/release surface grafted onto one shard's
    JobScheduler.  Callers (the RPC handlers, the in-process sim) hold
    the shard's server lock — the plane itself is lock-free."""

    def __init__(self, scheduler, shard_name: str):
        self.scheduler = scheduler
        self.shard = shard_name
        scheduler.shard_name = shard_name
        scheduler.fed = self
        self.leases: dict[str, Lease] = {}

    # -- reserve --

    def free_count(self, partition: str, req: np.ndarray) -> int:
        """How many nodes of ``partition`` could be leased for a
        per-node requirement ``req`` right now (advisory — the answer
        can go stale the moment the lock drops; the arbiter treats it
        as a split hint, never a promise)."""
        part = self.scheduler.meta.partitions.get(partition)
        if part is None:
            return 0
        nodes = self.scheduler.meta.nodes
        return sum(1 for nid in part.node_ids
                   if nodes[nid].schedulable
                   and (req <= nodes[nid].avail).all())

    def lease_nodes(self, lease_id: str, partition: str, node_num: int,
                    req: np.ndarray, ttl: float, now: float):
        """Reserve ``node_num`` schedulable nodes of ``partition`` with
        ``avail >= req`` each.  Returns (node_names, epoch, durable_seq)
        or raises ValueError with the refusal reason."""
        self.expire(now)
        sched = self.scheduler
        meta = sched.meta
        if lease_id in self.leases:
            raise ValueError(f"lease {lease_id!r} already held")
        part = meta.partitions.get(partition)
        if part is None:
            raise ValueError(f"partition {partition!r} not owned by "
                             f"shard {self.shard!r}")
        chosen: list[int] = []
        for nid in sorted(part.node_ids):
            node = meta.nodes[nid]
            if node.schedulable and (req <= node.avail).all():
                chosen.append(nid)
                if len(chosen) == node_num:
                    break
        if len(chosen) < node_num:
            raise ValueError(
                f"{partition}: only {len(chosen)}/{node_num} nodes free")
        names = []
        for nid in chosen:
            node = meta.nodes[nid]
            node.fed_leased = lease_id
            # same revalidation trigger as a node death: an in-flight
            # local cycle must not commit onto a node leased mid-solve
            meta._log_event(ResReduceEvent(nid))
            names.append(node.name)
        epoch = sched.fencing_epoch
        deadline = now + ttl if ttl > 0 else float("inf")
        seq = 0
        if sched.wal is not None:
            seq = sched.wal.fed_event("fed_reserve", {
                "lease_id": lease_id, "partition": partition,
                "node_names": names, "epoch": epoch,
                "deadline": deadline})
        self.leases[lease_id] = Lease(lease_id, partition, list(chosen),
                                      epoch, deadline, seq,
                                      reserved_at=now)
        sched.events.emit(
            "fed_lease_granted", "info", time=now,
            detail=f"lease={lease_id} part={partition} "
                   f"nodes={len(chosen)} epoch={epoch}")
        _MET_LEASES.inc()
        return names, epoch, seq

    # -- confirm (phase two) --

    def confirm_gang(self, lease_id: str, gang_id: str, spec: JobSpec,
                     node_names: list[str], now: float,
                     epoch: int = 0) -> int:
        """Commit one gang member onto (a subset of) a lease's nodes.
        Returns the shard-local job id; raises ValueError on refusal —
        the lease stays held for the arbiter to release."""
        sched = self.scheduler
        meta = sched.meta
        lease = self.leases.get(lease_id)
        if lease is None:
            raise ValueError(f"no such lease {lease_id!r}")
        if epoch and epoch != sched.fencing_epoch:
            raise ValueError(
                f"fencing: lease epoch {epoch} != current "
                f"{sched.fencing_epoch}")
        if not node_names:
            node_ids = list(lease.node_ids)
        else:
            name_to_id = meta._name_to_id
            node_ids = []
            for name in node_names:
                nid = name_to_id.get(name)
                if nid is None or nid not in lease.node_ids:
                    raise ValueError(f"node {name!r} not in lease")
                node_ids.append(nid)
        # the whole lease returns to the local pool NOW: the confirmed
        # subset is about to be malloc'd to the member, the rest frees.
        # Safe against local racing because the caller holds the shard's
        # server lock until the commit below is durable.
        for nid in lease.node_ids:
            meta.nodes[nid].fed_leased = ""
        del self.leases[lease_id]

        wal = sched.wal
        try:
            if wal is not None:
                wal.begin_batch()
            job_id = sched.submit(spec, now)
            if not job_id:
                raise ValueError("member spec rejected by submit")
            job = sched.pending[job_id]
            # the _commit_preemption template, minus eviction: admission
            # first, then malloc with the leased nodes
            if job.spec.licenses and not sched.licenses.malloc(
                    job.spec.licenses):
                sched.cancel(job_id, now)
                raise ValueError("licenses exhausted")
            if not sched._malloc_run_limits(job):
                sched.licenses.free(job.spec.licenses or {})
                sched.cancel(job_id, now)
                raise ValueError("QoS run limit")
            job.node_ids = list(node_ids)
            job.alloc_cache = None
            if not meta.malloc_resource(job.job_id, node_ids,
                                        sched._job_alloc(job)):
                sched.licenses.free(job.spec.licenses or {})
                sched._free_run_limits(job)
                job.node_ids = []
                job.alloc_cache = None
                sched.cancel(job_id, now)
                raise ValueError("leased nodes no longer fit the spec")
            del sched.pending[job_id]
            job.status = JobStatus.RUNNING
            job.start_time = now
            job.pending_reason = PendingReason.NONE
            sched._init_steps(job, now)
            sched.running[job_id] = job
            sched._ledger_add(job, now)
            if wal is not None:
                wal.job_started(job)
                wal.fed_event("fed_confirm", {
                    "lease_id": lease_id, "gang_id": gang_id,
                    "job_id": job_id, "epoch": sched.fencing_epoch})
            if sched.jobtrace is not None:
                # the arbiter's two-phase hop, spanned on the member's
                # own timeline (sequenced BEFORE placed so the
                # waterfall reads reserve -> confirm -> placed):
                # arbiter_reserve at lease-grant time, arbiter_confirm
                # now — their gap is the cross-shard coordination cost
                sched.jobtrace.stamp(
                    job_id, job.requeue_count, "arbiter_reserve",
                    lease.reserved_at or now,
                    epoch=lease.epoch)
                sched.jobtrace.stamp(
                    job_id, job.requeue_count, "arbiter_confirm", now,
                    epoch=sched.fencing_epoch)
                sched.jobtrace.stamp(job_id, job.requeue_count, "placed",
                                     now, epoch=sched.fencing_epoch)
            sched._trigger_dep_event(job)
            sched._queue_dispatch(job, node_ids)
        finally:
            if wal is not None:
                wal.commit_batch()
        # durable-before-dispatch, the dispatch-ring discipline: the
        # group's fsync returned above, so the drain pushes immediately
        sched._drain_dispatch_ring()
        return job_id

    # -- release / expiry / recovery --

    def release_lease(self, lease_id: str, now: float,
                      detail: str = "released") -> bool:
        sched = self.scheduler
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return False
        for nid in lease.node_ids:
            node = sched.meta.nodes.get(nid)
            if node is not None and node.fed_leased == lease_id:
                node.fed_leased = ""
        if sched.wal is not None:
            sched.wal.fed_event("fed_release", {
                "lease_id": lease_id, "epoch": lease.epoch})
        sched.events.emit(
            "fed_lease_revoked", "warning", time=now,
            detail=f"lease={lease_id} {detail}")
        _MET_REVOKED.inc()
        if sched.cycle_kick is not None:
            sched.cycle_kick()  # freed nodes may unblock local pending
        return True

    def expire(self, now: float) -> int:
        """Drop leases past their TTL (a dead arbiter never holds
        capacity hostage).  Returns the number expired."""
        due = [lid for lid, lease in self.leases.items()
               if lease.deadline <= now]
        for lid in due:
            self.release_lease(lid, now, detail="ttl expired")
        return len(due)

    def recover(self, now: float) -> int:
        """Post-replay cleanup: any lease whose last WAL record is a
        bare ``fed_reserve`` was reserved but never confirmed before the
        crash — write its release tombstone.  (Only ``fed_confirm``
        creates a job, so nothing placed can be lost here; the arbiter
        re-places the gang against fresh leases.)  Returns the number of
        leases dropped."""
        sched = self.scheduler
        if sched.wal is None:
            return 0
        dropped = 0
        state = sched.wal.replay_fed(sched.wal.path)
        for lease_id, (ev, payload) in sorted(state.items()):
            if ev != "fed_reserve":
                continue
            sched.wal.fed_event("fed_release", {
                "lease_id": lease_id,
                "epoch": payload.get("epoch", 0)})
            sched.events.emit(
                "fed_lease_revoked", "warning", time=now,
                detail=f"lease={lease_id} dropped by recovery "
                       "(reserve without confirm)")
            _MET_REVOKED.inc()
            dropped += 1
        return dropped

    def stats(self) -> dict:
        return {"shard": self.shard, "leases": len(self.leases)}
