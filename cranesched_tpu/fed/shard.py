"""Per-shard lease plane: the shard-side half of the arbiter's
two-phase cross-partition gang commit.

A shard owns its partitions outright — local jobs schedule through the
normal cycle with no federation awareness.  The only cross-shard
authority is the placement arbiter, and its entire contract with a
shard is three operations grafted onto the local ``JobScheduler`` here:

``lease_nodes``
    Reserve concrete nodes for an arbiter solve.  The reservation is a
    durable ``fed_reserve`` WAL record plus ``NodeMeta.fed_leased``
    flags; the flag folds into ``schedulable``, so leased nodes vanish
    from the local snapshot AND fail local mallocs — a shard cycle can
    never race the arbiter onto a leased node.  The lease carries the
    shard's CURRENT fencing epoch and a TTL: a dead arbiter's leases
    self-expire, and a confirm under a stale epoch is refused (the
    dispatch-ring fencing discipline, reused).

``confirm_gang``
    Turn one lease into a RUNNING local gang member.  The member is a
    normal local job (submitted, committed, WAL'd, dispatched through
    the ordinary dispatch ring) created inside one WAL group together
    with the ``fed_confirm`` record — the ONLY record that creates a
    job.  A crash before the group's fsync leaves a bare reserve, which
    recovery drops; a crash after leaves the job durable exactly once.
    Never double-placed, never half-placed.

``release_lease``
    Drop an unconfirmed reservation (arbiter abort, TTL expiry, or
    recovery finding a reserve without a confirm).

Recovery: :meth:`recover` replays ``fed_*`` records after the normal
job replay.  Leases whose last record is ``fed_reserve`` are released
(durable ``fed_release`` tombstone) — their gang was never committed
here, and the arbiter's own retry logic re-places it from scratch.

Live partition migration (fed/rebalance.py drives it) adds a second
WAL protocol on the same plane.  A partition moves shard-to-shard in
four durable phases, each its own record:

``fed_migrate_begin`` (source)
    The partition is sealed — local submits refuse, arbiter leases on
    its nodes release — and the intent (mid, dest, job_ids) is durable.

``fed_migrate_import`` (dest)
    The ONLY record that creates jobs on the destination.  The import
    validates and mallocs EVERY job first, before a single record is
    appended — a refusal (unknown node, placement that no longer
    fits) rolls the mallocs back and writes NOTHING, so a structured
    import error genuinely means "not adopted".  Then the whole
    handoff lands in one WAL group: node inventory adopted by NAME
    (ids are shard-local), every pending/running job re-created under
    a fresh dest-local id, then the import record.  A crash before
    the group's fsync imports nothing; after, everything — never half
    a partition.

``fed_migrate_commit`` (source)
    Written once the dest durably holds the jobs and the successor map
    is live.  The source then DROPS the migrated jobs — resources,
    licenses, run limits, submit slots freed; no terminal stamps, this
    is removal, not completion — and marks the partition's nodes dead.
    ``compact`` keeps this record forever: it is what filters the
    migrated jobs out of every future source replay.

``fed_migrate_abort`` (source)
    The handoff never reached the dest: unseal, keep everything.

A source SIGKILL mid-handoff leaves a begin without commit/abort;
recovery surfaces it and the coordinator/resolver settles it by
asking the dest :meth:`has_import` — imported means commit (the jobs
live there), not imported means abort (they never left).  Exactly one
shard ends up owning every job either way.

Recovery splits in two around the ordinary job replay:

:meth:`prepare_recovery`
    BEFORE ``scheduler.recover``: rebuild imported partitions' node
    meta (in original adoption order, so node ids renumber
    identically and replayed placements stay valid), filter
    committed-migration job_ids out of the replay (they live on the
    dest now), re-seal in-flight/migrated partitions, and re-seed the
    imports/begun tables.  State comes from the HA snapshot's ``fed``
    document first — the snapshotter prunes WAL segments a snapshot
    covers, fed_migrate_* records included — then the surviving WAL
    records overlay it.

:meth:`recover_migrations`
    AFTER ``scheduler.recover``: re-mark migrated-away partitions'
    nodes dead and surface begins with no commit/abort as
    :attr:`unresolved_migrations` for the resolver.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cranesched_tpu.ctld.defs import (
    DEP_NEVER,
    JobSpec,
    JobStatus,
    PendingReason,
)
from cranesched_tpu.ctld.meta import ResReduceEvent
from cranesched_tpu.ctld.wal import (
    WriteAheadLog,
    _job_from_dict,
    _job_to_dict,
)
from cranesched_tpu.obs import REGISTRY as _OBS

_MET_LEASES = _OBS.counter(
    "crane_fed_leases_granted_total",
    "arbiter node leases granted by this shard")
_MET_REVOKED = _OBS.counter(
    "crane_fed_leases_revoked_total",
    "arbiter node leases released, expired, or dropped by recovery")
_MET_MIG_JOBS = _OBS.counter(
    "crane_fed_migrated_jobs_total",
    "jobs adopted by this shard through live partition migration")


@dataclasses.dataclass
class Lease:
    """One live reservation: which nodes, under which fencing epoch,
    and when the shard self-expires it."""

    lease_id: str
    partition: str
    node_ids: list[int]
    epoch: int
    deadline: float
    seq: int = 0  # the fed_reserve record's WAL seq
    reserved_at: float = 0.0  # when granted (the arbiter_reserve span)


class FedShardPlane:
    """Lease/confirm/release surface grafted onto one shard's
    JobScheduler.  Callers (the RPC handlers, the in-process sim) hold
    the shard's server lock — the plane itself is lock-free."""

    def __init__(self, scheduler, shard_name: str):
        self.scheduler = scheduler
        self.shard = shard_name
        scheduler.shard_name = shard_name
        scheduler.fed = self
        self.leases: dict[str, Lease] = {}
        #: mid -> dest-local job ids adopted (the source's crash
        #: recovery asks :meth:`has_import` to resolve a bare begin)
        self.imports: dict[str, list[int]] = {}
        #: partitions this shard handed away (their nodes stay in meta,
        #: dead, so shard-local node ids never renumber)
        self.migrated_away: set[str] = set()
        #: ordered adoption records (mid, partition, priority, nodes)
        #: — the HA snapshot carries these so a dest restart can
        #: rebuild imported node meta even after the covering WAL
        #: segments were pruned; order IS the node-id renumbering
        self.import_meta: list[dict] = []
        #: mid -> begin payload for migrations this shard STARTED and
        #: has not yet committed/aborted; snapshotted alongside
        #: import_meta so an in-flight begin survives segment pruning
        self.begun: dict[str, dict] = {}
        #: begins recovery could not settle locally — the partition
        #: stays sealed until a resolver confirms the dest's
        #: has_import answer (rpc/server.py's resolve loop, the
        #: coordinator's resolve(), or an operator)
        self.unresolved_migrations: list[dict] = []

    # -- reserve --

    def free_count(self, partition: str, req: np.ndarray) -> int:
        """How many nodes of ``partition`` could be leased for a
        per-node requirement ``req`` right now (advisory — the answer
        can go stale the moment the lock drops; the arbiter treats it
        as a split hint, never a promise)."""
        part = self.scheduler.meta.partitions.get(partition)
        if part is None:
            return 0
        nodes = self.scheduler.meta.nodes
        return sum(1 for nid in part.node_ids
                   if nodes[nid].schedulable
                   and (req <= nodes[nid].avail).all())

    def lease_nodes(self, lease_id: str, partition: str, node_num: int,
                    req: np.ndarray, ttl: float, now: float):
        """Reserve ``node_num`` schedulable nodes of ``partition`` with
        ``avail >= req`` each.  Returns (node_names, epoch, durable_seq)
        or raises ValueError with the refusal reason."""
        self.expire(now)
        sched = self.scheduler
        meta = sched.meta
        if lease_id in self.leases:
            raise ValueError(f"lease {lease_id!r} already held")
        part = meta.partitions.get(partition)
        if part is None:
            raise ValueError(f"partition {partition!r} not owned by "
                             f"shard {self.shard!r}")
        chosen: list[int] = []
        for nid in sorted(part.node_ids):
            node = meta.nodes[nid]
            if node.schedulable and (req <= node.avail).all():
                chosen.append(nid)
                if len(chosen) == node_num:
                    break
        if len(chosen) < node_num:
            raise ValueError(
                f"{partition}: only {len(chosen)}/{node_num} nodes free")
        names = []
        for nid in chosen:
            node = meta.nodes[nid]
            node.fed_leased = lease_id
            # same revalidation trigger as a node death: an in-flight
            # local cycle must not commit onto a node leased mid-solve
            meta._log_event(ResReduceEvent(nid))
            names.append(node.name)
        epoch = sched.fencing_epoch
        deadline = now + ttl if ttl > 0 else float("inf")
        seq = 0
        if sched.wal is not None:
            seq = sched.wal.fed_event("fed_reserve", {
                "lease_id": lease_id, "partition": partition,
                "node_names": names, "epoch": epoch,
                "deadline": deadline})
        self.leases[lease_id] = Lease(lease_id, partition, list(chosen),
                                      epoch, deadline, seq,
                                      reserved_at=now)
        sched.events.emit(
            "fed_lease_granted", "info", time=now,
            detail=f"lease={lease_id} part={partition} "
                   f"nodes={len(chosen)} epoch={epoch}")
        _MET_LEASES.inc()
        return names, epoch, seq

    # -- confirm (phase two) --

    def confirm_gang(self, lease_id: str, gang_id: str, spec: JobSpec,
                     node_names: list[str], now: float,
                     epoch: int = 0) -> int:
        """Commit one gang member onto (a subset of) a lease's nodes.
        Returns the shard-local job id; raises ValueError on refusal —
        the lease stays held for the arbiter to release."""
        sched = self.scheduler
        meta = sched.meta
        lease = self.leases.get(lease_id)
        if lease is None:
            raise ValueError(f"no such lease {lease_id!r}")
        if epoch and epoch != sched.fencing_epoch:
            raise ValueError(
                f"fencing: lease epoch {epoch} != current "
                f"{sched.fencing_epoch}")
        if not node_names:
            node_ids = list(lease.node_ids)
        else:
            name_to_id = meta._name_to_id
            node_ids = []
            for name in node_names:
                nid = name_to_id.get(name)
                if nid is None or nid not in lease.node_ids:
                    raise ValueError(f"node {name!r} not in lease")
                node_ids.append(nid)
        # the whole lease returns to the local pool NOW: the confirmed
        # subset is about to be malloc'd to the member, the rest frees.
        # Safe against local racing because the caller holds the shard's
        # server lock until the commit below is durable.
        for nid in lease.node_ids:
            meta.nodes[nid].fed_leased = ""
        del self.leases[lease_id]

        wal = sched.wal
        try:
            if wal is not None:
                wal.begin_batch()
            job_id = sched.submit(spec, now)
            if not job_id:
                raise ValueError("member spec rejected by submit")
            job = sched.pending[job_id]
            # the _commit_preemption template, minus eviction: admission
            # first, then malloc with the leased nodes
            if job.spec.licenses and not sched.licenses.malloc(
                    job.spec.licenses):
                sched.cancel(job_id, now)
                raise ValueError("licenses exhausted")
            if not sched._malloc_run_limits(job):
                sched.licenses.free(job.spec.licenses or {})
                sched.cancel(job_id, now)
                raise ValueError("QoS run limit")
            job.node_ids = list(node_ids)
            job.alloc_cache = None
            if not meta.malloc_resource(job.job_id, node_ids,
                                        sched._job_alloc(job)):
                sched.licenses.free(job.spec.licenses or {})
                sched._free_run_limits(job)
                job.node_ids = []
                job.alloc_cache = None
                sched.cancel(job_id, now)
                raise ValueError("leased nodes no longer fit the spec")
            del sched.pending[job_id]
            job.status = JobStatus.RUNNING
            job.start_time = now
            job.pending_reason = PendingReason.NONE
            sched._init_steps(job, now)
            sched.running[job_id] = job
            sched._ledger_add(job, now)
            if wal is not None:
                wal.job_started(job)
                wal.fed_event("fed_confirm", {
                    "lease_id": lease_id, "gang_id": gang_id,
                    "job_id": job_id, "epoch": sched.fencing_epoch})
            if sched.jobtrace is not None:
                # the arbiter's two-phase hop, spanned on the member's
                # own timeline (sequenced BEFORE placed so the
                # waterfall reads reserve -> confirm -> placed):
                # arbiter_reserve at lease-grant time, arbiter_confirm
                # now — their gap is the cross-shard coordination cost
                sched.jobtrace.stamp(
                    job_id, job.requeue_count, "arbiter_reserve",
                    lease.reserved_at or now,
                    epoch=lease.epoch)
                sched.jobtrace.stamp(
                    job_id, job.requeue_count, "arbiter_confirm", now,
                    epoch=sched.fencing_epoch)
                sched.jobtrace.stamp(job_id, job.requeue_count, "placed",
                                     now, epoch=sched.fencing_epoch)
            sched._trigger_dep_event(job)
            sched._queue_dispatch(job, node_ids)
        finally:
            if wal is not None:
                wal.commit_batch()
        # durable-before-dispatch, the dispatch-ring discipline: the
        # group's fsync returned above, so the drain pushes immediately
        sched._drain_dispatch_ring()
        return job_id

    # -- release / expiry / recovery --

    def release_lease(self, lease_id: str, now: float,
                      detail: str = "released") -> bool:
        sched = self.scheduler
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return False
        for nid in lease.node_ids:
            node = sched.meta.nodes.get(nid)
            if node is not None and node.fed_leased == lease_id:
                node.fed_leased = ""
        if sched.wal is not None:
            sched.wal.fed_event("fed_release", {
                "lease_id": lease_id, "epoch": lease.epoch})
        sched.events.emit(
            "fed_lease_revoked", "warning", time=now,
            detail=f"lease={lease_id} {detail}")
        _MET_REVOKED.inc()
        if sched.cycle_kick is not None:
            sched.cycle_kick()  # freed nodes may unblock local pending
        return True

    def expire(self, now: float) -> int:
        """Drop leases past their TTL (a dead arbiter never holds
        capacity hostage).  Returns the number expired."""
        due = [lid for lid, lease in self.leases.items()
               if lease.deadline <= now]
        for lid in due:
            self.release_lease(lid, now, detail="ttl expired")
        return len(due)

    def recover(self, now: float) -> int:
        """Post-replay cleanup: any lease whose last WAL record is a
        bare ``fed_reserve`` was reserved but never confirmed before the
        crash — write its release tombstone.  (Only ``fed_confirm``
        creates a job, so nothing placed can be lost here; the arbiter
        re-places the gang against fresh leases.)  Returns the number of
        leases dropped."""
        sched = self.scheduler
        if sched.wal is None:
            return 0
        dropped = 0
        state = sched.wal.replay_fed(sched.wal.path)
        for lease_id, (ev, payload) in sorted(state.items()):
            if ev != "fed_reserve":
                continue
            sched.wal.fed_event("fed_release", {
                "lease_id": lease_id,
                "epoch": payload.get("epoch", 0)})
            sched.events.emit(
                "fed_lease_revoked", "warning", time=now,
                detail=f"lease={lease_id} dropped by recovery "
                       "(reserve without confirm)")
            _MET_REVOKED.inc()
            dropped += 1
        return dropped

    # ------------------------------------------------------------------
    # live partition migration (the four-phase WAL protocol; see the
    # module docstring — fed/rebalance.py MigrationCoordinator drives)
    # ------------------------------------------------------------------

    def partition_jobs(self, partition: str) -> list[int]:
        """Live (pending + running) job ids of one partition."""
        sched = self.scheduler
        ids = [jid for jid, j in sched.pending.items()
               if j.spec.partition == partition]
        ids += [jid for jid, j in sched.running.items()
                if j.spec.partition == partition]
        return sorted(ids)

    def seal_partition(self, mid: str, partition: str, dest: str,
                       now: float) -> list[int]:
        """Phase one on the SOURCE: stop admitting into ``partition``
        and make the intent durable.  Local submits into a sealed
        partition return 0 (the successor map owns it), and any arbiter
        lease on its nodes releases — the gang re-places against the
        successor map.  Returns the job ids that will travel."""
        sched = self.scheduler
        if partition not in sched.meta.partitions:
            raise ValueError(f"partition {partition!r} not owned by "
                             f"shard {self.shard!r}")
        if partition in sched.sealed_partitions:
            raise ValueError(f"partition {partition!r} already sealed "
                             "(migration in flight)")
        for lid in [lid for lid, lease in self.leases.items()
                    if lease.partition == partition]:
            self.release_lease(lid, now, detail="partition migrating")
        sched.sealed_partitions.add(partition)
        job_ids = self.partition_jobs(partition)
        self.begun[str(mid)] = {"mid": str(mid), "partition": partition,
                                "dest": dest, "job_ids": list(job_ids)}
        if sched.wal is not None:
            sched.wal.fed_event("fed_migrate_begin", {
                "mid": str(mid), "partition": partition, "dest": dest,
                "job_ids": job_ids})
        sched.events.emit(
            "fed_migrate_begin", "info", time=now,
            detail=f"mid={mid} part={partition} dest={dest} "
                   f"jobs={len(job_ids)}")
        return job_ids

    def export_partition(self, mid: str, partition: str) -> dict:
        """The handoff payload: partition metadata, node inventory, and
        every live job.  Nodes and per-job placements travel by NAME —
        node ids are shard-local and the dest assigns its own.  The
        dispatch ring is empty by the time this runs (the caller holds
        the shard lock and every committed dispatch drained before it
        was taken), so the payload is the complete partition state."""
        sched = self.scheduler
        meta = sched.meta
        part = meta.partitions.get(partition)
        if part is None:
            raise ValueError(f"partition {partition!r} not owned by "
                             f"shard {self.shard!r}")
        nodes = []
        for nid in sorted(part.node_ids):
            node = meta.nodes[nid]
            nodes.append({"name": node.name,
                          "total": [int(x) for x in node.total],
                          "partitions": sorted(node.partitions)})
        jobs = []
        for jid in self.partition_jobs(partition):
            job = sched.pending.get(jid) or sched.running.get(jid)
            jobs.append({"job": _job_to_dict(job),
                         "node_names": [meta.nodes[n].name
                                        for n in job.node_ids]})
        return {"mid": str(mid), "partition": partition,
                "source": self.shard, "priority": part.priority,
                "nodes": nodes, "jobs": jobs}

    def import_partition(self, payload: dict, now: float
                         ) -> tuple[list[int], list[int]]:
        """Phase two on the DEST: adopt the partition in ONE WAL group.

        Jobs are re-created under fresh dest-local ids (ascending in
        source-id order, preserving relative queue age); running jobs
        re-malloc their named nodes and re-enter the running set exactly
        as :meth:`JobScheduler.recover` re-adopts survivors — the
        physical tasks never stopped, only their controller moved.
        Idempotent per mid: a retried handoff returns the first
        import's ids.  Returns (job_ids, node_ids-added)."""
        sched = self.scheduler
        meta = sched.meta
        mid = str(payload["mid"])
        partition = str(payload["partition"])
        if mid in self.imports:
            return list(self.imports[mid]), []
        if partition not in meta.partitions:
            meta.add_partition(partition,
                               priority=int(payload.get("priority", 0)))
        new_nodes: list[int] = []
        for doc in payload.get("nodes", []) or []:
            nid = meta._name_to_id.get(doc["name"])
            if nid is None:
                node = meta.add_node(
                    doc["name"], np.asarray(doc["total"], np.int32),
                    partitions=doc.get("partitions") or (partition,))
                nid = node.node_id
                meta.craned_up(nid)
                new_nodes.append(nid)
            elif (not meta.nodes[nid].alive
                  and partition in meta.nodes[nid].partitions):
                # a prior refused attempt left the node parked dead —
                # revive it for this retry
                meta.craned_up(nid)
                new_nodes.append(nid)
        entries = sorted(payload.get("jobs", []) or [],
                         key=lambda e: e["job"]["job_id"])
        idmap: dict[int, int] = {}
        for entry in entries:
            idmap[int(entry["job"]["job_id"])] = sched._next_job_id
            sched._next_job_id += 1
        # Phase A — validate and malloc EVERYTHING before a single
        # record is appended: commit_batch flushes partial groups even
        # on error, so a refusal discovered mid-write would half-import
        # durably.  An exception here rolls back every malloc, parks
        # the adopted nodes dead, and writes NOTHING — a structured
        # import error genuinely means "not adopted".
        jobs: list = []
        mallocd: list[tuple[int, list[int], object]] = []
        try:
            for entry in entries:
                job = _job_from_dict(entry["job"])
                job.job_id = idmap[int(entry["job"]["job_id"])]
                self._remap_job(job, idmap,
                                entry.get("node_names") or [])
                if job.status in (JobStatus.RUNNING,
                                  JobStatus.SUSPENDED):
                    alloc = sched._job_alloc(job)
                    if not meta.malloc_resource(job.job_id,
                                                job.node_ids, alloc):
                        raise ValueError(
                            f"imported nodes cannot hold job "
                            f"{entry['job']['job_id']} "
                            f"(mid={mid}, part={partition})")
                    mallocd.append((job.job_id, list(job.node_ids),
                                    alloc))
                jobs.append(job)
        except Exception:
            for jid, nids, alloc in mallocd:
                meta.free_resource(jid, nids, alloc)
            for nid in new_nodes:
                meta.craned_down(nid)
            raise
        # Phase B — everything fits: bookkeeping plus ONE WAL group.
        wal = sched.wal
        imported: list[int] = []
        try:
            if wal is not None:
                wal.begin_batch()
            for job in jobs:
                if job.status in (JobStatus.RUNNING,
                                  JobStatus.SUSPENDED):
                    sched.licenses.restore(job.spec.licenses or {})
                    if sched.account_meta is not None and job.qos_name:
                        sched.account_meta.restore_run(
                            job.spec.user, job.spec.account,
                            job.qos_name, job.spec)
                        job.run_usage_taken = True
                    sched.running[job.job_id] = job
                    sched._ledger_add(job, now)
                    if wal is not None:
                        wal.job_started(job)
                else:
                    sched.pending[job.job_id] = job
                    # waiting edges re-register so co-migrated
                    # dependees still fire events on this shard
                    for dep_id, v in job.dep_state.items():
                        if v is None:
                            sched._dependents.setdefault(
                                dep_id, set()).add(job.job_id)
                    if wal is not None:
                        wal.job_submitted(job)
                if (sched.account_meta is not None and job.qos_name
                        and job.array_parent_id is None):
                    sched.account_meta.restore_submit(
                        job.spec.user, job.spec.account, job.qos_name)
                if (sched.global_usage is not None
                        and job.array_parent_id is None):
                    sched.global_usage.note_submit(job.spec.user,
                                                   job.spec.account)
                if sched.jobtrace is not None:
                    sched.jobtrace.stamp(job.job_id, job.requeue_count,
                                         "migrated_in", now,
                                         epoch=sched.fencing_epoch)
                imported.append(job.job_id)
                _MET_MIG_JOBS.inc()
            if wal is not None:
                # node inventory rides the import record: recovery must
                # rebuild these meta entries BEFORE replaying the jobs
                wal.fed_event("fed_migrate_import", {
                    "mid": mid, "partition": partition,
                    "source": str(payload.get("source", "")),
                    "priority": int(payload.get("priority", 0)),
                    "nodes": payload.get("nodes", []) or [],
                    "job_ids": imported})
        finally:
            if wal is not None:
                wal.commit_batch()
        self.imports[mid] = list(imported)
        self.import_meta.append({
            "mid": mid, "partition": partition,
            "priority": int(payload.get("priority", 0)),
            "nodes": [dict(d) for d in payload.get("nodes", []) or []]})
        sched.events.emit(
            "fed_migrate_import", "info", time=now,
            detail=f"mid={mid} part={partition} jobs={len(imported)} "
                   f"nodes={len(new_nodes)}")
        sched._kick()
        return imported, new_nodes

    def _remap_job(self, job, idmap: dict[int, int],
                   node_names: list[str]) -> None:
        """Rewrite every shard-local id in an imported job: placement
        by node NAME, dependency/array edges through ``idmap``.  A
        waiting dependency whose dependee did NOT co-migrate can never
        fire here — it becomes DEP_NEVER (cross-shard dependencies are
        out of contract, same as at submit routing)."""
        meta = self.scheduler.meta
        node_ids = []
        for name in node_names:
            nid = meta._name_to_id.get(name)
            if nid is None:
                raise ValueError(f"imported job placed on unknown "
                                 f"node {name!r}")
            node_ids.append(nid)
        job.node_ids = node_ids
        job.alloc_cache = None
        if job.spec.dependencies:
            job.spec = dataclasses.replace(job.spec, dependencies=tuple(
                dataclasses.replace(dep, job_id=idmap.get(dep.job_id,
                                                          dep.job_id))
                for dep in job.spec.dependencies))
        dep_state = {}
        for old_id, v in job.dep_state.items():
            if old_id in idmap:
                dep_state[idmap[old_id]] = v
            elif v is None:
                dep_state[old_id] = DEP_NEVER
            else:
                dep_state[old_id] = v  # resolved on the source: keep
        job.dep_state = dep_state
        if job.array_parent_id is not None:
            job.array_parent_id = idmap.get(job.array_parent_id,
                                            job.array_parent_id)
        if job.array_children:
            job.array_children = [idmap.get(c, c)
                                  for c in job.array_children]

    def has_import(self, mid: str) -> bool:
        """Did this shard durably adopt handoff ``mid``?  The answer
        the source's crash recovery keys commit-vs-abort on."""
        return str(mid) in self.imports

    def commit_migration(self, mid: str, partition: str,
                         now: float) -> list[int]:
        """Final phase on the SOURCE, once the dest holds the jobs and
        the successor map is live: write the commit record, then DROP
        the migrated jobs — free resources/licenses/limits/slots,
        remove from the queues with no terminal stamps (removal, not
        completion) — and mark the partition's nodes dead.  The
        partition stays sealed forever here; compact keeps the commit
        record forever so no future replay resurrects the jobs."""
        sched = self.scheduler
        meta = sched.meta
        job_ids = self.partition_jobs(partition)
        if sched.wal is not None:
            sched.wal.fed_event("fed_migrate_commit", {
                "mid": str(mid), "partition": partition,
                "job_ids": job_ids})
        for jid in job_ids:
            job = sched.running.get(jid)
            if job is not None:
                meta.free_resource(jid, job.node_ids,
                                   sched._job_alloc(job))
                sched._ledger.remove(jid)
                sched.licenses.free(job.spec.licenses or {})
                sched._free_run_limits(job)
                del sched.running[jid]
            else:
                job = sched.pending.pop(jid)
            # the submit slot travels with the job (the dest restored
            # its own at import)
            if (sched.account_meta is not None and job.qos_name
                    and job.array_parent_id is None):
                sched.account_meta.free_submit(
                    job.spec.user, job.spec.account, job.qos_name)
            if (sched.global_usage is not None
                    and job.array_parent_id is None):
                sched.global_usage.note_release_submit(
                    job.spec.user, job.spec.account)
            sched._dependents.pop(jid, None)
        part = meta.partitions.get(partition)
        if part is not None:
            for nid in sorted(part.node_ids):
                if meta.nodes[nid].alive:
                    meta.craned_down(nid)
        self.migrated_away.add(partition)
        self.begun.pop(str(mid), None)
        self.unresolved_migrations = [
            r for r in self.unresolved_migrations
            if r.get("mid") != str(mid)]
        sched.events.emit(
            "fed_migrate_commit", "info", time=now,
            detail=f"mid={mid} part={partition} "
                   f"handed_off={len(job_ids)}")
        return job_ids

    def abort_migration(self, mid: str, partition: str,
                        now: float) -> None:
        """The handoff never reached the dest: unseal and keep
        everything — the begin record is annulled durably."""
        sched = self.scheduler
        if sched.wal is not None:
            sched.wal.fed_event("fed_migrate_abort", {
                "mid": str(mid), "partition": partition})
        sched.sealed_partitions.discard(partition)
        self.begun.pop(str(mid), None)
        self.unresolved_migrations = [
            r for r in self.unresolved_migrations
            if r.get("mid") != str(mid)]
        sched.events.emit(
            "fed_migrate_abort", "warning", time=now,
            detail=f"mid={mid} part={partition}")

    def _adopt_meta(self, rec: dict) -> None:
        """Recreate one adoption's partition + node meta (recovery
        path; mirrors the live import's inventory adoption, so node
        ids renumber identically and replayed placements stay valid)."""
        meta = self.scheduler.meta
        partition = str(rec["partition"])
        if partition not in meta.partitions:
            meta.add_partition(partition,
                               priority=int(rec.get("priority", 0)))
        for doc in rec.get("nodes", []) or []:
            nid = meta._name_to_id.get(doc["name"])
            if nid is None:
                node = meta.add_node(
                    doc["name"], np.asarray(doc["total"], np.int32),
                    partitions=doc.get("partitions") or (partition,))
                meta.craned_up(node.node_id)

    def snapshot_doc(self) -> dict:
        """Migration state for the HA snapshot.  The snapshotter
        prunes WAL segments a snapshot covers — ``fed_migrate_*``
        records included — so the snapshot itself must carry enough to
        rebuild imported node meta, the committed-migration replay
        filter, and in-flight begins across a restart."""
        return {
            "imports": {m: list(ids)
                        for m, ids in sorted(self.imports.items())},
            "import_meta": [dict(e) for e in self.import_meta],
            "migrated_away": sorted(self.migrated_away),
            "sealed": sorted(self.scheduler.sealed_partitions),
            "begun": [dict(self.begun[m]) for m in sorted(self.begun)],
        }

    def prepare_recovery(self, wal_path, replayed: dict,
                         snap_fed: dict | None = None) -> None:
        """BEFORE ``scheduler.recover``: fold migration history into
        the replay.  ``replayed`` is the job_id -> job dict the WAL
        replay assembled (mutated in place); ``snap_fed`` is the HA
        snapshot's ``fed`` document, applied first, with the surviving
        WAL records overlaid on top.

        * imported partitions' node meta is rebuilt in original
          adoption order and :attr:`imports` re-seeds (the source may
          still ask :meth:`has_import`),
        * committed migrations' job_ids drop out of ``replayed`` (the
          jobs live on the dest now) and the partition re-seals,
        * a begin with no commit/abort re-seals its partition and
          re-seeds :attr:`begun` for :meth:`recover_migrations` to
          surface as unresolved.
        """
        sched = self.scheduler
        if snap_fed:
            for rec in snap_fed.get("import_meta", []) or []:
                self._adopt_meta(rec)
                self.import_meta.append(dict(rec))
            for m, ids in (snap_fed.get("imports") or {}).items():
                self.imports[str(m)] = list(ids)
            self.migrated_away.update(
                str(p) for p in snap_fed.get("migrated_away", []) or [])
            for p in snap_fed.get("sealed", []) or []:
                sched.sealed_partitions.add(str(p))
            for rec in snap_fed.get("begun", []) or []:
                self.begun[str(rec["mid"])] = dict(rec)
        migs = (WriteAheadLog.replay_migrations(wal_path)
                if wal_path else {})
        for mid, entry in sorted(migs.items(),
                                 key=lambda kv: kv[1].get("seq", 0)):
            ev = entry.get("ev", "")
            partition = str(entry.get("partition", ""))
            if ev == "fed_migrate_import":
                if mid in self.imports:
                    continue  # the snapshot already carried it
                self._adopt_meta(entry)
                self.imports[mid] = list(entry.get("job_ids") or [])
                self.import_meta.append({
                    "mid": mid, "partition": partition,
                    "priority": int(entry.get("priority", 0)),
                    "nodes": [dict(d)
                              for d in entry.get("nodes", []) or []]})
            elif ev == "fed_migrate_begin":
                sched.sealed_partitions.add(partition)
                self.begun[mid] = {
                    "mid": mid, "partition": partition,
                    "dest": str(entry.get("dest", "")),
                    "job_ids": list(entry.get("job_ids") or [])}
            elif ev == "fed_migrate_commit":
                for jid in entry.get("job_ids") or []:
                    replayed.pop(jid, None)
                sched.sealed_partitions.add(partition)
                self.migrated_away.add(partition)
                self.begun.pop(mid, None)
            elif ev == "fed_migrate_abort":
                self.begun.pop(mid, None)
                if partition not in self.migrated_away:
                    sched.sealed_partitions.discard(partition)

    def recover_migrations(self, now: float) -> list[dict]:
        """AFTER ``scheduler.recover``: re-mark migrated-away
        partitions' nodes dead (recover marks observed nodes up) and
        surface begins with no commit/abort as
        :attr:`unresolved_migrations` — each partition stays sealed
        until the resolver settles its begin against the dest's
        :meth:`has_import` answer (commit if adopted, abort if not)."""
        sched = self.scheduler
        meta = sched.meta
        for partition in sorted(self.migrated_away):
            sched.sealed_partitions.add(partition)
            part = meta.partitions.get(partition)
            if part is not None:
                for nid in sorted(part.node_ids):
                    if meta.nodes[nid].alive:
                        meta.craned_down(nid)
        unresolved = [dict(self.begun[m]) for m in sorted(self.begun)]
        for rec in unresolved:
            sched.events.emit(
                "fed_migrate_unresolved", "warning", time=now,
                detail=f"mid={rec['mid']} part={rec['partition']} "
                       "(begin without commit/abort — resolving "
                       "against the destination)")
        self.unresolved_migrations = unresolved
        return unresolved

    def stats(self) -> dict:
        return {"shard": self.shard, "leases": len(self.leases),
                "sealed": sorted(self.scheduler.sealed_partitions),
                "migrated_away": sorted(self.migrated_away),
                "imports": len(self.imports),
                "begun": len(self.begun),
                "unresolved": len(self.unresolved_migrations)}
