"""In-job coordination library: the PMIx client-side role.

Job scripts (and multi-host frameworks bootstrapping inside crane
gangs) use this to reach the gang's rendezvous service — hosted by
the rank-0 supervisor and advertised via ``CRANE_RENDEZVOUS``
(reference: PMIx fences/modex, src/Utilities/Pmix/Pmix.h:44; the
fork-env role Pmix.h:54-57).

Python:

    from cranesched_tpu import coord
    coord.fence("ready")                      # gang-wide barrier
    coord.put("rank0-addr", b"10.0.0.5:9999") # modex publish
    addr = coord.get("rank0-addr", timeout=60)
    jax.distributed.initialize(coord.jax_coordinator(),
                               num_processes=coord.nranks(),
                               process_id=coord.rank())

Shell (inside job scripts):

    python -m cranesched_tpu.coord fence ready
    python -m cranesched_tpu.coord put KEY VALUE
    python -m cranesched_tpu.coord get KEY --timeout 60

``jax_coordinator()`` solves the bootstrap port problem properly:
rank 0 binds a FREE port on its host and publishes it through the
modex, so the deterministic CRANE_RENDEZVOUS port is never reused for
the framework's own coordinator (review r3: hash-derived ports can
collide between live gangs — the modex-published port cannot).
"""

from __future__ import annotations

import os
import socket
import sys


def rank() -> int:
    return int(os.environ.get("CRANE_NODE_RANK", "0"))


def nranks() -> int:
    return int(os.environ.get("CRANE_NNODES", "1"))


def nodelist() -> str:
    return os.environ.get("CRANE_JOB_NODELIST", "")


def _client():
    from cranesched_tpu.rpc.rendezvous import RendezvousClient
    address = os.environ.get("CRANE_RENDEZVOUS", "")
    if not address:
        raise RuntimeError(
            "no CRANE_RENDEZVOUS in the environment — not inside a "
            "multi-node crane step?")
    tls = None
    ca = os.environ.get("CRANE_RENDEZVOUS_CA", "")
    if ca:
        # TLS cluster: rank-0 serves the fence/modex with its node
        # cert; verify against the cluster CA so the gang token and
        # modex payloads never ride plaintext node-to-node
        from cranesched_tpu.utils.pki import TlsConfig
        tls = TlsConfig(ca=ca)
    return RendezvousClient(
        address, token=os.environ.get("CRANE_RENDEZVOUS_TOKEN", ""),
        tls=tls)


def fence(name: str, data: bytes = b"",
          timeout: float = 300.0) -> list[bytes]:
    """Block until every gang member reaches the fence; returns the
    rank-ordered data contributions.  Single-node gangs return
    immediately (no service exists, none is needed)."""
    if nranks() <= 1:
        return [data]
    client = _client()
    try:
        return client.fence(name, rank(), nranks(), data=data,
                            timeout=timeout)
    finally:
        client.close()


def put(key: str, value: bytes) -> None:
    client = _client()
    try:
        client.put(key, value)
    finally:
        client.close()


def get(key: str, timeout: float = 60.0) -> bytes | None:
    client = _client()
    try:
        return client.get(key, timeout=timeout)
    finally:
        client.close()


def jax_coordinator(timeout: float = 120.0, port: int = 0) -> str:
    """Coordinator address for ``jax.distributed.initialize`` (or any
    torchrun-style bootstrap): rank 0 picks a port on its host and
    publishes it via the modex; everyone else reads it.

    With ``port=0`` rank 0 probes a free ephemeral port — the probe
    socket closes before the framework rebinds it, so a racing
    process can still steal it in that window (narrow, not zero).
    Deployments that manage ports should pass an explicit ``port``."""
    if nranks() <= 1:
        return "127.0.0.1:0"
    if rank() == 0:
        host = os.environ.get("CRANE_RENDEZVOUS", "").split(":")[0] \
            or socket.gethostname()
        if not port:
            with socket.socket() as s:
                s.setsockopt(socket.SOL_SOCKET,
                             socket.SO_REUSEADDR, 1)
                s.bind(("", 0))
                port = s.getsockname()[1]
        addr = f"{host}:{port}"
        put("crane/jax_coordinator", addr.encode())
        return addr
    value = get("crane/jax_coordinator", timeout=timeout)
    if value is None:
        raise RuntimeError("rank 0 never published the coordinator "
                           "address")
    return value.decode()


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="crane-coord")
    sub = ap.add_subparsers(dest="cmd", required=True)
    f = sub.add_parser("fence")
    f.add_argument("name")
    f.add_argument("--data", default="")
    f.add_argument("--timeout", type=float, default=300.0)
    p = sub.add_parser("put")
    p.add_argument("key")
    p.add_argument("value")
    g = sub.add_parser("get")
    g.add_argument("key")
    g.add_argument("--timeout", type=float, default=60.0)
    args = ap.parse_args(argv)
    if args.cmd == "fence":
        gathered = fence(args.name, data=args.data.encode(),
                         timeout=args.timeout)
        for i, item in enumerate(gathered):
            if item:
                print(f"{i}:{item.decode(errors='replace')}")
        return 0
    if args.cmd == "put":
        put(args.key, args.value.encode())
        return 0
    value = get(args.key, timeout=args.timeout)
    if value is None:
        return 1
    print(value.decode(errors="replace"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
