"""Persistent structure-of-arrays over the pending queue.

The reference scheduler is event-triggered: its pending task map is a
live structure updated by submits/cancels/status events, and a cycle
consults it without rebuilding anything.  Our reproduction's cycle used
to walk every pending job in Python (`_pending_candidates`) and
re-encode every priority row (`_priority_sort`) each tick; at 100k+
pending jobs the prelude dominated even when nothing changed.

This table keeps one numpy row per pending job, written by the events
that can change it (submit / cancel / hold / modify / dep trigger /
requeue) and masked **vectorially** each cycle:

    candidate = live & ~template & ~held
                & begin <= now & dep_ready(now) & license_ok

so the per-cycle candidate scan is one vectorized pass, and the
priority/batch row build gathers straight from these columns instead of
touching Job objects.  ``epoch`` bumps on every mutation — the
scheduler's no-op-cycle fingerprint (scheduler.py `_cycle_fingerprint`)
is built from it.

Rows live in insertion order (append-only with tombstones, compacted
in-order when mostly dead), which preserves the dict-iteration candidate
order of the old Python loop exactly — required for bit-exact parity
with the from-scratch rebuild path (tests/test_delta_cycle.py).
"""

from __future__ import annotations

import numpy as np

# gate codes: why a live row is not a candidate this cycle.  The
# numeric order encodes the OLD loop's reason precedence (held beats
# begin beats deps beats licenses); -1 marks "never evaluated" so a
# fresh upsert always rewrites the job's pending_reason once.
GATE_NONE = -1          # freshly (re)written row, gate unknown
GATE_CANDIDATE = 0
GATE_HELD = 1
GATE_BEGIN = 2
GATE_DEP = 3
GATE_DEP_NEVER = 4
GATE_LICENSE = 5


class PendingTable:
    """SoA mirror of ``scheduler.pending`` (non-terminal rows only).

    All columns are plain numpy; the scheduler derives the values (it
    owns the Job/JobSpec semantics) and this class owns storage, the
    vectorized gate evaluation, and the epoch/dirty accounting.
    """

    def __init__(self, num_res: int, cap: int = 64):
        self.num_res = int(num_res)
        #: bumped on every upsert/remove — feeds the cycle fingerprint
        self.epoch = 0
        #: rows dirtied since the last candidates() call (trace column)
        self.last_dirty = 0
        self._dirty = 0
        self._row: dict[int, int] = {}     # job_id -> row index
        self._n = 0                        # rows used, incl. tombstones
        self._dead = 0
        # license-set interning: key 0 is the empty set (no licenses)
        self._lic_ids: dict[frozenset, int] = {frozenset(): 0}
        self.lic_sets: list[frozenset] = [frozenset()]
        self._alloc(max(int(cap), 8))

    def _alloc(self, cap: int) -> None:
        self.job_id = np.zeros(cap, np.int64)
        self.live = np.zeros(cap, bool)
        self.template = np.zeros(cap, bool)       # array parents
        self.held = np.zeros(cap, bool)
        self.begin = np.full(cap, -np.inf)        # begin_time gate
        self.dep = np.full(cap, -np.inf)          # dep-ready time
        self.dep_never = np.zeros(cap, bool)
        self.lic = np.zeros(cap, np.int32)        # license-set id
        self.gate = np.full(cap, GATE_NONE, np.int8)
        # priority-row attributes (gathered by _priority_sort)
        self.submit = np.zeros(cap, np.float64)
        self.qos = np.zeros(cap, np.int32)
        self.part = np.zeros(cap, np.int32)       # partition priority
        self.nnum = np.zeros(cap, np.int32)
        self.cpus = np.zeros(cap, np.float64)
        self.mem = np.zeros(cap, np.float64)
        self.acct = np.zeros(cap, np.int32)
        # batch-build attributes (gathered by _build_batch)
        self.tlimit = np.zeros(cap, np.int32)
        self.packed = np.zeros(cap, bool)         # needs the packed route
        self.req = np.zeros((cap, self.num_res), np.int32)
        # cached mask-table class id, valid iff cls_gen matches the
        # mask table's generation (derived state: no epoch bump)
        self.cls = np.zeros(cap, np.int32)
        self.cls_gen = np.full(cap, -1, np.int64)

    def __len__(self) -> int:
        return len(self._row)

    def __contains__(self, job_id: int) -> bool:
        return job_id in self._row

    def _grow(self) -> None:
        old, cap = self._n, len(self.job_id)
        new_cap = cap * 2
        for name in ("job_id", "live", "template", "held", "begin",
                     "dep", "dep_never", "lic", "gate", "submit", "qos",
                     "part", "nnum", "cpus", "mem", "acct", "tlimit",
                     "packed", "req", "cls", "cls_gen"):
            col = getattr(self, name)
            shape = (new_cap,) + col.shape[1:]
            fresh = np.zeros(shape, col.dtype)
            if name == "gate":
                fresh[:] = GATE_NONE
            elif name == "cls_gen":
                fresh[:] = -1
            elif name in ("begin", "dep"):
                fresh[:] = -np.inf
            fresh[:old] = col[:old]
            setattr(self, name, fresh)

    def lic_key(self, licenses) -> int:
        """Intern a license requirement mapping; 0 = no licenses."""
        if not licenses:
            return 0
        key = frozenset(licenses.items())
        lid = self._lic_ids.get(key)
        if lid is None:
            lid = len(self.lic_sets)
            self._lic_ids[key] = lid
            self.lic_sets.append(key)
        return lid

    def upsert(self, job_id: int, *, template, held, begin, dep,
               dep_never, lic, submit, qos, part, nnum, cpus, mem,
               acct, tlimit, packed, req) -> None:
        row = self._row.get(job_id)
        if row is None:
            if self._n == len(self.job_id):
                self._grow()
            row = self._n
            self._n += 1
            self._row[job_id] = row
            self.job_id[row] = job_id
            self.live[row] = True
        self.template[row] = template
        self.held[row] = held
        self.begin[row] = begin
        self.dep[row] = dep
        self.dep_never[row] = dep_never
        self.lic[row] = lic
        self.gate[row] = GATE_NONE       # force one reason rewrite
        self.submit[row] = submit
        self.qos[row] = qos
        self.part[row] = part
        self.nnum[row] = nnum
        self.cpus[row] = cpus
        self.mem[row] = mem
        self.acct[row] = acct
        self.tlimit[row] = tlimit
        self.packed[row] = packed
        self.req[row] = req
        self.cls_gen[row] = -1
        self.epoch += 1
        self._dirty += 1

    def remove(self, job_id: int) -> None:
        row = self._row.pop(job_id, None)
        if row is None:
            return
        self.live[row] = False
        self._dead += 1
        self.epoch += 1
        self._dirty += 1
        if self._dead > 64 and self._dead * 2 > self._n:
            self._compact()

    def _compact(self) -> None:
        """Drop tombstones, preserving insertion order."""
        keep = np.nonzero(self.live[:self._n])[0]
        k = len(keep)
        for name in ("job_id", "live", "template", "held", "begin",
                     "dep", "dep_never", "lic", "gate", "submit", "qos",
                     "part", "nnum", "cpus", "mem", "acct", "tlimit",
                     "packed", "req", "cls", "cls_gen"):
            col = getattr(self, name)
            col[:k] = col[keep]
        self._n = k
        self._dead = 0
        self._row = {int(j): i for i, j in enumerate(self.job_id[:k])}

    # ---- per-cycle vectorized evaluation ----

    def license_mask(self, license_ok) -> np.ndarray:
        """bool per interned license-set id, from a ``sufficient``-style
        predicate evaluated ONCE per unique set (satellite: the old loop
        re-checked identical sets once per job per tick)."""
        ok = np.ones(len(self.lic_sets), bool)
        for lid in range(1, len(self.lic_sets)):
            ok[lid] = license_ok(dict(self.lic_sets[lid]))
        return ok

    def candidates(self, now: float, lic_ok: np.ndarray):
        """One vectorized pass -> (candidate_rows, changed_rows, gates).

        ``candidate_rows`` are row indices in insertion order (== the
        old dict-iteration order); ``changed_rows``/``gates`` are the
        rows whose gate differs from the stored one, so the scheduler
        rewrites pending_reason for O(changed) jobs, not O(pending).
        Resets the dirty-row counter into ``last_dirty``.
        """
        self.last_dirty = self._dirty
        self._dirty = 0
        n = self._n
        if n == 0:
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.int8))
        gate = np.zeros(n, np.int8)
        # reverse precedence order: later writes win, matching the old
        # loop's held > begin > deps > licenses reason priority
        np.putmask(gate, ~lic_ok[self.lic[:n]], GATE_LICENSE)
        blocked = self.dep[:n] > now
        np.putmask(gate, blocked, GATE_DEP)
        np.putmask(gate, blocked & self.dep_never[:n], GATE_DEP_NEVER)
        np.putmask(gate, self.begin[:n] > now, GATE_BEGIN)
        np.putmask(gate, self.held[:n], GATE_HELD)
        vis = self.live[:n] & ~self.template[:n]
        changed = np.nonzero(vis & (gate != self.gate[:n]))[0]
        self.gate[:n] = np.where(vis, gate, self.gate[:n])
        cand = np.nonzero(vis & (gate == GATE_CANDIDATE))[0]
        return cand, changed, gate[changed]

    def next_edge(self, now: float) -> float:
        """Earliest future time a gate flips without an event: the next
        begin_time or dep-satisfaction deadline strictly after ``now``.
        inf when no time-dependent gate is pending."""
        n = self._n
        if n == 0:
            return np.inf
        live = self.live[:n]
        edge = np.inf
        begin = self.begin[:n]
        m = live & (begin > now) & np.isfinite(begin)
        if m.any():
            edge = float(begin[m].min())
        dep = self.dep[:n]
        m = live & (dep > now) & np.isfinite(dep) & ~self.dep_never[:n]
        if m.any():
            edge = min(edge, float(dep[m].min()))
        return edge
