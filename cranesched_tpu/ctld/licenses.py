"""Cluster-wide consumable licenses.

Mirrors the reference's LicenseManager (reference:
src/CraneCtld/Accounting/LicenseManager.h:46-125 — local license counts
with a reserve→malloc→free lifecycle checked inside the scheduling cycle;
CheckLicenseCountSufficient is called from NodeSelect,
JobScheduler.cpp:6739).  Remote license-server sync is out of scope
(gated, not stubbed): this is the local ledger the cycle consults."""

from __future__ import annotations

import dataclasses
from typing import Mapping


@dataclasses.dataclass
class License:
    name: str
    total: int
    in_use: int = 0

    @property
    def free(self) -> int:
        return self.total - self.in_use


class LicenseManager:
    def __init__(self):
        self.licenses: dict[str, License] = {}

    def configure(self, name: str, total: int) -> None:
        lic = self.licenses.get(name)
        if lic is None:
            self.licenses[name] = License(name=name, total=total)
        else:
            lic.total = total

    def legal(self, wanted: Mapping[str, int] | None) -> str:
        """Submit-time legality (reference CheckLicensesLegal): every
        requested license exists and the count fits the TOTAL."""
        for name, count in (wanted or {}).items():
            lic = self.licenses.get(name)
            if lic is None:
                return f"unknown license {name}"
            if count > lic.total:
                return (f"license {name}: requested {count} "
                        f"> total {lic.total}")
        return ""

    def sufficient(self, wanted: Mapping[str, int] | None) -> bool:
        """Cycle-time availability (CheckLicenseCountSufficient)."""
        return all(count <= self.licenses[name].free
                   for name, count in (wanted or {}).items()
                   if name in self.licenses)

    def malloc(self, wanted: Mapping[str, int] | None) -> bool:
        """Atomically take all or none."""
        if not self.sufficient(wanted):
            return False
        for name, count in (wanted or {}).items():
            self.licenses[name].in_use += count
        return True

    def restore(self, wanted: Mapping[str, int] | None) -> None:
        """Crash recovery: force-account seats a recovered running job
        already holds.  May push in_use past total (e.g. totals lowered
        between restarts) — sufficient() then admits nothing new until
        the overcommit drains, which is the safe direction."""
        for name, count in (wanted or {}).items():
            lic = self.licenses.get(name)
            if lic is not None:
                lic.in_use += count

    def free(self, wanted: Mapping[str, int] | None) -> None:
        for name, count in (wanted or {}).items():
            lic = self.licenses.get(name)
            if lic is not None:
                lic.in_use = max(lic.in_use - count, 0)
