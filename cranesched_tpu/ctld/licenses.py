"""Cluster-wide consumable licenses.

Mirrors the reference's LicenseManager (reference:
src/CraneCtld/Accounting/LicenseManager.h:46-125 — local license
counts AND remote/server-synced ones, with a reserve→malloc→free
lifecycle checked inside the scheduling cycle;
CheckLicenseCountSufficient is called from NodeSelect,
JobScheduler.cpp:6739).

Remote licenses: a ``remote`` license's total and externally-consumed
seat count come from a license server, reconciled by a periodic sync
program (``LicenseSyncer`` — the lmstat-parsing role; any executable
printing ``name total used`` lines works).  The cycle's availability
math then subtracts BOTH this cluster's in-flight seats and the
server-reported external usage."""

from __future__ import annotations

import dataclasses
from typing import Mapping


@dataclasses.dataclass
class License:
    name: str
    total: int
    in_use: int = 0
    # remote (server-synced) license state: the sync loop owns total
    # and external_used; in_use stays THIS cluster's seats.
    # external_used should exclude this cluster's own checkouts (the
    # sync program's responsibility); when it cannot, the overlap
    # double-counts — the conservative direction.
    remote: bool = False
    external_used: int = 0

    @property
    def free(self) -> int:
        return self.total - self.in_use - self.external_used


class LicenseManager:
    def __init__(self):
        self.licenses: dict[str, License] = {}
        # bumped whenever availability can have changed (configure /
        # sync / malloc / free / restore that actually moved a count) —
        # one term of the scheduler's no-op-cycle fingerprint
        self.epoch = 0

    def configure(self, name: str, total: int,
                  remote: bool = False) -> None:
        lic = self.licenses.get(name)
        if lic is None:
            self.licenses[name] = License(name=name, total=total,
                                          remote=remote)
            self.epoch += 1
        else:
            if lic.total != total or lic.remote != remote:
                self.epoch += 1
            lic.total = total
            lic.remote = remote

    def sync(self, observed: Mapping[str, tuple[int, int]]) -> None:
        """Reconcile remote licenses against a server observation:
        ``{name: (total, external_used)}``.  Local (non-remote)
        licenses and this cluster's own in_use are never touched; an
        unknown name is configured as a new remote license (the
        reference discovers server licenses the same way)."""
        for name, (total, used) in observed.items():
            lic = self.licenses.get(name)
            if lic is None:
                lic = self.licenses[name] = License(
                    name=name, total=int(total), remote=True)
                self.epoch += 1
            if not lic.remote:
                continue   # a local license shadows the server's name
            if (lic.total != int(total)
                    or lic.external_used != max(int(used), 0)):
                self.epoch += 1
            lic.total = int(total)
            lic.external_used = max(int(used), 0)

    def legal(self, wanted: Mapping[str, int] | None) -> str:
        """Submit-time legality (reference CheckLicensesLegal): every
        requested license exists and the count fits the TOTAL."""
        for name, count in (wanted or {}).items():
            lic = self.licenses.get(name)
            if lic is None:
                return f"unknown license {name}"
            if count > lic.total:
                return (f"license {name}: requested {count} "
                        f"> total {lic.total}")
        return ""

    def sufficient(self, wanted: Mapping[str, int] | None) -> bool:
        """Cycle-time availability (CheckLicenseCountSufficient)."""
        return all(count <= self.licenses[name].free
                   for name, count in (wanted or {}).items()
                   if name in self.licenses)

    def malloc(self, wanted: Mapping[str, int] | None) -> bool:
        """Atomically take all or none."""
        if not self.sufficient(wanted):
            return False
        for name, count in (wanted or {}).items():
            self.licenses[name].in_use += count
            if count:
                self.epoch += 1
        return True

    def restore(self, wanted: Mapping[str, int] | None) -> None:
        """Crash recovery: force-account seats a recovered running job
        already holds.  May push in_use past total (e.g. totals lowered
        between restarts) — sufficient() then admits nothing new until
        the overcommit drains, which is the safe direction."""
        for name, count in (wanted or {}).items():
            lic = self.licenses.get(name)
            if lic is not None and count:
                lic.in_use += count
                self.epoch += 1

    def free(self, wanted: Mapping[str, int] | None) -> None:
        for name, count in (wanted or {}).items():
            lic = self.licenses.get(name)
            if lic is not None and lic.in_use > 0 and count:
                lic.in_use = max(lic.in_use - count, 0)
                self.epoch += 1


class LicenseSyncer:
    """Periodic remote-license reconciliation (the reference's
    server-synced mode, LicenseManager.h:46-125).  Runs ``program``
    (bash -c) every ``interval`` seconds and feeds its stdout —
    ``name total used`` per line — into ``manager.sync`` under the
    given lock (the ctld server lock: totals must not move mid-cycle).
    A failing or garbled run changes nothing (the last observation
    stands, which is the only sane failure mode for a license
    server blip)."""

    def __init__(self, manager: LicenseManager, program: str,
                 interval: float = 60.0, lock=None):
        self.manager = manager
        self.program = program
        self.interval = interval
        self.lock = lock
        self.last_sync: float | None = None
        self.last_error = ""
        self._stop = None

    @staticmethod
    def parse(text: str) -> dict[str, tuple[int, int]]:
        observed = {}
        for line in text.splitlines():
            parts = line.split()
            if len(parts) != 3 or parts[0].startswith("#"):
                continue
            try:
                observed[parts[0]] = (int(parts[1]), int(parts[2]))
            except ValueError:
                continue
        return observed

    def sync_once(self) -> bool:
        import subprocess
        import time as _time
        try:
            result = subprocess.run(
                ["bash", "-c", self.program], capture_output=True,
                text=True, timeout=55)
        except (OSError, subprocess.SubprocessError) as exc:
            self.last_error = str(exc)[:200]
            return False
        if result.returncode != 0:
            self.last_error = (result.stderr or "nonzero exit")[:200]
            return False
        observed = self.parse(result.stdout)
        if not observed:
            self.last_error = "sync program produced no license lines"
            return False
        if self.lock is not None:
            with self.lock:
                self.manager.sync(observed)
        else:
            self.manager.sync(observed)
        self.last_sync = _time.time()
        self.last_error = ""
        return True

    def start(self) -> None:
        import threading
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(self.interval):
                self.sync_once()

        threading.Thread(target=loop, daemon=True).start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
