"""Job/step lifecycle types for the control plane.

Mirrors the capability surface of the reference's public defs (reference:
src/CraneCtld/CtldPublicDefs.h — JobInCtld :782, job status space
protos/PublicDefs.proto TaskStatus, pending-reason strings
docs/en/reference/pending_reason.md) without porting its object design:
jobs here are small frozen specs + a mutable runtime record, and every
resource quantity lives in the dense vector encoding of ops/resources.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Mapping, Sequence

import numpy as np

from cranesched_tpu.ops.resources import ResourceLayout


class JobStatus(enum.Enum):
    """Job lifecycle (reference PublicDefs.proto TaskStatus)."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUSPENDED = "Suspended"         # frozen via cgroup freezer; keeps
                                    # its allocation
    COMPLETED = "Completed"         # exit code 0
    FAILED = "Failed"               # nonzero exit
    EXCEED_TIME_LIMIT = "ExceedTimeLimit"
    CANCELLED = "Cancelled"

    @property
    def is_terminal(self) -> bool:
        return self not in (JobStatus.PENDING, JobStatus.RUNNING,
                            JobStatus.SUSPENDED)

    @property
    def is_failed_kind(self) -> bool:
        """The 'not ok' terminal family for AFTER_NOT_OK dependencies."""
        return self in (JobStatus.FAILED, JobStatus.EXCEED_TIME_LIMIT,
                        JobStatus.CANCELLED)


class DepType(enum.Enum):
    """Job dependency types (reference PublicDefs.proto:136-152)."""

    AFTER = "after"              # satisfied when the dependee STARTS
    AFTER_ANY = "afterany"       # satisfied when it reaches ANY terminal
    AFTER_OK = "afterok"         # terminal Completed; else never
    AFTER_NOT_OK = "afternotok"  # terminal failed-kind; else never


@dataclasses.dataclass(frozen=True)
class Dependency:
    """One dependency edge with optional per-edge delay
    (reference Dependencies, PublicDefs.proto:136-152)."""

    job_id: int
    type: DepType = DepType.AFTER_OK
    delay_seconds: float = 0.0


@dataclasses.dataclass(frozen=True)
class ArraySpec:
    """Job array shape (reference ArraySpec, PublicDefs.proto:154-159):
    task ids start..end step stride; at most max_concurrent children run
    at once (0 = unlimited — the %N suffix)."""

    start: int
    end: int
    stride: int = 1
    max_concurrent: int = 0

    def task_ids(self) -> list[int]:
        return list(range(self.start, self.end + 1, self.stride))


# dependency edge state sentinel: edge can never be satisfied
DEP_NEVER = float("inf")


class PendingReason(str, enum.Enum):
    """User-visible pending reasons (reference
    docs/en/reference/pending_reason.md; set throughout NodeSelect and the
    submit/cycle paths)."""

    NONE = ""
    RESOURCE = "Resource"
    CONSTRAINT = "Constraint"  # partition/nodelist rules nodes out
    PRIORITY = "Priority"      # cut off by the schedule batch limit, or
                               # resources free but a higher-priority
                               # reservation would be delayed
    HELD = "Held"
    BEGIN_TIME = "BeginTime"
    DEPENDENCY = "Dependency"
    DEPENDENCY_NEVER_SATISFIED = "DependencyNeverSatisfied"
    QOS_LIMIT = "QOSResourceLimit"
    LICENSE = "Licenses"
    PREEMPTED = "Preempted"
    INVALID = "InvalidSpec"


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """Per-node resource request in human units; encoded once at submit."""

    cpu: float = 1.0
    mem_bytes: int = 0
    memsw_bytes: int = 0
    gres: Mapping[tuple[str, str], int] | None = None

    def encode(self, layout: ResourceLayout) -> np.ndarray:
        return layout.encode(cpu=self.cpu, mem_bytes=self.mem_bytes,
                             memsw_bytes=self.memsw_bytes, gres=self.gres)


class StepStatus(enum.Enum):
    """Step lifecycle (reference StepInCtld status machines,
    CtldPublicDefs.h:521-782): PENDING = accepted, waiting for room in
    the allocation; RUNNING = supervisors spawned; terminal mirrors the
    job status space."""

    PENDING = "Pending"
    RUNNING = "Running"
    COMPLETED = "Completed"
    FAILED = "Failed"
    EXCEED_TIME_LIMIT = "ExceedTimeLimit"
    CANCELLED = "Cancelled"

    @property
    def is_terminal(self) -> bool:
        return self not in (StepStatus.PENDING, StepStatus.RUNNING)


@dataclasses.dataclass(frozen=True)
class StepSpec:
    """One unit of execution inside a job allocation (reference
    StepInCtld / crun within calloc, CtldPublicDefs.h:521;
    AllocSteps dispatch JobScheduler.cpp:1793-1839).

    ``res`` is the per-node share of the ALLOCATION the step occupies
    while running; None = the whole allocation (steps then serialize).
    ``node_num`` = how many of the job's nodes the step spans (0 = all).
    ``time_limit`` 0 inherits the job's remaining time."""

    name: str = "step"
    script: str = ""
    res: ResourceSpec | None = None
    node_num: int = 0
    time_limit: int = 0
    output_path: str = ""
    # interactive I/O: the submitting client's embedded CraneFored
    # endpoint; the supervisor streams stdout/stderr there and accepts
    # stdin (reference CforedClient, CforedClient.h:28-95).  The token
    # is the per-submission stream secret the first chunk must present.
    interactive_address: str = ""
    pty: bool = False
    interactive_token: str = ""
    # container step: run the script inside this OCI image via the
    # node's runtime (reference ContainerInstance, TaskManager.h:353)
    container_image: str = ""
    container_mounts: Sequence[str] = ()
    # observation channel (cattach): starts immediately, holds no
    # share of the allocation (Slurm --overlap analog)
    overlap: bool = False
    # overlap placement: run on the nodes of this RUNNING step (the
    # cattach target); None = allocation prefix
    follow_step: int | None = None
    # X11 forwarding (crun --x11 inside an allocation)
    x11: bool = False
    x11_cookie: str = ""
    # simulation-only (real planes learn these from the supervisor)
    sim_runtime: float | None = None
    sim_exit_code: int = 0


@dataclasses.dataclass
class Step:
    """Runtime record of one step (reference CommonStepInCtld /
    DaemonStepInCtld, CtldPublicDefs.h:713-782)."""

    step_id: int
    spec: StepSpec
    submit_time: float
    status: StepStatus = StepStatus.PENDING
    start_time: float | None = None
    end_time: float | None = None
    exit_code: int | None = None
    node_ids: list[int] = dataclasses.field(default_factory=list)
    # per-node terminal reports, same aggregation rule as the job's
    node_reports: dict[int, tuple] = dataclasses.field(
        default_factory=dict)
    cancel_requested: bool = False
    # efficiency sample (ceff): summed over the step's nodes / peak
    cpu_seconds: float = 0.0
    max_rss_bytes: int = 0


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """What a user submits (reference JobToCtld / cbatch flags)."""

    name: str = "job"
    user: str = "user"
    account: str = "default"
    partition: str = "default"
    res: ResourceSpec = dataclasses.field(default_factory=ResourceSpec)
    node_num: int = 1
    # task packing (reference min_res_view = node res + task res * ntasks,
    # JobScheduler.cpp:6152; get_max_tasks :6171): per-node requirement is
    # ``res`` plus ``task_res`` per task.  Defaults collapse to the simple
    # one-allocation-per-node shape.
    task_res: ResourceSpec | None = None
    ntasks: int | None = None         # total tasks; None = node_num
    ntasks_per_node_min: int = 1
    ntasks_per_node_max: int = 1
    exclusive: bool = False           # whole idle nodes only (cpp:6248)
    time_limit: int = 3600            # seconds
    qos: str = ""                     # QoS name (resolved via accounting;
                                      # account default when empty)
    qos_priority: int = 0             # direct priority when accounting is
                                      # not configured
    held: bool = False
    include_nodes: Sequence[str] = ()
    exclude_nodes: Sequence[str] = ()
    begin_time: float | None = None   # epoch seconds; None = now
    requeue_if_failed: bool = False
    # dependencies (4 types w/ per-edge delay; AND by default, OR when
    # deps_is_or — reference Dependencies.is_or)
    dependencies: Sequence[Dependency] = ()
    deps_is_or: bool = False
    # job arrays: this spec becomes a pending template; children
    # materialize one per cycle (reference ArrayManager, Array.h:124)
    array: ArraySpec | None = None
    # named reservation to run inside (reference ResvMeta)
    reservation: str = ""
    # consumable licenses: name -> count (reference LicenseManager)
    licenses: Mapping[str, int] | None = None
    # batch script (run as bash -c by the supervisor) and output path
    # pattern (%j substitutes the job id; reference batch meta)
    script: str = ""
    output_path: str = ""
    # calloc-style allocation: hold resources WITHOUT an implicit batch
    # step; steps are submitted separately (SubmitStep) and the job ends
    # on FreeAllocation / cancel / time limit (reference InteractiveMeta
    # + calloc semantics, CtldPublicDefs.h:282)
    alloc_only: bool = False
    # interactive batch (crun without an allocation): step 0 streams to
    # this client-side CraneFored endpoint instead of output files
    interactive_address: str = ""
    pty: bool = False
    interactive_token: str = ""
    # container job: the batch step runs inside this OCI image
    # (reference ContainerInstance/PodInstance, TaskManager.h:293-353;
    # ccon run).  Mounts are host:ctr[:ro] specs passed to the runtime.
    container_image: str = ""
    container_mounts: Sequence[str] = ()
    # X11 forwarding for the interactive step (reference
    # SetupX11forwarding_, CforedClient.h:29-66)
    x11: bool = False
    x11_cookie: str = ""
    # simulation-only: how long the job actually runs and its exit code
    # (real clusters learn these when the step exits)
    sim_runtime: float | None = None
    sim_exit_code: int = 0


@dataclasses.dataclass
class Job:
    """Runtime record the scheduler owns (reference JobInCtld,
    CtldPublicDefs.h:782 — submit/start/end times, status, craned_ids,
    pending reason, requeue count)."""

    job_id: int
    spec: JobSpec
    submit_time: float
    status: JobStatus = JobStatus.PENDING
    qos_name: str = ""                    # resolved QoS (accounting)
    qos_priority: int = 0                 # effective qos priority
    held: bool = False                    # runtime hold flag (mutable;
                                          # seeded from spec.held at submit)
    cancel_requested: bool = False        # persisted cancel intent: survives
                                          # races with node death (the kill
                                          # may never be confirmed)
    pending_reason: PendingReason = PendingReason.NONE
    start_time: float | None = None
    end_time: float | None = None
    exit_code: int | None = None
    node_ids: list[int] = dataclasses.field(default_factory=list)
    task_layout: list[int] = dataclasses.field(default_factory=list)
    # per-node terminal reports for multi-node jobs (real node plane):
    # the job is terminal once every allocated node reported
    node_reports: dict[int, tuple] = dataclasses.field(
        default_factory=dict)
    requeue_count: int = 0
    # efficiency accounting (ceff): summed cpu-seconds across all step
    # reports and the peak RSS any of them observed
    cpu_seconds: float = 0.0
    max_rss_bytes: int = 0
    # dependency edge state: dep job_id -> earliest satisfiable time, or
    # DEP_NEVER (event-driven, reference AddDependent /
    # TriggerTerminalDependencyEvents, CtldPublicDefs.cpp:1750-1775)
    dep_state: dict[int, float | None] = dataclasses.field(
        default_factory=dict)
    # array bookkeeping: children carry (parent, task id); the parent is
    # a template tracking materialization (reference ArrayMeta)
    array_parent_id: int | None = None
    array_task_id: int | None = None
    array_remaining: list[int] = dataclasses.field(default_factory=list)
    array_children: list[int] = dataclasses.field(default_factory=list)
    # suspend/resume: suspended wall time is credited back to the time
    # limit (reference JobScheduler.cpp:118-126)
    suspend_time: float | None = None
    suspended_total: float = 0.0
    # steps inside the allocation (reference job->steps;
    # batch jobs get an implicit step 0 at start, alloc_only jobs start
    # empty and accept SubmitStep).  next_step_id survives requeue resets
    # per the reference's step-id-counter-reset-on-requeue rule.
    steps: dict[int, "Step"] = dataclasses.field(default_factory=dict)
    next_step_id: int = 0
    # cached per-node allocation vectors for the current incarnation
    # (derived state — not persisted; cleared on requeue)
    alloc_cache: list | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # cached solver-batch row ``(spec, (encoded req, node_num,
    # time_limit))`` — modify_job REPLACES job.spec
    # (dataclasses.replace), so a plain identity check on the first
    # element invalidates exactly when the row could change (derived
    # state — not persisted)
    row_cache: tuple | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # run-limit usage actually taken for this incarnation (keeps the
    # accounting free symmetric even if the QoS is deleted mid-run)
    run_usage_taken: bool = dataclasses.field(
        default=False, repr=False, compare=False)
    # global (federation-wide) run slot reserved at admission but not
    # yet converted by the running-dict hook — batch commits check the
    # whole set before any insert, so the gate must see earlier
    # same-cycle admissions through these reservations
    global_run_reserved: bool = dataclasses.field(
        default=False, repr=False, compare=False)
    priority: float = 0.0
    # topology placement record (topo/): the leaf block name when the
    # gang landed inside one block, "" otherwise; cross_block marks the
    # spanning fallback (exported as crane_topo_cross_block_gangs_total)
    topo_block: str = ""
    cross_block: bool = False

    def reset_for_requeue(self) -> None:
        """Return to pending after a failure/node-death (reference
        ResetForRequeue, JobScheduler.cpp:6950-6965)."""
        self.status = JobStatus.PENDING
        self.pending_reason = PendingReason.NONE
        self.start_time = None
        self.end_time = None
        self.exit_code = None
        self.node_ids = []
        self.task_layout = []
        self.node_reports = {}
        self.alloc_cache = None
        self.requeue_count += 1
        self.priority = 0.0
        # step-id counters reset on requeue (reference
        # PersistAndRequeueJobs_/ResetForRequeue, JobScheduler.cpp:
        # 6950-6965: "step-id counters reset")
        self.steps = {}
        self.next_step_id = 0
