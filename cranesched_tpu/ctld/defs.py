"""Job/step lifecycle types for the control plane.

Mirrors the capability surface of the reference's public defs (reference:
src/CraneCtld/CtldPublicDefs.h — JobInCtld :782, job status space
protos/PublicDefs.proto TaskStatus, pending-reason strings
docs/en/reference/pending_reason.md) without porting its object design:
jobs here are small frozen specs + a mutable runtime record, and every
resource quantity lives in the dense vector encoding of ops/resources.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Mapping, Sequence

import numpy as np

from cranesched_tpu.ops.resources import ResourceLayout


class JobStatus(enum.Enum):
    """Job lifecycle (reference PublicDefs.proto TaskStatus)."""

    PENDING = "Pending"
    RUNNING = "Running"
    COMPLETED = "Completed"         # exit code 0
    FAILED = "Failed"               # nonzero exit
    EXCEED_TIME_LIMIT = "ExceedTimeLimit"
    CANCELLED = "Cancelled"

    @property
    def is_terminal(self) -> bool:
        return self not in (JobStatus.PENDING, JobStatus.RUNNING)


class PendingReason(str, enum.Enum):
    """User-visible pending reasons (reference
    docs/en/reference/pending_reason.md; set throughout NodeSelect and the
    submit/cycle paths)."""

    NONE = ""
    RESOURCE = "Resource"
    CONSTRAINT = "Constraint"  # partition/nodelist rules nodes out
    PRIORITY = "Priority"      # cut off by the schedule batch limit, or
                               # resources free but a higher-priority
                               # reservation would be delayed
    HELD = "Held"
    BEGIN_TIME = "BeginTime"
    DEPENDENCY = "Dependency"
    DEPENDENCY_NEVER_SATISFIED = "DependencyNeverSatisfied"
    QOS_LIMIT = "QOSResourceLimit"
    INVALID = "InvalidSpec"


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """Per-node resource request in human units; encoded once at submit."""

    cpu: float = 1.0
    mem_bytes: int = 0
    memsw_bytes: int = 0
    gres: Mapping[tuple[str, str], int] | None = None

    def encode(self, layout: ResourceLayout) -> np.ndarray:
        return layout.encode(cpu=self.cpu, mem_bytes=self.mem_bytes,
                             memsw_bytes=self.memsw_bytes, gres=self.gres)


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """What a user submits (reference JobToCtld / cbatch flags)."""

    name: str = "job"
    user: str = "user"
    account: str = "default"
    partition: str = "default"
    res: ResourceSpec = dataclasses.field(default_factory=ResourceSpec)
    node_num: int = 1
    # task packing (reference min_res_view = node res + task res * ntasks,
    # JobScheduler.cpp:6152; get_max_tasks :6171): per-node requirement is
    # ``res`` plus ``task_res`` per task.  Defaults collapse to the simple
    # one-allocation-per-node shape.
    task_res: ResourceSpec | None = None
    ntasks: int | None = None         # total tasks; None = node_num
    ntasks_per_node_min: int = 1
    ntasks_per_node_max: int = 1
    exclusive: bool = False           # whole idle nodes only (cpp:6248)
    time_limit: int = 3600            # seconds
    qos: str = ""                     # QoS name (resolved via accounting;
                                      # account default when empty)
    qos_priority: int = 0             # direct priority when accounting is
                                      # not configured
    held: bool = False
    include_nodes: Sequence[str] = ()
    exclude_nodes: Sequence[str] = ()
    begin_time: float | None = None   # epoch seconds; None = now
    requeue_if_failed: bool = False
    # simulation-only: how long the job actually runs and its exit code
    # (real clusters learn these when the step exits)
    sim_runtime: float | None = None
    sim_exit_code: int = 0


@dataclasses.dataclass
class Job:
    """Runtime record the scheduler owns (reference JobInCtld,
    CtldPublicDefs.h:782 — submit/start/end times, status, craned_ids,
    pending reason, requeue count)."""

    job_id: int
    spec: JobSpec
    submit_time: float
    status: JobStatus = JobStatus.PENDING
    qos_name: str = ""                    # resolved QoS (accounting)
    qos_priority: int = 0                 # effective qos priority
    held: bool = False                    # runtime hold flag (mutable;
                                          # seeded from spec.held at submit)
    cancel_requested: bool = False        # persisted cancel intent: survives
                                          # races with node death (the kill
                                          # may never be confirmed)
    pending_reason: PendingReason = PendingReason.NONE
    start_time: float | None = None
    end_time: float | None = None
    exit_code: int | None = None
    node_ids: list[int] = dataclasses.field(default_factory=list)
    task_layout: list[int] = dataclasses.field(default_factory=list)
    requeue_count: int = 0
    # cached per-node allocation vectors for the current incarnation
    # (derived state — not persisted; cleared on requeue)
    alloc_cache: list | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # run-limit usage actually taken for this incarnation (keeps the
    # accounting free symmetric even if the QoS is deleted mid-run)
    run_usage_taken: bool = dataclasses.field(
        default=False, repr=False, compare=False)
    priority: float = 0.0

    def reset_for_requeue(self) -> None:
        """Return to pending after a failure/node-death (reference
        ResetForRequeue, JobScheduler.cpp:6950-6965)."""
        self.status = JobStatus.PENDING
        self.pending_reason = PendingReason.NONE
        self.start_time = None
        self.end_time = None
        self.exit_code = None
        self.node_ids = []
        self.task_layout = []
        self.alloc_cache = None
        self.requeue_count += 1
        self.priority = 0.0
