"""Device-resident ClusterState across scheduling cycles.

The immediate-fit solve used to rebuild its device state from full host
arrays every cycle — a complete ``[N, R]`` host→device transfer per
tick even though the incremental prelude already tracks exactly which
rows moved.  ResidentClusterState keeps the ClusterState buffers on
device between ticks and ships only ``(dirty_idx, dirty_rows)``:

- **Dirty tracking** piggybacks on MetaContainer's ``_touch_node`` hook
  (``dirty_listeners``): every snapshot-relevant node mutation lands in
  ``_pending``.  Rows the solver subtracted on device but the host then
  rejected at commit (license cap, QoS, malloc race, stale dirty row)
  are fed back through ``mark_diverged`` — those are the only rows
  where device and host can disagree without a host-side mutation.
- **Ownership discipline** for buffer donation: ``acquire()`` hands the
  state to the solve and forgets it; the solve runs a donating jit
  (``donate_argnums=(0,)``) and the scheduler gives the *returned*
  state back via ``adopt()``.  The donated input is dead after the
  call — on TPU its buffers were rewritten in place — and this class
  guarantees nothing else holds a reference to it.
- **Invalidation contract**: the caller passes a ``key`` (solver
  backend label, node count, resource dims, mask-table generation).
  Any mismatch — backend switch, craned (de)registration changing N,
  mask-table reset (reservation epoch / node-count change), topology
  permutation toggle (the scheduler calls ``invalidate()`` directly
  for that and for ``rebuild_device_state``) — drops the resident
  state and the next acquire pays one full rebuild.
- **Double buffering**: ``stage()`` runs right after commit and issues
  the *next* cycle's patch rows as an async ``jax.device_put`` while
  the dispatch drain and the following prelude run.  ``acquire()``
  consumes the staged upload only if nothing moved since (same
  ``meta_epoch`` and same row set), so steady-state cycles pay
  ``max(solve, patch-upload)`` instead of the sum and the patch itself
  is a device-side scatter with no host wait.

Cost seed note: ``RunLedger.cost0`` is time-dependent — it changes for
*every* node every cycle — so the ``[N]`` int32 cost ledger always
ships full and is excluded from the dirty-row delta.
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

from cranesched_tpu.models.solver import (
    make_cluster_state,
    patch_cluster_state,
    refresh_cost_ledger,
)

# dirty-row counts are bucketed to powers of two (floor 16) so the
# patch jit sees a handful of static shapes instead of one per count
_ROW_FLOOR = 16


def _bucket(n: int, floor: int = _ROW_FLOOR) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def padded_rows(dirty: int, num_nodes: int) -> int:
    """Padded patch length: power-of-two bucketed (floor 16) so the
    scatter jit sees few static shapes, capped at the node count (a
    pad larger than N would ship more than a full rebuild)."""
    return min(_bucket(dirty), max(num_nodes, 1))


def patch_row_bytes(num_dims: int) -> int:
    """Host→device bytes for one patched row: int32 index + int32
    avail[R] + int32 total[R] + bool alive."""
    return 4 + 4 * num_dims + 4 * num_dims + 1


def full_state_bytes(num_nodes: int, num_dims: int) -> int:
    """Host→device bytes for a full rebuild (avail+total int32 [N,R],
    alive bool [N], cost int32 [N])."""
    return num_nodes * (8 * num_dims + 1) + 4 * num_nodes


class ResidentClusterState:
    """Owns the cross-cycle device ClusterState for one scheduler."""

    def __init__(self, meta, enabled: bool = True):
        self.meta = meta
        self.enabled = enabled
        self._state = None
        self._key = None
        self._pending: set[int] = set()
        self._diverged: set[int] = set()
        # (meta_epoch, rowset, idx_dev, avail_dev, total_dev, alive_dev)
        self._staged = None
        # telemetry (persistent; per-cycle mode is consumed by the
        # scheduler via pop_cycle_mode)
        self.full_rebuilds = 0
        self.patch_cycles = 0
        self.ledger_cycles = 0
        self.staged_hits = 0
        self.last_mode: str | None = None
        self.last_h2d_rows = 0
        self.last_h2d_bytes = 0
        self.last_overlap = False
        self.last_issued_id: int | None = None
        self._cycle_mode: str | None = None
        if enabled:
            meta.dirty_listeners.append(self._note_dirty)

    # ---- dirty feeds ----

    def _note_dirty(self, node_id: int) -> None:
        self._pending.add(node_id)

    def mark_diverged(self, node_ids: Iterable[int]) -> None:
        """Commit rejected solver placements on these nodes: the device
        subtracted resources the host never allocated, and no host
        mutation will ever dirty the row.  Force-patch them next cycle."""
        if self.enabled and self._state is not None:
            self._diverged.update(int(i) for i in node_ids)

    def invalidate(self) -> None:
        """Drop the resident state; the next acquire() fully rebuilds."""
        self._state = None
        self._key = None
        self._staged = None
        self._pending.clear()
        self._diverged.clear()

    # ---- cycle protocol ----

    def acquire(self, avail, total, alive, cost0, key):
        """Hand a current device ClusterState to this cycle's solve.

        Ownership transfers to the caller: the solve donates the
        buffers, so this object forgets the state here and must be
        given the solve's returned state via adopt().  Returns
        ``(state, mode)`` with mode "rebuild", "patch", or "ledger"
        ("ledger" = empty delta, only the time-dependent cost ledger
        shipped — exactly 4*N bytes; the BENCH_r10 churn legs ran
        entirely in this mode but reported it as "patch", which made
        the steady-state H2D look like patch traffic with zero dirty
        rows).
        """
        state, self._state = self._state, None
        n = int(np.asarray(avail).shape[0])
        r = int(np.asarray(avail).shape[1])
        if state is None or key != self._key:
            self.invalidate()
            self._key = key
            state = make_cluster_state(avail, total, alive, cost0)
            self.full_rebuilds += 1
            self.last_mode = self._cycle_mode = "rebuild"
            self.last_h2d_rows = n
            self.last_h2d_bytes = full_state_bytes(n, r)
            self.last_overlap = False
            self.last_issued_id = id(state)
            return state, "rebuild"

        rows = frozenset(self._pending | self._diverged)
        staged, self._staged = self._staged, None
        if not rows:
            # empty delta: nothing moved, so only the time-dependent
            # cost ledger ships — no scatter, trivially overlapped
            state = refresh_cost_ledger(state, cost0)
            self.patch_cycles += 1
            self.ledger_cycles += 1
            self.staged_hits += 1
            self.last_mode = self._cycle_mode = "ledger"
            self.last_overlap = True
            self.last_h2d_rows = 0
            self.last_h2d_bytes = 4 * n
            self.last_issued_id = id(state)
            return state, "ledger"
        if (staged is not None and staged[0] == self.meta.meta_epoch
                and staged[1] == rows):
            # overlap hit: the delta was uploaded asynchronously at the
            # end of the previous cycle and nothing moved since
            _, _, idx, av, tot, al = staged
            self.staged_hits += 1
            self.last_overlap = True
        else:
            idx, av, tot, al = self._gather_live(rows, n, r)
            self.last_overlap = False
        state = patch_cluster_state(state, idx, av, tot, al, cost0)
        # only retire the rows this patch covered; concurrent dirties
        # that land after the frozenset copy stay pending for next tick
        self._pending -= rows
        self._diverged -= rows
        self.patch_cycles += 1
        self.last_mode = self._cycle_mode = "patch"
        self.last_h2d_rows = len(rows)
        # padded rows + the always-full [N] cost ledger
        self.last_h2d_bytes = (padded_rows(len(rows), n)
                               * patch_row_bytes(r) + 4 * n)
        self.last_issued_id = id(state)
        return state, "patch"

    def adopt(self, new_state) -> None:
        """Take ownership of the solve's returned (post-placement)
        state; it becomes the resident state for the next cycle."""
        if self.enabled:
            self._state = new_state

    def stage(self) -> None:
        """Post-commit: asynchronously upload the rows dirtied by this
        cycle's commit so the next acquire() finds them already on
        device (the device_put overlaps the dispatch drain and the next
        prelude).  No-op when the resident path is idle."""
        if not self.enabled or self._state is None:
            return
        import jax

        rows = frozenset(self._pending | self._diverged)
        if not rows:
            # empty delta: acquire()'s fast path needs no upload
            self._staged = None
            return
        n = len(self.meta.nodes)
        r = self.meta.layout.num_dims
        idx, av, tot, al = self._gather_live(rows, n, r)
        self._staged = (self.meta.meta_epoch, rows,
                        jax.device_put(idx), jax.device_put(av),
                        jax.device_put(tot), jax.device_put(al))

    # ---- helpers ----

    def _gather_live(self, rows, n, r):
        """Padded (idx, avail, total, alive) read straight from the
        live ledger (meta.nodes).  Pad index = n → dropped by the
        scatter's mode="drop"."""
        p = padded_rows(len(rows), n)
        idx = np.full(p, n, np.int32)
        av = np.zeros((p, r), np.int32)
        tot = np.zeros((p, r), np.int32)
        al = np.zeros(p, bool)
        nodes = self.meta.nodes
        for k, i in enumerate(sorted(rows)):
            node = nodes[i]
            idx[k] = i
            av[k] = node.avail
            tot[k] = node.total
            al[k] = node.schedulable
        return idx, av, tot, al

    def pop_cycle_mode(self) -> str | None:
        """Mode of the acquire() this cycle performed, if any;
        consumed by _record_cycle_stats so cycles that bypass the
        resident path (backfill, packed, topo) report nothing."""
        mode, self._cycle_mode = self._cycle_mode, None
        return mode

    def overlap_share(self) -> float:
        """Share of patch cycles whose delta upload was pre-staged."""
        return self.staged_hits / self.patch_cycles if self.patch_cycles else 0.0
