"""Incremental per-cycle state for running allocations.

Round 2 rebuilt two O(running-jobs) Python structures EVERY cycle:
``_initial_cost`` (the MinCpuTimeRatioFirst cost seed, reference
NodeRater JobScheduler.h:499-516) and ``_timed_state``'s release rows
(the TimeAvailResMap feed).  Fine at 10k running jobs; fatal at the
reference's 2M-concurrent envelope (BASELINE.md).

This ledger maintains one flat numpy row per (job, node) allocation,
updated O(nodes-of-job) on start/finish/suspend/resume events; the
per-cycle products are O(rows) vectorized numpy (no Python loop over
jobs):

* ``cost0(now)``  — per-node int32 cost seed.  Bit-identical to the
  old per-job loop: the same float32 expression
  ``round(f32(remaining) * f32(cpus) * f32(SCALE) / f32(cpu_total))``
  is evaluated per row (IEEE elementwise == the scalar loop), then
  summed per node in int64.
* ``timed_rows(now, res, T)`` — (node, alloc, end_bucket) release rows
  for the backfill grid.

Suspension: a suspended job's effective end grows with wall time
(suspended time is credited back), so its REMAINING time is the
constant ``end0 - suspend_time``; rows flip to a stored constant
remaining on suspend and flip back (with the credit applied) on
resume — no per-cycle special-casing.
"""

from __future__ import annotations

import numpy as np

from cranesched_tpu.models.solver import COST_SCALE
from cranesched_tpu.ops.resources import CPU_SCALE, DIM_CPU


class RunLedger:
    """Flat SoA of live (job, node) allocation rows."""

    def __init__(self, num_dims: int, capacity: int = 256):
        self._dims = num_dims
        self._cap = capacity
        n = capacity
        self.node = np.zeros(n, np.int32)
        self.alloc = np.zeros((n, num_dims), np.int64)
        self.cpus = np.zeros(n, np.float32)       # allocated cpus
        self.cpu_total = np.ones(n, np.float32)   # node cpu capacity
        self.end_time = np.zeros(n, np.float64)   # running rows
        self.rem_const = np.zeros(n, np.float64)  # suspended rows
        self.active = np.zeros(n, bool)
        self.suspended = np.zeros(n, bool)
        self._free: list[int] = list(range(n))
        self._rows_of: dict[int, list[int]] = {}  # job_id -> rows

    def __contains__(self, job_id: int) -> bool:
        return job_id in self._rows_of

    def _grow(self) -> None:
        old = self._cap
        self._cap *= 2
        for name in ("node", "cpus", "cpu_total", "end_time",
                     "rem_const", "active", "suspended"):
            arr = getattr(self, name)
            grown = np.zeros(self._cap, arr.dtype)
            grown[:old] = arr
            setattr(self, name, grown)
        grown = np.zeros((self._cap, self._dims), np.int64)
        grown[:old] = self.alloc
        self.alloc = grown
        self.cpu_total[old:] = 1.0
        self._free.extend(range(old, self._cap))

    def add(self, job_id: int, node_ids, allocs, end_time: float,
            node_cpu_totals) -> None:
        """Register a started job: one row per (node, alloc)."""
        if job_id in self._rows_of:
            self.remove(job_id)
        rows = []
        for node_id, alloc, cpu_total in zip(node_ids, allocs,
                                             node_cpu_totals):
            if not self._free:
                self._grow()
            i = self._free.pop()
            rows.append(i)
            self.node[i] = node_id
            self.alloc[i] = alloc
            self.cpus[i] = np.float32(float(alloc[DIM_CPU]) / CPU_SCALE)
            self.cpu_total[i] = np.float32(
                max(float(cpu_total) / CPU_SCALE, 1e-9))
            self.end_time[i] = end_time
            self.active[i] = True
            self.suspended[i] = False
        self._rows_of[job_id] = rows

    def add_batch(self, entries) -> None:
        """Register a whole just-started set in one call: ``entries``
        is a list of ``add`` argument tuples.  Capacity is ensured once
        for the batch (no mid-loop doubling churn) and the row fill
        runs with hoisted array refs — the commit-phase batching
        counterpart of meta.malloc_resource_batch."""
        need = sum(len(e[1]) for e in entries)
        while len(self._free) < need:
            self._grow()
        node, alloc = self.node, self.alloc
        cpus, cpu_total = self.cpus, self.cpu_total
        end, active, susp = self.end_time, self.active, self.suspended
        free_pop = self._free.pop
        for job_id, node_ids, allocs, end_time, node_cpu_totals in \
                entries:
            if job_id in self._rows_of:
                self.remove(job_id)
            rows = []
            for node_id, a, ct in zip(node_ids, allocs,
                                      node_cpu_totals):
                i = free_pop()
                rows.append(i)
                node[i] = node_id
                alloc[i] = a
                cpus[i] = np.float32(float(a[DIM_CPU]) / CPU_SCALE)
                cpu_total[i] = np.float32(
                    max(float(ct) / CPU_SCALE, 1e-9))
                end[i] = end_time
                active[i] = True
                susp[i] = False
            self._rows_of[job_id] = rows

    def remove(self, job_id: int) -> None:
        for i in self._rows_of.pop(job_id, ()):
            self.active[i] = False
            self.suspended[i] = False
            self._free.append(i)

    def suspend(self, job_id: int, now: float) -> None:
        """Remaining time freezes at (end - now) while suspended."""
        for i in self._rows_of.get(job_id, ()):
            self.rem_const[i] = self.end_time[i] - now
            self.suspended[i] = True

    def resume(self, job_id: int, now: float) -> None:
        """The credit: the end moves out to now + frozen remaining."""
        for i in self._rows_of.get(job_id, ()):
            self.end_time[i] = now + self.rem_const[i]
            self.suspended[i] = False

    def set_end_time(self, job_id: int, end_time: float) -> None:
        """Rebase the expected release (ccontrol modify time_limit) —
        without this, every later time map would plan reservations
        against the stale release bucket.  A suspended row keeps
        freezing from the NEW end."""
        for i in self._rows_of.get(job_id, ()):
            if self.suspended[i]:
                # preserve the frozen-remaining semantics relative to
                # the new deadline: shift the stored remaining by the
                # same delta the end moved
                self.rem_const[i] += end_time - self.end_time[i]
            self.end_time[i] = end_time

    # -- the per-cycle products (vectorized, no Python per-job loop) --

    def remaining(self, now: float) -> np.ndarray:
        """Seconds left per ACTIVE row (>= 0), suspended rows constant."""
        rem = np.where(self.suspended, self.rem_const,
                       self.end_time - now)
        return np.maximum(rem, 0.0)

    def cost0(self, now: float, num_nodes: int) -> np.ndarray:
        """Per-node int32 cost seed; bit-identical to the per-job loop
        it replaces (same float32 expression per row, int64 sum)."""
        mask = self.active
        rem = self.remaining(now)[mask].astype(np.float32)
        dcost = np.round(rem * self.cpus[mask]
                         * np.float32(COST_SCALE)
                         / self.cpu_total[mask]).astype(np.int64)
        out = np.zeros(num_nodes, np.int64)
        np.add.at(out, self.node[mask], dcost)
        return out.astype(np.int32)

    def timed_rows(self, now: float, resolution: float, T: int,
                   grid=None
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(nodes[M,1], allocs[M,R], end_buckets[M]) for the backfill
        grid; overdue rows release no earlier than bucket 1.  With a
        TimeGrid the release bucket follows its (possibly geometric)
        edges; the bare (resolution, T) path is the uniform special
        case kept for existing callers."""
        mask = self.active
        M = int(mask.sum())
        if M == 0:
            return (np.full((1, 1), -1, np.int32),
                    np.zeros((1, self._dims), np.int32),
                    np.full(1, T, np.int32))
        rem = self.remaining(now)[mask]
        if grid is not None:
            eb = np.minimum(grid.release_bucket(rem), T).astype(np.int32)
        else:
            eb = np.maximum(np.ceil(rem / resolution), 1).astype(np.int32)
        return (self.node[mask].astype(np.int32).reshape(-1, 1),
                self.alloc[mask].astype(np.int32),
                eb)
