"""Durable accounting: accounts/users/QoS/txn-log in sqlite.

The reference persists the whole accounting hierarchy in MongoDB
(reference: src/CraneCtld/Database/DbClient.h:87-724 — user/account/qos
collections plus the Txn audit log) and rebuilds AccountManager from it
on boot.  Round 3 kept all of it in RAM: a ctld restart silently lost
every account, user, QoS, and audit row while the WAL faithfully
restored the jobs that reference them (VERDICT r3 missing #2).  This
module is the fix, following the same pattern as ctld/archive.py: one
sqlite file, entity rows as JSON records, synced after every successful
mutation (accounting CRUD is rare admin-path work, so a full-entity
sync per mutation is cheap and leaves no partial-write states).

Boot order matters: the store loads BEFORE WAL replay so that
``JobScheduler.recover`` can re-take QoS usage (restore_submit /
restore_run) against the restored hierarchy.
"""

from __future__ import annotations

import json
import sqlite3
import threading

import numpy as np

from cranesched_tpu.ctld.accounting import (
    Account,
    AccountManager,
    AdminLevel,
    Qos,
    User,
    UserAccountAttrs,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS qos      (name TEXT PRIMARY KEY,
                                     record TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS accounts (name TEXT PRIMARY KEY,
                                     record TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS users    (name TEXT PRIMARY KEY,
                                     record TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS txns    (seq INTEGER PRIMARY KEY AUTOINCREMENT,
                                    actor TEXT, action TEXT, target TEXT);
"""


def _arr(x):
    return None if x is None else np.asarray(x).tolist()


def _unarr(x):
    return None if x is None else np.asarray(x, np.int64)


def _qos_to_dict(q: Qos) -> dict:
    d = {
        "name": q.name, "description": q.description,
        "priority": q.priority,
        "max_jobs_per_user": q.max_jobs_per_user,
        "max_jobs_per_account": q.max_jobs_per_account,
        "max_submit_jobs_per_user": q.max_submit_jobs_per_user,
        "max_submit_jobs_per_account": q.max_submit_jobs_per_account,
        "max_jobs": q.max_jobs, "max_submit_jobs": q.max_submit_jobs,
        "max_wall": q.max_wall,
        "max_time_limit_per_job": q.max_time_limit_per_job,
        "max_cpus_per_user": (None if q.max_cpus_per_user == float("inf")
                              else q.max_cpus_per_user),
        "max_tres": _arr(q.max_tres),
        "max_tres_per_user": _arr(q.max_tres_per_user),
        "max_tres_per_account": _arr(q.max_tres_per_account),
        "preempt": sorted(q.preempt),
        "reference_count": q.reference_count,
    }
    return d


def _qos_from_dict(d: dict) -> Qos:
    return Qos(
        name=d["name"], description=d.get("description", ""),
        priority=d.get("priority", 0),
        max_jobs_per_user=d["max_jobs_per_user"],
        max_jobs_per_account=d["max_jobs_per_account"],
        max_submit_jobs_per_user=d["max_submit_jobs_per_user"],
        max_submit_jobs_per_account=d["max_submit_jobs_per_account"],
        max_jobs=d["max_jobs"], max_submit_jobs=d["max_submit_jobs"],
        max_wall=d["max_wall"],
        max_time_limit_per_job=d["max_time_limit_per_job"],
        max_cpus_per_user=(float("inf")
                           if d.get("max_cpus_per_user") is None
                           else d["max_cpus_per_user"]),
        max_tres=_unarr(d.get("max_tres")),
        max_tres_per_user=_unarr(d.get("max_tres_per_user")),
        max_tres_per_account=_unarr(d.get("max_tres_per_account")),
        preempt=set(d.get("preempt", ())),
        reference_count=d.get("reference_count", 0))


def _account_to_dict(a: Account) -> dict:
    return {
        "name": a.name, "parent": a.parent,
        "description": a.description,
        "users": sorted(a.users),
        "child_accounts": sorted(a.child_accounts),
        "allowed_partitions": (None if a.allowed_partitions is None
                               else sorted(a.allowed_partitions)),
        "allowed_qos": sorted(a.allowed_qos),
        "default_qos": a.default_qos,
        "coordinators": sorted(a.coordinators),
        "blocked": a.blocked,
    }


def _account_from_dict(d: dict) -> Account:
    return Account(
        name=d["name"], parent=d.get("parent"),
        description=d.get("description", ""),
        users=set(d.get("users", ())),
        child_accounts=set(d.get("child_accounts", ())),
        allowed_partitions=(None if d.get("allowed_partitions") is None
                            else set(d["allowed_partitions"])),
        allowed_qos=set(d.get("allowed_qos", ())),
        default_qos=d.get("default_qos", ""),
        coordinators=set(d.get("coordinators", ())),
        blocked=d.get("blocked", False))


def _user_to_dict(u: User) -> dict:
    return {
        "name": u.name, "uid": u.uid,
        "default_account": u.default_account,
        "accounts": {
            name: {"allowed_partitions":
                   (None if attrs.allowed_partitions is None
                    else sorted(attrs.allowed_partitions)),
                   "blocked": attrs.blocked}
            for name, attrs in u.accounts.items()},
        "admin_level": int(u.admin_level),
    }


def _user_from_dict(d: dict) -> User:
    return User(
        name=d["name"], uid=d.get("uid", 0),
        default_account=d.get("default_account", ""),
        accounts={
            name: UserAccountAttrs(
                allowed_partitions=(None
                                    if a.get("allowed_partitions") is None
                                    else set(a["allowed_partitions"])),
                blocked=a.get("blocked", False))
            for name, a in d.get("accounts", {}).items()},
        admin_level=AdminLevel(d.get("admin_level", 0)))


class AccountStore:
    """sqlite persistence for the AccountManager (the MongoDB-collections
    analog).  ``sync`` rewrites the three entity tables to match the
    in-memory state inside one transaction; ``append_txn`` appends to the
    audit log."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.executescript(_SCHEMA)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()

    def sync(self, mgr: AccountManager) -> None:
        with self._lock:
            cur = self._db.cursor()
            for table, items, to_dict in (
                    ("qos", mgr.qos, _qos_to_dict),
                    ("accounts", mgr.accounts, _account_to_dict),
                    ("users", mgr.users, _user_to_dict)):
                cur.execute(f"DELETE FROM {table}")
                cur.executemany(
                    f"INSERT INTO {table} (name, record) VALUES (?, ?)",
                    [(name, json.dumps(to_dict(obj),
                                       separators=(",", ":")))
                     for name, obj in items.items()])
            self._db.commit()

    def append_txn(self, entry: dict) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO txns (actor, action, target) "
                "VALUES (?, ?, ?)",
                (entry.get("actor", ""), entry.get("action", ""),
                 entry.get("target", "")))
            self._db.commit()

    def load_into(self, mgr: AccountManager) -> int:
        """Rebuild the manager's hierarchy + txn log from disk.  Returns
        the number of entities restored.  Rows loaded from disk replace
        same-named in-memory entries (config-seeded root users keep
        their entry unless the store knows better)."""
        n = 0
        with self._lock:
            for table, target, from_dict in (
                    ("qos", mgr.qos, _qos_from_dict),
                    ("accounts", mgr.accounts, _account_from_dict),
                    ("users", mgr.users, _user_from_dict)):
                for name, record in self._db.execute(
                        f"SELECT name, record FROM {table}"):
                    target[name] = from_dict(json.loads(record))
                    n += 1
            mgr.txn_log = [
                dict(actor=a, action=act, target=t)
                for a, act, t in self._db.execute(
                    "SELECT actor, action, target FROM txns "
                    "ORDER BY seq")]
        return n


def attach_store(mgr: AccountManager, store: AccountStore) -> int:
    """Load the store into the manager and arrange for every subsequent
    successful mutation to persist (every mutating AccountManager method
    records a txn as its last step, so hooking ``_txn`` is exactly the
    commit point)."""
    restored = store.load_into(mgr)
    plain_txn = mgr._txn

    def txn_and_persist(actor: str, action: str, target: str) -> None:
        plain_txn(actor, action, target)
        store.append_txn(dict(actor=actor, action=action, target=target))
        store.sync(mgr)

    mgr._txn = txn_and_persist
    mgr.store = store
    return restored
