"""Request authentication + authorization for the ctld RPC surface.

The reference authenticates every external RPC with a per-user mTLS
certificate whose identity must match the claimed uid
(CheckCertAndUIDAllowed_, reference:
src/CraneCtld/RpcService/CtldGrpcServer.h:568, used at :698+; certs are
signed via Vault, AccountManager::SignUserCertificate
AccountManager.h:171), then authorizes via RBAC admin levels.

Here the minimum viable equivalent per VERDICT r2 #6: per-user bearer
tokens issued by ctld, carried as gRPC metadata (``crane-token``),
verified on every call; mutating RPCs require ownership or an admin
identity; the accounting actor is the AUTHENTICATED identity, never a
request field.  Craned-internal RPCs authenticate with a cluster
secret mapped to the pseudo-identity ``@craned``.

Tokens persist in a JSON file (0600) so a ctld restart keeps issued
credentials — the moral analog of the reference's signed-cert
durability.  mTLS/Vault remain env-gated (no PKI in this image).
"""

from __future__ import annotations

import json
import os
import secrets
import threading

CRANED_IDENTITY = "@craned"
TOKEN_METADATA_KEY = "crane-token"


class AuthManager:
    """Token table + identity/authorization checks."""

    def __init__(self, token_file: str | None = None,
                 admins: tuple[str, ...] = ("root",),
                 accounts=None):
        self.token_file = token_file
        self.admins = set(admins) | {"root"}
        # AccountManager (optional): its RBAC admin levels also grant
        # admin here (reference: RBAC after cert check)
        self.accounts = accounts
        self._tokens: dict[str, str] = {}   # token -> user
        self._lock = threading.Lock()
        self.root_token = ""
        self.craned_token = ""
        self._load()
        self._bootstrap()

    # -- persistence --

    def _load(self) -> None:
        if not self.token_file or not os.path.exists(self.token_file):
            return
        try:
            with open(self.token_file, encoding="utf-8") as fh:
                self._tokens = dict(json.load(fh))
        except (OSError, json.JSONDecodeError, ValueError):
            self._tokens = {}

    def _save(self) -> None:
        if not self.token_file:
            return
        tmp = self.token_file + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(self._tokens, fh)
        os.replace(tmp, self.token_file)

    def _bootstrap(self) -> None:
        """Ensure a root token and the craned cluster secret exist."""
        with self._lock:
            for token, user in self._tokens.items():
                if user == "root" and not self.root_token:
                    self.root_token = token
                elif user == CRANED_IDENTITY and not self.craned_token:
                    self.craned_token = token
            changed = False
            if not self.root_token:
                self.root_token = secrets.token_urlsafe(24)
                self._tokens[self.root_token] = "root"
                changed = True
            if not self.craned_token:
                self.craned_token = secrets.token_urlsafe(24)
                self._tokens[self.craned_token] = CRANED_IDENTITY
                changed = True
            if changed:
                self._save()

    # -- identity --

    def identity(self, metadata) -> str | None:
        """Map the request's token metadata to a user; None = unauthenticated."""
        token = None
        for key, value in metadata or ():
            if key == TOKEN_METADATA_KEY:
                token = value
                break
        if not token:
            return None
        with self._lock:
            return self._tokens.get(token)

    # -- authorization --

    def is_admin(self, user: str | None) -> bool:
        if user is None:
            return False
        if user in self.admins:
            return True
        if self.accounts is not None:
            from cranesched_tpu.ctld.accounting import AdminLevel
            rec = self.accounts.users.get(user)
            if rec is not None and rec.admin_level >= AdminLevel.OPERATOR:
                return True
        return False

    def may_act_on_job(self, user: str | None, job) -> bool:
        """Owner-or-admin rule for job mutations (cancel/hold/suspend/
        steps/free)."""
        if user is None:
            return False
        return user == job.spec.user or self.is_admin(user)

    # -- issuance --

    def issue(self, actor: str | None, user: str) -> str | None:
        """Admin-only token issuance (the SignUserCertificate analog)."""
        if not self.is_admin(actor):
            return None
        token = secrets.token_urlsafe(24)
        with self._lock:
            self._tokens[token] = user
            self._save()
        return token

    def revoke(self, actor: str | None, user: str) -> int:
        """Admin-only: drop every token of ``user`` (RevokeCert analog).
        Returns the number revoked."""
        if not self.is_admin(actor):
            return -1
        with self._lock:
            doomed = [t for t, u in self._tokens.items() if u == user]
            for t in doomed:
                del self._tokens[t]
            if doomed:
                self._save()
        return len(doomed)
