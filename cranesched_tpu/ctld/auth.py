"""Request authentication + authorization for the ctld RPC surface.

The reference authenticates every external RPC with a per-user mTLS
certificate whose identity must match the claimed uid
(CheckCertAndUIDAllowed_, reference:
src/CraneCtld/RpcService/CtldGrpcServer.h:568, used at :698+; certs are
signed via Vault, AccountManager::SignUserCertificate
AccountManager.h:171), then authorizes via RBAC admin levels.

Here the minimum viable equivalent per VERDICT r2 #6: per-user bearer
tokens issued by ctld, carried as gRPC metadata (``crane-token``),
verified on every call; mutating RPCs require ownership or an admin
identity; the accounting actor is the AUTHENTICATED identity, never a
request field.

Hardening per ADVICE r3:

* The on-disk token table stores **SHA-256 hashes**, never plaintext —
  a leaked table file cannot be replayed.  Plaintext is returned exactly
  once at issuance.  The ctld's own bootstrap credentials (root + the
  legacy cluster secret) live in a separate 0600 keyring file so the
  daemon can keep using them across restarts.
* Craneds can hold **per-node identities** ``@craned/<name>`` (issued by
  an admin via ``issue_craned``); the server validates a node-bound RPC's
  ``node_id`` against the token's node name, so one compromised node can
  no longer impersonate the whole node plane.  The single shared
  ``@craned`` cluster secret remains supported for small/sim deployments
  (the documented residual risk).
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import threading

CRANED_IDENTITY = "@craned"
TOKEN_METADATA_KEY = "crane-token"


def _th(token: str) -> str:
    return hashlib.sha256(token.encode()).hexdigest()


def craned_node_of(ident: str | None) -> str | None:
    """``@craned`` -> "*" (any node), ``@craned/<name>`` -> name,
    anything else -> None (not a craned identity)."""
    if ident is None:
        return None
    if ident == CRANED_IDENTITY:
        return "*"
    if ident.startswith(CRANED_IDENTITY + "/"):
        return ident[len(CRANED_IDENTITY) + 1:]
    return None


class AuthManager:
    """Token table + identity/authorization checks."""

    def __init__(self, token_file: str | None = None,
                 admins: tuple[str, ...] = ("root",),
                 accounts=None):
        self.token_file = token_file
        self.keyring_file = token_file + ".keyring" if token_file else None
        self.admins = set(admins) | {"root"}
        # AccountManager (optional): its RBAC admin levels also grant
        # admin here (reference: RBAC after cert check)
        self.accounts = accounts
        self._tokens: dict[str, str] = {}   # sha256(token) -> identity
        self._lock = threading.Lock()
        self.root_token = ""
        self.craned_token = ""
        self._recovered_legacy_creds = False
        self._load()
        self._bootstrap()

    # -- persistence --

    def _load(self) -> None:
        if self.keyring_file and os.path.exists(self.keyring_file):
            try:
                with open(self.keyring_file, encoding="utf-8") as fh:
                    keys = json.load(fh)
                self.root_token = keys.get("root", "")
                self.craned_token = keys.get("craned", "")
            except (OSError, json.JSONDecodeError, ValueError):
                pass
        if not self.token_file or not os.path.exists(self.token_file):
            return
        try:
            with open(self.token_file, encoding="utf-8") as fh:
                raw = dict(json.load(fh))
        except (OSError, json.JSONDecodeError, ValueError):
            return
        for key, ident in raw.items():
            if len(key) == 64 and all(c in "0123456789abcdef"
                                      for c in key):
                self._tokens[key] = ident
            else:
                # legacy plaintext row (pre-hashing table): convert, and
                # recover the daemon credentials into the keyring so a
                # restart keeps working
                self._tokens[_th(key)] = ident
                if ident == "root" and not self.root_token:
                    self.root_token = key
                    self._recovered_legacy_creds = True
                elif ident == CRANED_IDENTITY and not self.craned_token:
                    self.craned_token = key
                    self._recovered_legacy_creds = True

    def _save(self) -> None:
        if not self.token_file:
            return
        tmp = self.token_file + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(self._tokens, fh)
        os.replace(tmp, self.token_file)

    def _save_keyring(self) -> None:
        if not self.keyring_file:
            return
        tmp = self.keyring_file + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump({"root": self.root_token,
                       "craned": self.craned_token}, fh)
        os.replace(tmp, self.keyring_file)

    def _bootstrap(self) -> None:
        """Ensure a root token and the craned cluster secret exist."""
        with self._lock:
            changed = self._recovered_legacy_creds  # persist migrations
            if not self.root_token:
                self.root_token = secrets.token_urlsafe(24)
                changed = True
            if not self.craned_token:
                self.craned_token = secrets.token_urlsafe(24)
                changed = True
            self._tokens.setdefault(_th(self.root_token), "root")
            self._tokens.setdefault(_th(self.craned_token),
                                    CRANED_IDENTITY)
            self._save()
            if changed:
                self._save_keyring()

    # -- identity --

    def identity(self, metadata) -> str | None:
        """Map the request's token metadata to an identity; None =
        unauthenticated."""
        token = None
        for key, value in metadata or ():
            if key == TOKEN_METADATA_KEY:
                token = value
                break
        if not token:
            return None
        with self._lock:
            return self._tokens.get(_th(token))

    # -- authorization --

    def is_admin(self, user: str | None) -> bool:
        if user is None:
            return False
        if user in self.admins:
            return True
        if self.accounts is not None:
            from cranesched_tpu.ctld.accounting import AdminLevel
            rec = self.accounts.users.get(user)
            if rec is not None and rec.admin_level >= AdminLevel.OPERATOR:
                return True
        return False

    def may_act_on_job(self, user: str | None, job) -> bool:
        """Owner-or-admin rule for job mutations (cancel/hold/suspend/
        steps/free)."""
        if user is None:
            return False
        return user == job.spec.user or self.is_admin(user)

    # -- issuance --

    def issue(self, actor: str | None, user: str) -> str | None:
        """Admin-only token issuance (the SignUserCertificate analog).
        The plaintext is returned exactly once; only its hash persists."""
        if not self.is_admin(actor):
            return None
        token = secrets.token_urlsafe(24)
        with self._lock:
            self._tokens[_th(token)] = user
            self._save()
        return token

    def issue_craned(self, actor: str | None, node_name: str
                     ) -> str | None:
        """Admin-only per-node craned token (identity
        ``@craned/<name>``); the server binds node-scoped RPCs to it."""
        if not self.is_admin(actor):
            return None
        return self.issue(actor, f"{CRANED_IDENTITY}/{node_name}")

    def revoke(self, actor: str | None, user: str) -> int:
        """Admin-only: drop every token of ``user`` (RevokeCert analog).
        Returns the number revoked.

        Revoking the bootstrap identities (``root`` / ``@craned``)
        additionally ROTATES the keyring credential — without that, the
        old plaintext still sits in the keyring file and the next
        restart's bootstrap would resurrect its hash, silently undoing
        the revocation."""
        if not self.is_admin(actor):
            return -1
        with self._lock:
            doomed = [t for t, u in self._tokens.items() if u == user]
            for t in doomed:
                del self._tokens[t]
            rotated = False
            if user == "root":
                self.root_token = secrets.token_urlsafe(24)
                self._tokens[_th(self.root_token)] = "root"
                rotated = True
            elif user == CRANED_IDENTITY:
                self.craned_token = secrets.token_urlsafe(24)
                self._tokens[_th(self.craned_token)] = CRANED_IDENTITY
                rotated = True
            if doomed or rotated:
                self._save()
            if rotated:
                self._save_keyring()
        return len(doomed)
