"""Host control plane: job lifecycle, cluster state, scheduling cycles.

The TPU-native counterpart of the reference's CraneCtld process
(reference: src/CraneCtld/).  The heavy per-cycle placement math runs on
device (models/ + parallel/); this package owns everything around it:

- ``defs``      job/step lifecycle types (reference CtldPublicDefs.h)
- ``meta``      authoritative cluster state — nodes, partitions, resource
                ledger, mid-cycle reduce events (CranedMetaContainer)
- ``scheduler`` submit → cycle → commit → dispatch → status-change → free
                (JobScheduler / ScheduleThread_)
"""

from cranesched_tpu.ctld.defs import (
    JobSpec,
    JobStatus,
    PendingReason,
    ResourceSpec,
    Step,
    StepSpec,
    StepStatus,
)
from cranesched_tpu.ctld.meta import MetaContainer, NodeMeta, Partition
from cranesched_tpu.ctld.scheduler import JobScheduler, SchedulerConfig

__all__ = [
    "JobScheduler",
    "JobSpec",
    "JobStatus",
    "MetaContainer",
    "NodeMeta",
    "Partition",
    "PendingReason",
    "ResourceSpec",
    "SchedulerConfig",
    "Step",
    "StepSpec",
    "StepStatus",
]
