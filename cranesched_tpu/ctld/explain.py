"""Pending-reason explainability: decompose WHY a job is not running.

``pending_reason`` tells an operator the last reason the cycle stamped
on a job; this module recomputes the FIRST FAILING GATE from current
state, in the exact order the scheduling cycle applies them, and names
the binding constraint — down to the resource dimension a job is
queued on or the topology block fragmentation splitting its gang.

Gate order (mirrors the cycle: PendingTable gates, then the
eligibility mask ``_mask_for`` builds from the factored class rows,
then the per-node fit the solver evaluates, then placement):

    held -> begin_time -> dependency -> license -> qos_limit
    -> eligibility (partition/include/exclude/reservation)
    -> alive -> capacity (total, never-satisfiable)
    -> resources (avail, per-dimension shortfall)
    -> topology (block fragmentation for gangs)
    -> priority (feasible now; lost the race)

The result is a JSON-friendly dict: every gate with its pass/fail and
detail (the ``checks`` list), plus the first failure's ``gate``,
``reason`` (a PendingReason value, matching what the cycle would
stamp) and human ``detail``.  Surfaced as ``cexplain <job>`` and the
``explain_json`` field of QueryJobSummary.  Read-only: the one trial
mutation (QoS run-limit malloc) is rolled back immediately under the
same lock.  Callers hold the server lock.
"""

from __future__ import annotations

import numpy as np

from cranesched_tpu.ctld.defs import PendingReason
from cranesched_tpu.ops.resources import (
    CPU_SCALE,
    DIM_CPU,
    NUM_BASE_DIMS,
    gres_key_str,
)

_BASE_DIM_NAMES = ("cpu", "mem", "memsw")


def dim_names(layout) -> list:
    """Human names for every resource dimension in layout order."""
    return list(_BASE_DIM_NAMES) + [gres_key_str(p)
                                    for p in layout.gres_pairs]


def _fmt_dim(d: int, amount: int, names: list) -> str:
    if d == DIM_CPU:
        return "%g cpu" % (amount / CPU_SCALE)
    if d < NUM_BASE_DIMS:
        return "%d MiB %s" % (amount, names[d])
    return "%d %s" % (amount, names[d])


def explain_pending(sched, job_id: int, now: float) -> dict:
    """First-failing-gate decomposition for one job.  ``sched`` is the
    JobScheduler; the caller holds the server lock."""
    out = {"job_id": int(job_id), "time": float(now), "state": "",
           "reason": "", "gate": "", "detail": "", "checks": []}
    checks = out["checks"]

    def gate(name: str, ok: bool, detail: str = "") -> bool:
        checks.append({"gate": name, "ok": bool(ok), "detail": detail})
        if not ok and not out["gate"]:
            out["gate"] = name
            out["detail"] = detail
        return ok

    def finish(reason) -> dict:
        out["reason"] = (reason.value if isinstance(reason, PendingReason)
                         else str(reason))
        return out

    job = sched.pending.get(job_id)
    if job is None:
        other = sched.running.get(job_id) or sched.history.get(job_id)
        if other is None:
            out["detail"] = "no such job"
            out["gate"] = "exists"
            return out
        out["state"] = other.status.name
        out["detail"] = "job is %s, not pending" % other.status.name
        return out
    out["state"] = job.status.name
    pr = job.pending_reason
    out["pending_reason"] = (pr.value if isinstance(pr, PendingReason)
                             else str(pr or ""))
    spec = job.spec

    if spec.array is not None:
        out["gate"] = "array_template"
        out["detail"] = ("array template: children run in its place "
                         "(%d tasks left)" % len(job.array_remaining))
        return out

    # -- PendingTable gates, in table order --
    if not gate("held", not job.held,
                "job is held (operator release required)"
                if job.held else ""):
        return finish(PendingReason.HELD)

    future = spec.begin_time is not None and spec.begin_time > now
    if not gate("begin_time", not future,
                "begin time %.0fs away" % ((spec.begin_time or 0.0) - now)
                if future else ""):
        return finish(PendingReason.BEGIN_TIME)

    dep = sched._deps_runnable(job, now)
    unmet = [str(d) for d, v in (job.dep_state or {}).items()
             if v is None or v > now]
    if not gate("dependency", dep is None,
                "waiting on job(s) %s" % ", ".join(unmet)
                if dep is not None else ""):
        return finish(dep)

    short = []
    for name, need in (spec.licenses or {}).items():
        lic = sched.licenses.licenses.get(name)
        if lic is not None and lic.free < need:
            short.append("%s: need %d, free %d" % (name, need, lic.free))
    if not gate("license", not short, "; ".join(short)):
        return finish(PendingReason.LICENSE)

    # -- QoS run limits (trial malloc, rolled back immediately) --
    qos_err = ""
    if (sched.accounts is not None and sched.account_meta is not None
            and job.qos_name and not job.run_usage_taken):
        qos = sched.accounts.qos.get(job.qos_name)
        if qos is not None:
            qos_err = sched.account_meta.check_and_malloc_run(
                spec.user, spec.account, qos, spec) or ""
            if not qos_err:
                sched.account_meta.free_run(spec.user, spec.account,
                                            job.qos_name, spec)
    if not gate("qos_limit", not qos_err, qos_err):
        return finish(PendingReason.QOS_LIMIT)

    # -- eligibility mask (what the factored [C, N] class row encodes) --
    mask = np.asarray(sched._mask_for(job, now), bool)
    if not int(mask.sum()):
        if spec.partition not in sched.meta.partitions:
            d = "unknown partition %r" % spec.partition
        else:
            pm = sched.meta.partition_mask(
                spec.partition, spec.include_nodes, spec.exclude_nodes)
            if not int(pm.sum()):
                d = ("partition/include/exclude constraints rule out "
                     "every node")
            elif spec.reservation:
                resv = sched.meta.reservations.get(spec.reservation)
                d = ("reservation %r %s" % (
                    spec.reservation,
                    "does not exist" if resv is None
                    else "is not active now or holds no nodes"))
            else:
                d = ("active reservations carve out every otherwise-"
                     "eligible node")
        gate("eligibility", False, d)
        return finish(PendingReason.CONSTRAINT)
    gate("eligibility", True, "%d eligible nodes" % int(mask.sum()))

    avail, total, alive = sched.meta.snapshot()
    eligible = mask & alive
    node_num = int(spec.node_num)
    if not gate("alive", int(eligible.sum()) >= max(node_num, 1),
                "only %d of %d eligible nodes are up/schedulable "
                "(gang needs %d)" % (int(eligible.sum()),
                                     int(mask.sum()), node_num)
                if int(eligible.sum()) < max(node_num, 1) else ""):
        return finish(PendingReason.CONSTRAINT)

    req = np.asarray(sched._job_row(job)[0], np.int64)
    names = dim_names(sched.meta.layout)
    dims = [d for d in range(req.shape[0]) if req[d] > 0]

    # capacity: could the job EVER fit on node_num eligible nodes?
    cap_ok = eligible & np.all(total >= req[None, :], axis=1)
    if int(cap_ok.sum()) < node_num:
        counts = sorted(
            (int((eligible & (total[:, d] >= req[d])).sum()), d)
            for d in dims)
        cnt, d = counts[0] if counts else (0, DIM_CPU)
        gate("capacity", False,
             "needs %s per node but only %d eligible nodes have that "
             "capacity at all (gang needs %d) — never satisfiable as "
             "the cluster stands" % (_fmt_dim(d, int(req[d]), names),
                                     cnt, node_num))
        return finish(PendingReason.CONSTRAINT)
    gate("capacity", True)

    # resources: does it fit RIGHT NOW, and which dimension binds?
    feasible = eligible & np.all(avail >= req[None, :], axis=1)
    n_fit = int(feasible.sum())
    if n_fit < node_num:
        counts = sorted(
            (int((eligible & (avail[:, d] >= req[d])).sum()), d)
            for d in dims)
        cnt, d = counts[0] if counts else (0, DIM_CPU)
        gate("resources", False,
             "waiting on %s: %d/%d needed nodes can fit now "
             "(binding dimension, %d nodes free on it)" % (
                 names[d], n_fit, node_num, cnt))
        return finish(PendingReason.RESOURCE)
    gate("resources", True, "%d nodes fit now (gang needs %d)"
         % (n_fit, node_num))

    # topology: a feasible gang may still be split across blocks
    topo = sched._active_topology()
    if topo is not None and node_num > 1:
        blocks = np.asarray(topo.block_of_node)
        inb = feasible & (blocks >= 0)
        per_block = np.bincount(blocks[inb],
                                minlength=topo.num_blocks)
        best = int(per_block.max(initial=0))
        if not gate("topology", best >= node_num,
                    "block fragmentation: largest block has %d feasible "
                    "nodes, gang needs %d (cross-block spanning fallback "
                    "may still place it)" % (best, node_num)
                    if best < node_num else ""):
            return finish(PendingReason.RESOURCE)

    out["gate"] = "priority"
    out["detail"] = ("feasible now: waiting on the priority order, the "
                     "schedule batch cut, or the next cycle")
    return finish(PendingReason.PRIORITY)
