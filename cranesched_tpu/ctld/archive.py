"""Durable job history archive.

The reference archives terminal jobs to MongoDB and only then purges
them from the embedded WAL (PersistAndTransferJobsToMongodb_, reference:
src/CraneCtld/JobScheduler.cpp:6918-6948; the accounting/history DB
surface is DbClient.h:87-724).  Round 2 shipped history as a RAM dict
that died at the first WAL compaction or restart — this module is the
fix: every finalized job is appended to a sqlite file BEFORE it can be
purged anywhere, and ``cacct``/QueryJobsInfo(include_history) read
live + archive merged.

sqlite over a bespoke file: durable (WAL journal), queryable with
indexes (user/account/partition/time), concurrent-reader safe, stdlib.
"""

from __future__ import annotations

import json
import sqlite3
import threading

from cranesched_tpu.ctld.defs import Job
from cranesched_tpu.ctld.wal import _job_from_dict, _job_to_dict

_SCHEMA = """
CREATE TABLE IF NOT EXISTS jobs (
    job_id      INTEGER PRIMARY KEY,
    name        TEXT,
    user        TEXT,
    account     TEXT,
    partition   TEXT,
    status      TEXT,
    submit_time REAL,
    start_time  REAL,
    end_time    REAL,
    exit_code   INTEGER,
    record      TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_jobs_user ON jobs (user);
CREATE INDEX IF NOT EXISTS idx_jobs_account ON jobs (account);
CREATE INDEX IF NOT EXISTS idx_jobs_partition ON jobs (partition);
CREATE INDEX IF NOT EXISTS idx_jobs_end ON jobs (end_time);
"""


class JobArchive:
    """Append-on-finalize job history (INSERT OR REPLACE keyed by
    job_id: an array parent finalizing after its children, or a
    recovery re-archive, simply refreshes the row)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.executescript(_SCHEMA)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()

    def append(self, job: Job) -> None:
        record = json.dumps(_job_to_dict(job), separators=(",", ":"))
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO jobs (job_id, name, user, "
                "account, partition, status, submit_time, start_time, "
                "end_time, exit_code, record) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                (job.job_id, job.spec.name, job.spec.user,
                 job.spec.account, job.spec.partition, job.status.name,
                 job.submit_time, job.start_time, job.end_time,
                 job.exit_code, record))
            self._db.commit()

    def query(self, job_ids=(), user: str = "", partition: str = "",
              limit: int = 0, after_job_id: int = 0,
              keyset: bool = False) -> list[Job]:
        """Filterable history read.  Default order is newest first;
        with ``keyset`` (or a nonzero ``after_job_id``) the read
        becomes a keyset page (ascending job id, strictly after the
        cursor — 0 = from the start) so pagination reaches EVERY
        archived row: applying the cursor post-hoc to a newest-first
        capped read would silently hide everything past the cap."""
        keyset = keyset or bool(after_job_id)
        clauses, params = [], []
        if job_ids:
            clauses.append("job_id IN (%s)"
                           % ",".join("?" * len(job_ids)))
            params.extend(int(j) for j in job_ids)
        if user:
            clauses.append("user = ?")
            params.append(user)
        if partition:
            clauses.append("partition = ?")
            params.append(partition)
        if after_job_id:
            clauses.append("job_id > ?")
            params.append(int(after_job_id))
        sql = "SELECT record FROM jobs"
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += (" ORDER BY job_id ASC" if keyset
                else " ORDER BY end_time DESC, job_id DESC")
        if limit:
            sql += " LIMIT ?"
            params.append(int(limit))
        with self._lock:
            rows = self._db.execute(sql, params).fetchall()
        return [_job_from_dict(json.loads(r[0])) for r in rows]

    def __contains__(self, job_id: int) -> bool:
        with self._lock:
            row = self._db.execute(
                "SELECT 1 FROM jobs WHERE job_id = ?",
                (job_id,)).fetchone()
        return row is not None

    def count(self) -> int:
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(*) FROM jobs").fetchone()[0]

    def max_job_id(self) -> int:
        """Highest archived job id (0 = empty) — seeds the id counter
        after a restart whose WAL was compacted, so reused ids can never
        INSERT OR REPLACE over history."""
        with self._lock:
            row = self._db.execute(
                "SELECT MAX(job_id) FROM jobs").fetchone()
        return int(row[0] or 0)
