"""Accounts, users, QoS: hierarchy, RBAC, and runtime limit enforcement.

TPU-native counterpart of the reference's accounting stack (reference:
src/CraneCtld/Account/AccountManager.h:33-445 — hierarchical accounts/
users/QoS CRUD with admin levels None/Operator/Admin/Root and coordinator
permissions, AccountDefs.h:180-290 — and
src/CraneCtld/Accounting/AccountMetaContainer.h:70-265 — the runtime
usage ledger that enforces submit-time limits (MaxSubmitJobs per user/
account/qos) and schedule-time limits (MaxJobs, MaxTresPerUser/Account,
MaxWall) inside the scheduling cycle).

Host-side plain Python: this is control-plane bookkeeping consulted at
submit and commit time, not per-(job × node) math — the device solve
stays unaware of it (two-phase: the host ledger is authoritative, the
same split the reference uses between NodeSelect and
CheckAndMallocMetaResource, JobScheduler.cpp:1557-1573)."""

from __future__ import annotations

import dataclasses
import enum
from typing import Iterable

import numpy as np

from cranesched_tpu.ctld.defs import JobSpec
from cranesched_tpu.ops.resources import ResourceLayout

UNLIMITED = 2**32 - 1  # matches the reference's uint32 "no limit"


class AdminLevel(enum.IntEnum):
    """Reference User::AdminLevel (AccountDefs.h:220): same-level users
    cannot control each other; higher controls lower."""

    NONE = 0
    OPERATOR = 1
    ADMIN = 2
    ROOT = 3


@dataclasses.dataclass
class Qos:
    """Reference Qos (AccountDefs.h:27-58)."""

    name: str
    description: str = ""
    priority: int = 0
    max_jobs_per_user: int = UNLIMITED
    max_jobs_per_account: int = UNLIMITED
    max_submit_jobs_per_user: int = UNLIMITED
    max_submit_jobs_per_account: int = UNLIMITED
    max_jobs: int = UNLIMITED
    max_submit_jobs: int = UNLIMITED
    max_wall: int = UNLIMITED            # seconds
    max_time_limit_per_job: int = UNLIMITED
    max_cpus_per_user: float = float("inf")
    max_tres: np.ndarray | None = None             # total in-flight
    max_tres_per_user: np.ndarray | None = None
    max_tres_per_account: np.ndarray | None = None
    # QoS names this QoS may preempt (reference Qos.preempt set)
    preempt: set[str] = dataclasses.field(default_factory=set)
    reference_count: int = 0


@dataclasses.dataclass
class Account:
    """Reference Account (AccountDefs.h:180)."""

    name: str
    parent: str | None = None
    description: str = ""
    users: set[str] = dataclasses.field(default_factory=set)
    child_accounts: set[str] = dataclasses.field(default_factory=set)
    allowed_partitions: set[str] | None = None     # None = all
    allowed_qos: set[str] = dataclasses.field(default_factory=set)
    default_qos: str = ""
    coordinators: set[str] = dataclasses.field(default_factory=set)
    blocked: bool = False


@dataclasses.dataclass
class UserAccountAttrs:
    """Reference User::AttrsInAccount (AccountDefs.h:235)."""

    allowed_partitions: set[str] | None = None     # None = inherit account
    blocked: bool = False


@dataclasses.dataclass
class User:
    """Reference User (AccountDefs.h:208)."""

    name: str
    uid: int = 0
    default_account: str = ""
    accounts: dict[str, UserAccountAttrs] = dataclasses.field(
        default_factory=dict)
    admin_level: AdminLevel = AdminLevel.NONE


class AccountingError(Exception):
    pass


class AccountManager:
    """Hierarchical account/user/QoS registry + permission checks
    (reference AccountManager.h — CheckUserPermissionToPartition :120s,
    CheckQosLimitOnJob, coordinator/admin RBAC)."""

    def __init__(self):
        self.accounts: dict[str, Account] = {}
        self.users: dict[str, User] = {}
        self.qos: dict[str, Qos] = {}
        self.txn_log: list[dict] = []   # audit (reference Txn,
                                        # AccountDefs.h:345)

    def _txn(self, actor: str, action: str, target: str) -> None:
        self.txn_log.append(dict(actor=actor, action=action, target=target))

    # ---- RBAC ----

    def _level(self, actor: str) -> AdminLevel:
        user = self.users.get(actor)
        return user.admin_level if user else AdminLevel.NONE

    def has_admin(self, actor: str,
                  needed: AdminLevel = AdminLevel.OPERATOR) -> bool:
        return self._level(actor) >= needed

    def is_coordinator(self, actor: str, account: str) -> bool:
        """Coordinators manage their account subtree."""
        acc = self.accounts.get(account)
        while acc is not None:
            if actor in acc.coordinators:
                return True
            acc = self.accounts.get(acc.parent) if acc.parent else None
        return False

    def can_manage(self, actor: str, account: str) -> bool:
        return self.has_admin(actor) or self.is_coordinator(actor, account)

    # ---- QoS CRUD ----

    def add_qos(self, actor: str, qos: Qos) -> None:
        if not self.has_admin(actor):
            raise AccountingError("permission denied")
        if qos.name in self.qos:
            raise AccountingError(f"qos {qos.name} exists")
        self.qos[qos.name] = qos
        self._txn(actor, "add_qos", qos.name)

    def delete_qos(self, actor: str, name: str) -> None:
        if not self.has_admin(actor):
            raise AccountingError("permission denied")
        q = self.qos.get(name)
        if q is None:
            raise AccountingError(f"qos {name} not found")
        if q.reference_count > 0:
            raise AccountingError(f"qos {name} is in use")
        del self.qos[name]
        self._txn(actor, "delete_qos", name)

    def modify_qos(self, actor: str, name: str, **fields) -> None:
        if not self.has_admin(actor):
            raise AccountingError("permission denied")
        q = self.qos.get(name)
        if q is None:
            raise AccountingError(f"qos {name} not found")
        for k, v in fields.items():
            if not hasattr(q, k):
                raise AccountingError(f"qos has no field {k}")
            setattr(q, k, v)
        self._txn(actor, "modify_qos", name)

    # ---- account CRUD ----

    def add_account(self, actor: str, account: Account) -> None:
        if not self.has_admin(actor):
            raise AccountingError("permission denied")
        if account.name in self.accounts:
            raise AccountingError(f"account {account.name} exists")
        if account.parent is not None:
            parent = self.accounts.get(account.parent)
            if parent is None:
                raise AccountingError(
                    f"parent account {account.parent} not found")
            parent.child_accounts.add(account.name)
        for q in account.allowed_qos:
            if q not in self.qos:
                raise AccountingError(f"qos {q} not found")
            self.qos[q].reference_count += 1
        self.accounts[account.name] = account
        self._txn(actor, "add_account", account.name)

    def delete_account(self, actor: str, name: str) -> None:
        if not self.has_admin(actor):
            raise AccountingError("permission denied")
        acc = self.accounts.get(name)
        if acc is None:
            raise AccountingError(f"account {name} not found")
        if acc.child_accounts or acc.users:
            raise AccountingError(f"account {name} is not empty")
        if acc.parent and acc.parent in self.accounts:
            self.accounts[acc.parent].child_accounts.discard(name)
        for q in acc.allowed_qos:
            if q in self.qos:
                self.qos[q].reference_count -= 1
        del self.accounts[name]
        self._txn(actor, "delete_account", name)

    def block_account(self, actor: str, name: str,
                      blocked: bool = True) -> None:
        if not self.can_manage(actor, name):
            raise AccountingError("permission denied")
        if name not in self.accounts:
            raise AccountingError(f"account {name} not found")
        self.accounts[name].blocked = blocked
        self._txn(actor, "block_account", name)

    # ---- user CRUD ----

    def add_user(self, actor: str, user: User, account: str) -> None:
        if not self.can_manage(actor, account):
            raise AccountingError("permission denied")
        acc = self.accounts.get(account)
        if acc is None:
            raise AccountingError(f"account {account} not found")
        existing = self.users.setdefault(user.name, user)
        existing.accounts.setdefault(account, UserAccountAttrs())
        if not existing.default_account:
            existing.default_account = account
        acc.users.add(user.name)
        self._txn(actor, "add_user", f"{user.name}@{account}")

    def remove_user(self, actor: str, name: str, account: str) -> None:
        if not self.can_manage(actor, account):
            raise AccountingError("permission denied")
        user = self.users.get(name)
        if user is None or account not in user.accounts:
            raise AccountingError(f"user {name} not in {account}")
        del user.accounts[account]
        self.accounts[account].users.discard(name)
        self._txn(actor, "remove_user", f"{name}@{account}")

    def set_admin_level(self, actor: str, name: str,
                        level: AdminLevel) -> None:
        # users with the same level cannot control each other
        # (AccountDefs.h:212-219)
        target = self.users.get(name)
        if target is None:
            raise AccountingError(f"user {name} not found")
        if self._level(actor) <= max(target.admin_level, level) and \
                self._level(actor) < AdminLevel.ROOT:
            raise AccountingError("permission denied")
        target.admin_level = level
        self._txn(actor, "set_admin_level", f"{name}={level.name}")

    def block_user(self, actor: str, name: str, account: str,
                   blocked: bool = True) -> None:
        if not self.can_manage(actor, account):
            raise AccountingError("permission denied")
        user = self.users.get(name)
        if user is None or account not in user.accounts:
            raise AccountingError(f"user {name} not in {account}")
        user.accounts[account].blocked = blocked
        self._txn(actor, "block_user", f"{name}@{account}")

    # ---- submit-time resolution (reference CheckUserPermission... +
    #      qos resolution in AcquireJobAttributes) ----

    def resolve_submit(self, user_name: str, account_name: str,
                       partition: str, qos_name: str | None
                       ) -> tuple[Qos | None, str]:
        """Returns (qos, error).  qos None + error "" means accounting is
        not configured for this user (open system, reference behavior
        with no accounting DB)."""
        if not self.users and not self.accounts:
            return None, ""              # accounting disabled
        user = self.users.get(user_name)
        if user is None:
            return None, f"user {user_name} unknown"
        attrs = user.accounts.get(account_name)
        if attrs is None:
            return None, f"user {user_name} not in account {account_name}"
        if attrs.blocked:
            return None, f"user {user_name} blocked in {account_name}"
        acc = self.accounts.get(account_name)
        if acc is None:
            return None, f"account {account_name} unknown"
        if acc.blocked:
            return None, f"account {account_name} blocked"
        allowed_parts = (attrs.allowed_partitions
                         if attrs.allowed_partitions is not None
                         else acc.allowed_partitions)
        if allowed_parts is not None and partition not in allowed_parts:
            return None, (f"partition {partition} not allowed for "
                          f"{user_name}@{account_name}")
        name = qos_name or acc.default_qos
        if not name:
            return None, ""              # no qos configured
        if acc.allowed_qos and name not in acc.allowed_qos:
            return None, f"qos {name} not allowed for {account_name}"
        qos = self.qos.get(name)
        if qos is None:
            return None, f"qos {name} unknown"
        return qos, ""


@dataclasses.dataclass
class _Usage:
    jobs: int = 0          # running
    submit_jobs: int = 0   # pending + running
    tres: np.ndarray | None = None

    def tres_vec(self, dims: int) -> np.ndarray:
        if self.tres is None:
            self.tres = np.zeros(dims, np.int64)
        return self.tres


class AccountMetaContainer:
    """Runtime usage ledger + limit enforcement (reference
    AccountMetaContainer.h:70-265: TryMallocMetaSubmitResource :86 at
    submit, CheckAndMallocMetaResource :113 at schedule commit,
    CheckRunLimits_ :239)."""

    def __init__(self, layout: ResourceLayout | None = None):
        self.layout = layout or ResourceLayout()
        self._qos: dict[str, _Usage] = {}
        self._user: dict[tuple[str, str], _Usage] = {}   # (qos, user)
        self._acct: dict[tuple[str, str], _Usage] = {}   # (qos, account)

    def _u(self, d, key) -> _Usage:
        if key not in d:
            d[key] = _Usage()
        return d[key]

    @staticmethod
    def _job_tres(spec: JobSpec, layout: ResourceLayout) -> np.ndarray:
        per_node = spec.res.encode(layout).astype(np.int64)
        if spec.task_res is not None:
            ntasks = spec.ntasks or spec.node_num
            return (per_node * spec.node_num
                    + spec.task_res.encode(layout).astype(np.int64)
                    * ntasks)
        return per_node * spec.node_num

    # ---- submit-time (TryMallocMetaSubmitResource) ----

    def try_malloc_submit(self, user: str, account: str, qos: Qos,
                          spec: JobSpec) -> str:
        """Returns "" on success (slots taken), else the refusal reason."""
        if spec.time_limit > qos.max_time_limit_per_job:
            return "time limit exceeds qos MaxTimeLimitPerJob"
        if spec.time_limit > qos.max_wall:
            return "time limit exceeds qos MaxWall"
        uq = self._u(self._user, (qos.name, user))
        aq = self._u(self._acct, (qos.name, account))
        qq = self._u(self._qos, qos.name)
        if uq.submit_jobs >= qos.max_submit_jobs_per_user:
            return "qos MaxSubmitJobsPerUser reached"
        if aq.submit_jobs >= qos.max_submit_jobs_per_account:
            return "qos MaxSubmitJobsPerAccount reached"
        if qq.submit_jobs >= qos.max_submit_jobs:
            return "qos MaxSubmitJobs reached"
        uq.submit_jobs += 1
        aq.submit_jobs += 1
        qq.submit_jobs += 1
        return ""

    def free_submit(self, user: str, account: str, qos_name: str) -> None:
        for usage in (self._user.get((qos_name, user)),
                      self._acct.get((qos_name, account)),
                      self._qos.get(qos_name)):
            if usage is not None and usage.submit_jobs > 0:
                usage.submit_jobs -= 1

    # ---- schedule-time (CheckAndMallocMetaResource / CheckRunLimits_) ----

    def check_and_malloc_run(self, user: str, account: str, qos: Qos,
                             spec: JobSpec) -> str:
        """Returns "" on success (run usage taken), else the reason."""
        dims = self.layout.num_dims
        tres = self._job_tres(spec, self.layout)
        uq = self._u(self._user, (qos.name, user))
        aq = self._u(self._acct, (qos.name, account))
        qq = self._u(self._qos, qos.name)
        if uq.jobs >= qos.max_jobs_per_user:
            return "qos MaxJobsPerUser reached"
        if aq.jobs >= qos.max_jobs_per_account:
            return "qos MaxJobsPerAccount reached"
        if qq.jobs >= qos.max_jobs:
            return "qos MaxJobs reached"
        from cranesched_tpu.ops.resources import CPU_SCALE, DIM_CPU
        if (uq.tres_vec(dims)[DIM_CPU] + tres[DIM_CPU]) / CPU_SCALE > \
                qos.max_cpus_per_user:
            return "qos MaxCpusPerUser reached"
        if qos.max_tres_per_user is not None and np.any(
                uq.tres_vec(dims) + tres > qos.max_tres_per_user):
            return "qos MaxTresPerUser reached"
        if qos.max_tres_per_account is not None and np.any(
                aq.tres_vec(dims) + tres > qos.max_tres_per_account):
            return "qos MaxTresPerAccount reached"
        if qos.max_tres is not None and np.any(
                qq.tres_vec(dims) + tres > qos.max_tres):
            return "qos MaxTres reached"
        for usage in (uq, aq, qq):
            usage.jobs += 1
            usage.tres_vec(dims)[:] += tres
        return ""

    # ---- crash recovery: usage is derived state, rebuilt from the WAL
    #      replay without re-running the checks (the slots were already
    #      granted before the crash) ----

    def restore_submit(self, user: str, account: str,
                       qos_name: str) -> None:
        for usage in (self._u(self._user, (qos_name, user)),
                      self._u(self._acct, (qos_name, account)),
                      self._u(self._qos, qos_name)):
            usage.submit_jobs += 1

    def restore_run(self, user: str, account: str, qos_name: str,
                    spec: JobSpec) -> None:
        tres = self._job_tres(spec, self.layout)
        dims = self.layout.num_dims
        for usage in (self._u(self._user, (qos_name, user)),
                      self._u(self._acct, (qos_name, account)),
                      self._u(self._qos, qos_name)):
            usage.jobs += 1
            usage.tres_vec(dims)[:] += tres

    def free_run(self, user: str, account: str, qos_name: str,
                 spec: JobSpec) -> None:
        tres = self._job_tres(spec, self.layout)
        dims = self.layout.num_dims
        for usage in (self._user.get((qos_name, user)),
                      self._acct.get((qos_name, account)),
                      self._qos.get(qos_name)):
            if usage is not None and usage.jobs > 0:
                usage.jobs -= 1
                usage.tres_vec(dims)[:] = np.maximum(
                    usage.tres_vec(dims) - tres, 0)
