"""JobScheduler: the submit → cycle → dispatch → status-change loop.

TPU-native counterpart of the reference's JobScheduler/ScheduleThread_
(reference: src/CraneCtld/JobScheduler.cpp — submit path
SubmitJobToScheduler :3405, the 1 Hz scheduling cycle :1321-1981, batched
status changes CleanJobStatusChangeQueueCb_ :5318-5488, requeue
:6950-6965).  Differences by design, not omission:

* The per-cycle placement math (priority sort + greedy node selection) is
  a jit-compiled device solve (models/priority + models/solver, or the
  node-sharded parallel/sharded at scale), not a C++ loop.
* The cycle is an explicit ``schedule_cycle(now)`` call driven by the
  daemon loop (or tests), with virtual time — no hidden threads.  The
  reference's nine worker threads exist to multiplex queues onto cores;
  here the queues are drained inline and the heavy math is on device.
* Two-phase commit is kept: the device solve sees a snapshot; the host
  ledger (MetaContainer) is authoritative at commit and re-validates
  against mid-cycle ResReduceEvents, exactly like NodeSelect's
  post-validation (cpp:1466-1540).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Callable, Iterable

import numpy as np
import jax.numpy as jnp

from cranesched_tpu.ctld.defs import (
    DEP_NEVER,
    DepType,
    Job,
    JobSpec,
    JobStatus,
    PendingReason,
    Step,
    StepSpec,
    StepStatus,
)
from cranesched_tpu.ctld.accounting import AccountMetaContainer
from cranesched_tpu.ctld.licenses import LicenseManager
from cranesched_tpu.ctld.meta import MetaContainer
from cranesched_tpu.ctld.pending_table import (
    GATE_BEGIN,
    GATE_CANDIDATE,
    GATE_DEP,
    GATE_DEP_NEVER,
    GATE_HELD,
    GATE_LICENSE,
    PendingTable,
)
from cranesched_tpu.ctld.resident import ResidentClusterState
from cranesched_tpu.ctld.runledger import RunLedger
from cranesched_tpu.models.priority import (
    PendingPriorityAttrs,
    PriorityWeights,
    RunningPriorityAttrs,
    multifactor_priority,
    priority_order,
)
from cranesched_tpu.models.solver import (
    COST_SCALE,
    REASON_CONSTRAINT,
    REASON_RESOURCE,
    ClusterState,
    FactoredJobBatch,
    JobBatch,
    Placements,
    make_cluster_state,
    solve_greedy,
    solve_greedy_donating,
)
from cranesched_tpu.models.packing import PackedJobBatch, solve_packed
from cranesched_tpu.models.solver_time import (
    TimeGrid,
    TimedJobBatch,
    make_timed_state,
    solve_backfill,
)
from cranesched_tpu.obs import REGISTRY as _OBS
from cranesched_tpu.obs import introspect
from cranesched_tpu.obs.events import EventLog
from cranesched_tpu.obs.flight import FlightRecorder
from cranesched_tpu.obs.jobtrace import JobTraceRecorder
from cranesched_tpu.obs.slo import SloEngine
from cranesched_tpu.obs.trace import CycleTraceRing, solve_span
from cranesched_tpu.topo.place import solve_greedy_topo
from cranesched_tpu.ops.resources import CPU_SCALE, DIM_CPU, DIM_MEM

# cycle-plane metrics (naming: ARCHITECTURE.md "Observability")
_MET_CYCLES = _OBS.counter(
    "crane_cycles_total", "scheduling cycles completed")
_MET_PHASE = _OBS.histogram(
    "crane_cycle_phase_seconds",
    "wall time per cycle phase "
    "(label phase=prelude|solve|commit|dispatch)")
_MET_COMMIT_BATCH = _OBS.histogram(
    "crane_commit_batch_jobs", "jobs committed per _commit batch",
    buckets=tuple(float(2 ** k) for k in range(18)))
_MET_LOCK = _OBS.histogram(
    "crane_lock_held_seconds",
    "server-lock-held time per cycle (prelude + commit, never solve)")
_MET_SOLVE = _OBS.histogram(
    "crane_solve_seconds",
    "lock-released solve closure time (label backend)")
_MET_STARTED = _OBS.counter(
    "crane_jobs_started_total", "jobs started by the scheduler")
_MET_PREEMPTED = _OBS.counter(
    "crane_preempted_total", "running jobs evicted by preemption")
_MET_PENDING = _OBS.gauge(
    "crane_pending_jobs",
    "pending queue depth (updated on submit/finish events)")
_MET_RUNNING = _OBS.gauge(
    "crane_running_jobs",
    "running job count (updated on start/finish events)")
_MET_SKIPS = _OBS.counter(
    "crane_cycle_skips_total",
    "cycles short-circuited by the no-op fingerprint (label reason)")
_MET_TOPO_FRAG = _OBS.gauge(
    "crane_topo_fragmentation",
    "free-capacity fragmentation per topology level "
    "(1 - largest free group / total free; label level)")
_MET_TOPO_CROSS = _OBS.counter(
    "crane_topo_cross_block_gangs_total",
    "gangs placed across blocks by the spanning fallback")
_MET_H2D = _OBS.counter(
    "crane_solver_h2d_bytes_total",
    "host->device bytes shipped for the solve's cluster state "
    "(label mode=rebuild|patch)")
_MET_RESIDENT = _OBS.counter(
    "crane_resident_cycles_total",
    "immediate-fit cycles served by the device-resident state "
    "(label mode=rebuild|patch)")
_MET_OVERLAP = _OBS.gauge(
    "crane_resident_patch_overlap_share",
    "share of resident patch cycles whose delta upload was pre-staged "
    "(double-buffered) by the previous cycle")

_REASON_MAP = {
    REASON_RESOURCE: PendingReason.RESOURCE,
    REASON_CONSTRAINT: PendingReason.CONSTRAINT,
}

# PendingTable gate code -> the pending reason the old Python candidate
# loop would have written for the same blocked job
_GATE_REASON = {
    GATE_HELD: PendingReason.HELD,
    GATE_BEGIN: PendingReason.BEGIN_TIME,
    GATE_DEP: PendingReason.DEPENDENCY,
    GATE_DEP_NEVER: PendingReason.DEPENDENCY_NEVER_SATISFIED,
    GATE_LICENSE: PendingReason.LICENSE,
}


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Reference scheduler knobs (etc/config.yaml:97-112,190-198;
    CtldPublicDefs.h:42-60)."""

    schedule_batch_size: int = 100_000
    pending_queue_max_size: int = 900_000
    max_nodes_per_job: int = 8          # static gang bound of the solve
    priority_type: str = "multifactor"  # or "basic" (FIFO)
    priority_weights: PriorityWeights = dataclasses.field(
        default_factory=PriorityWeights)
    max_requeue_count: int = 3
    # time axis: duration-aware fit + conservative backfill (reference
    # TimeAvailResMap + EarliestStartSubsetSelector; the grid analog of
    # Slurm's bf_resolution).  Each solve step costs O(N * time_buckets
    # * R) vs O(N * R) for the immediate solver — ~time_buckets× heavier.
    # At very large scale either lower time_buckets or set backfill=False
    # (Slurm similarly separates its sched and bf passes).
    backfill: bool = True
    time_resolution: float = 60.0       # seconds per bucket
    time_buckets: int = 64              # horizon = resolution * buckets
    # optional geometric far horizon (TimeGrid, models/solver_time.py):
    # None keeps the uniform resolution*buckets grid; a value larger
    # than resolution*buckets stretches the tail buckets geometrically
    # so e.g. 7-day jobs reserve at day scale instead of saturating the
    # last uniform bucket (the 60x over-reservation fixed in round 6)
    time_horizon: float | None = None
    # bounded backfill lookahead (the Slurm bf_max_job_test analog,
    # default 1000; the reference bounds the same scan with
    # ScheduledBatchSize): cycles larger than this run the timed solve
    # only for the top-priority slice and place the tail with the fast
    # immediate solver against the MIN-over-horizon availability — a
    # tail job that fits the tightest bucket can never violate any
    # reservation, so the split is strictly conservative.  Measured at
    # 100k x 10k the full timed solve is ~15 s/cycle on TPU
    # (BENCH_r04_backfill) while the split fits the 1 s cycle budget.
    backfill_max_jobs: int = 1024
    # real node plane: a craned that misses pings for this long is down
    # (reference kCranedTimeoutSec = 30, PublicHeader.h:146)
    craned_timeout: float = 30.0
    # QoS preemption (reference TryPreempt_, JobScheduler.cpp:6378-6505;
    # config PreemptType/PreemptMode etc/config.yaml:280-290):
    # "off" | "requeue" | "cancel" — what happens to the victims
    preempt_mode: str = "off"
    # bounded ring of structured per-cycle traces (obs/trace.py),
    # queryable via QueryStats / `cstats --cycles`
    cycle_trace_ring: int = 64
    # solver backend for immediate-fit cycles: "auto" prefers the native
    # C++ treap solver (bit-identical, ~fastest single-host) and falls
    # back to the device scan; "device" forces the JAX scan; "native"
    # requires the C++ library; "pallas" runs the single-kernel TPU
    # solve (models/pallas_solver.py — interpret mode off-TPU, so only
    # useful for tests there); "sharded" runs the node-axis-sharded
    # multi-chip solve over every visible device
    # (parallel/sharded.py).  Backfill and packed cycles always run on
    # device.  All five are bit-identical on placements.
    solver: str = "auto"
    # post-commit dispatch fan-out width (YAML ``DispatchWorkers``).
    # None sizes the dispatcher pool from the cluster:
    # max(8, nodes // 64), capped at 128 — a 10k-node cluster gets 128
    # concurrent pushes instead of the historical hardcoded 8.
    dispatch_workers: int | None = None
    # incremental cycle state (YAML ``Incremental``): the PendingTable
    # candidate pass, delta meta snapshots, and the no-op-cycle
    # fingerprint short-circuit.  False restores the from-scratch
    # rebuild every cycle — the parity oracle and bench baseline.
    incremental: bool = True
    # event-driven loop (YAML ``CycleIdleSleep``): the longest the
    # server's cycle loop may sleep when the no-op fingerprint is armed
    # and no event arrives.  Bounds staleness of anything outside the
    # event/edge model (e.g. remote license syncs, which deliberately
    # do not kick the loop).
    cycle_idle_sleep: float = 30.0
    # device-resident cluster state (YAML ``ResidentState``): keep the
    # immediate-fit solve's ClusterState buffers on device across
    # cycles and scatter-patch only the dirty rows instead of
    # re-uploading [N, R] every tick (ctld/resident.py).  Effective for
    # solver "device" and "pallas" and only with ``incremental`` (the
    # dirty feed is the delta-snapshot machinery); False rebuilds from
    # the host snapshot every cycle — the parity oracle.
    resident_state: bool = True
    # S-stream Pallas solve knobs (YAML ``MaxStreams``/``BlockJobs``),
    # fed to plan_streams / solve_greedy_pallas_auto.  Defaults match
    # the shipped stream profile; re-measure on new hardware with
    # tools/kstream.py (writes profiles/<device>_STREAMS_PROFILE.md and
    # prints the YAML to pin).
    max_streams: int = 4
    block_jobs: int = 256
    # per-job lifecycle tracing (YAML ``Observability: JobTrace``):
    # event-sourced timelines (obs/jobtrace.py) stamped at submit /
    # candidate / commit / durable-dispatch / terminal edges plus the
    # craned-side spans shipped back with StepStatusChange.  False
    # removes every stamp from the hot path.
    job_trace: bool = True
    # bounded timeline store size (live + closed, each)
    job_trace_capacity: int = 4096
    # SLO targets over trace edges (YAML ``Observability: SLO``),
    # frozen-dataclass form: tuple of
    # (name, from_edge, to_edge, percentile, target_seconds, windows)
    slo: tuple = ()

    def __post_init__(self):
        if self.preempt_mode not in ("off", "requeue", "cancel"):
            raise ValueError(
                f"preempt_mode must be off|requeue|cancel, "
                f"got {self.preempt_mode!r}")
        if self.solver not in ("auto", "device", "native", "pallas",
                               "sharded"):
            raise ValueError(
                "solver must be auto|device|native|pallas|sharded, "
                f"got {self.solver!r}")
        if self.max_streams < 1 or self.block_jobs < 1:
            raise ValueError(
                f"max_streams/block_jobs must be >= 1, got "
                f"{self.max_streams}/{self.block_jobs}")


@dataclasses.dataclass
class StatusChange:
    """One craned→ctld step status report (reference StepStatusChange
    queue, JobScheduler.cpp:5294)."""

    job_id: int
    status: JobStatus
    exit_code: int
    time: float
    # incarnation (requeue_count) the report belongs to; None = trust the
    # caller (pre-aggregated).  A report queued for incarnation k must not
    # finalize incarnation k+1 — a node death can requeue + re-place the
    # job between the enqueue and the drain.
    incarnation: int | None = None


class _ObservedDict(dict):
    """dict with membership hooks: every insert/removal notifies the
    scheduler so derived indexes (the PendingTable, the template and
    alloc_only sets, the queue-depth gauges, the event-loop kick) stay
    in sync at the MUTATION SITE instead of being rebuilt per cycle.
    Hooks fire after the dict mutation, with the key's final value."""

    def __init__(self, on_set, on_del):
        super().__init__()
        self._on_set = on_set
        self._on_del = on_del

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self._on_set(key, value)

    def __delitem__(self, key):
        value = super().pop(key)
        self._on_del(key, value)

    def pop(self, key, *default):
        if key in self:
            value = super().pop(key)
            self._on_del(key, value)
            return value
        if default:
            return default[0]
        raise KeyError(key)

    def popitem(self):
        key, value = super().popitem()
        self._on_del(key, value)
        return key, value

    def clear(self):
        while self:
            self.popitem()

    def update(self, *args, **kwargs):
        for key, value in dict(*args, **kwargs).items():
            self[key] = value

    def setdefault(self, key, default=None):
        if key not in self:
            self[key] = default
        return super().__getitem__(key)


class _MaskTable:
    """Device-resident ``[C, N]`` eligibility-row table — the factored
    form of the per-job ``part_mask``.

    Rows are pure functions of a job's *class key* (partition +
    include/exclude lists + reservation identity/activity + the set of
    reservations overlapping the job's runtime window — see
    ``JobScheduler._class_key``), deduplicated by CONTENT so distinct
    keys with identical masks share one row.  The device table is
    bucketed (power-of-two row count, all-False padding) so solver jit
    shapes stay stable as classes appear, and row 0 is ALWAYS the
    all-False row: padding jobs gather an empty mask, exactly matching
    the dense builder's zero rows.

    Invalidation: a ``resv_epoch`` bump or node-count change drops
    everything (the same rule as the scalar ``_mask_cache``); within an
    epoch rows never mutate, so the [C, N] host→device transfer happens
    only when a NEW class appears — the per-cycle upload shrinks from
    O(J·N) to O(J + changed rows).
    """

    def __init__(self):
        self.epoch = -1
        self.num_nodes = -1
        # monotonic reset counter: PendingTable rows cache their class
        # id stamped with this, so a reset invalidates every cached id
        # without touching the rows
        self.generation = 0
        self.key_to_class: dict[tuple, int] = {}
        self._bytes_to_class: dict[bytes, int] = {}
        self.rows: list[np.ndarray] = []
        self.rows_np: np.ndarray | None = None  # padded [Cpad, N] mirror
        self.table = None                       # jnp twin of rows_np
        self.disjoint = True      # no node is in 2+ rows (see node_class)
        self._node_class: np.ndarray | None = None
        self.h2d_rows = 0         # rows shipped to device (observability)
        self.refreshes = 0        # full invalidations (observability)

    def reset(self, epoch: int, num_nodes: int) -> None:
        self.epoch = epoch
        self.num_nodes = num_nodes
        self.generation += 1
        self.key_to_class.clear()
        self._bytes_to_class.clear()
        row0 = np.zeros(max(num_nodes, 1), bool)
        self.rows = [row0]
        self._bytes_to_class[row0.tobytes()] = 0
        self.rows_np = None
        self.table = None
        self.disjoint = True
        self._node_class = None
        self.refreshes += 1

    def class_for(self, key: tuple, row_fn) -> int:
        """Class id for ``key``; ``row_fn()`` builds the [N] bool row
        only on first sight of the key."""
        cid = self.key_to_class.get(key)
        if cid is None:
            row = np.ascontiguousarray(row_fn(), dtype=bool)
            b = row.tobytes()
            cid = self._bytes_to_class.get(b)
            if cid is None:
                cid = len(self.rows)
                self.rows.append(row)
                self._bytes_to_class[b] = cid
                self.rows_np = None   # grew: rebuild the mirrors lazily
                self.table = None
                self._node_class = None
            self.key_to_class[key] = cid
        return cid

    def tables(self):
        """``(host [Cpad, N] bool, device twin)`` — padded to a
        power-of-two row count with all-False rows."""
        if self.rows_np is None or self.table is None:
            c = 1
            while c < len(self.rows):
                c *= 2
            padded = np.zeros((c, self.rows[0].shape[0]), bool)
            padded[: len(self.rows)] = self.rows
            self.rows_np = padded
            self.disjoint = bool(
                (padded.sum(axis=0, dtype=np.int64) <= 1).all())
            self.table = jnp.asarray(padded)
            self.h2d_rows += len(self.rows)
        return self.rows_np, self.table

    def node_class(self) -> np.ndarray | None:
        """Per-node owner class id iff the rows are pairwise disjoint —
        then ``rows[c] == (node_class == c)`` exactly, which feeds the
        native solver's partition-id fast path (no dense [J, N] mask
        materialized at all).  Unowned nodes get a label no job carries.
        None when rows overlap (caller falls back to a dense gather)."""
        rows_np, _ = self.tables()
        if not self.disjoint:
            return None
        if self._node_class is None:
            owner = np.full(rows_np.shape[1], rows_np.shape[0], np.int32)
            cls, node = np.nonzero(rows_np)
            owner[node] = cls
            self._node_class = owner
        return self._node_class


class JobScheduler:
    """Owns the pending/running maps and drives scheduling cycles.

    ``dispatch`` is called with (job, node_ids) for every committed
    placement — the seam where the real system fans out AllocJobs RPCs and
    tests plug a simulated cluster (the reference's testing seam is the
    same shape: intents out, transport elsewhere).
    """

    def __init__(self, meta: MetaContainer,
                 config: SchedulerConfig | None = None,
                 dispatch: Callable[[Job, list[int]], None] | None = None,
                 wal=None, accounts=None, submit_hook=None,
                 archive=None):
        self.meta = meta
        self.config = config or SchedulerConfig()
        self.dispatch = dispatch or (lambda job, nodes: None)
        # optional batched dispatch seam (GrpcDispatcher.wire sets it):
        # one call for the whole post-commit ring with per-craned
        # coalescing; None falls back to per-job self.dispatch
        self.dispatch_batch = None
        # ordered post-commit dispatch ring: (job, node_ids) queued
        # under the lock by _commit/_commit_preemption, drained with
        # the lock RELEASED by the cycle's final phase — and only after
        # the WAL group's fsync returned (durable-before-dispatch)
        self._dispatch_ring: collections.deque = collections.deque()
        self.wal = wal
        # HA fencing: this ctld's leadership term, stamped into every
        # craned push/registration by the dispatcher + server so craneds
        # can reject a deposed leader's in-flight RPCs after failover.
        # 0 = HA not configured (craneds skip the check).
        self.fencing_epoch = 0
        # durable history (ctld/archive.JobArchive): terminal jobs are
        # appended BEFORE any WAL purge can drop them (reference
        # PersistAndTransferJobsToMongodb_, JobScheduler.cpp:6918-6948);
        # None = RAM-only history (tests/simulations).  Attached at the
        # END of __init__ — attach_archive seeds _next_job_id.
        self.archive = None
        # accounting (reference AccountManager + AccountMetaContainer):
        # None = open system, no limit enforcement
        self.accounts = accounts
        self.account_meta = (AccountMetaContainer(meta.layout)
                             if accounts is not None else None)
        # cluster-wide accounting (fed/usage.py UsageBook): conservative
        # global MaxJobs/MaxSubmitJobs gate + fair-share service input.
        # None = per-shard limits only (single-controller behavior).
        self.global_usage = None
        # live partition migration (fed/rebalance.py): a sealed
        # partition stops admitting — its jobs are mid-handoff to
        # another shard and a new local submit would be stranded
        self.sealed_partitions: set[str] = set()
        self.licenses = LicenseManager()
        # submit hook (the reference's Lua JobSubmitLuaScript seam,
        # LuaJobHandler.h:39: rewrite the spec or reject with a message):
        # JobSpec -> JobSpec (possibly modified) | None (reject)
        self.submit_hook = submit_hook
        # persistent SoA mirror of the pending queue (ctld/
        # pending_table.py): event hooks below keep it current, the
        # cycle masks it vectorially instead of walking Job objects
        self._ptable = PendingTable(meta.layout.num_dims)
        # membership indexes maintained by the dict hooks so per-cycle
        # scans iterate exactly the rows they need, never O(pending) /
        # O(running): array templates awaiting materialization, and
        # alloc_only jobs whose time limit ctld itself enforces
        self._array_templates: set[int] = set()
        self._alloc_only: set[int] = set()
        # event-driven loop plumbing: the server points cycle_kick at
        # its wakeup event; mutations that can change the next cycle's
        # outcome call _kick() so a sleeping loop wakes immediately
        self.cycle_kick: Callable[[], None] | None = None
        # no-op short-circuit state: fingerprint + nearest time edge,
        # armed after a zero-placement cycle (_arm_noop / _cycle_body)
        self._noop_fp: tuple | None = None
        self._noop_edge: float = float("inf")
        self._cycle_fp0: tuple | None = None
        self._cycle_usage_denied0: int = 0
        self._skip_trace: dict | None = None
        # PendingTable row indexes aligned with the in-flight cycle's
        # candidates/ordered lists (the vectorized row-build gathers)
        self._cand_rows: np.ndarray | None = None
        self._ordered_rows: np.ndarray | None = None
        # running-set priority attrs: rebuilt only when running-set
        # MEMBERSHIP changes (the dict hooks bump _run_epoch on
        # start/finish/requeue) — per cycle only run_time is recomputed
        # from the cached start times
        self._run_attrs: tuple | None = None
        self._run_epoch = 0
        meta.delta_snapshot = self.config.incremental
        # job_id -> Job; insertion = id order (the hooks mirror
        # membership into the table/indexes/gauges at mutation time)
        self.pending: dict[int, Job] = _ObservedDict(
            self._on_pending_set, self._on_pending_del)
        self.running: dict[int, Job] = _ObservedDict(
            self._on_running_set, self._on_running_del)
        self.history: dict[int, Job] = {}    # terminal jobs
        self._status_queue: collections.deque[StatusChange] = (
            collections.deque())
        # step-level reports arriving from transport pool threads: deque
        # appends are thread-safe; the mutations happen when the cycle
        # (or an RPC holding the server lock) drains them.  Transport
        # code must NEVER call step_report directly — it mutates
        # job.steps / the WAL / _try_start_steps without the lock.
        self._step_report_queue: collections.deque[tuple] = (
            collections.deque())
        self._next_job_id = 1
        self._account_index: dict[str, int] = {}
        self._mask_cache: dict[tuple, np.ndarray] = {}
        self._mask_cache_epoch = -1
        # factored eligibility classes: the [C, N] row table lives on
        # device across cycles; per-cycle H2D is job_class[J] only
        self._mask_table = _MaskTable()
        self._mesh = None  # lazy device mesh for solver == "sharded"
        self._dependents: dict[int, set[int]] = {}  # dep job -> waiters
        # job_id -> last kill-send time for unconfirmed cancel intents
        self._cancel_kill_sent: dict[int, float] = {}
        # (job_id, step_id) -> last kill-send time for unconfirmed
        # step-level cancels (same lost-kill race as whole-job cancel:
        # dispatch_terminate_step swallows transport errors, so a single
        # send can vanish and the cancelled step would run to completion)
        self._step_cancel_sent: dict[tuple[int, int], float] = {}
        # job_id -> (new time limit, last send) for unconfirmed
        # ChangeTimeLimit pushes: the update can beat the supervisor
        # spawn on the craned (which then refuses it), so it re-sends
        # each cycle until the dispatcher confirms every node took it
        self._limit_intents: dict[int, tuple[float, float]] = {}
        self._finalized_since_compact = 0
        # incremental per-cycle state of running allocations: the cost
        # seed + backfill release rows come from O(rows) numpy instead
        # of an O(running) Python loop every cycle (VERDICT r2 weak #4)
        self._ledger = RunLedger(meta.layout.num_dims)
        # device-resident ClusterState across cycles (ctld/resident.py):
        # registers a dirty listener on meta so immediate-fit cycles
        # scatter-patch dirty rows instead of re-uploading [N, R]
        self._resident = ResidentClusterState(
            meta, enabled=(config.resident_state and config.incremental))
        # one shared time axis for every duration-aware solve: batch
        # time_limits stay in SECONDS and the solver derives occupancy
        # windows from these edges (uniform when time_horizon is None)
        self._grid = TimeGrid(config.time_buckets,
                              config.time_resolution,
                              horizon=config.time_horizon)
        # node lifecycle event seam (reference NodeEventHook,
        # Plugin.proto:75-95 — the plugin daemon's node-event surface):
        # callable(event_dict) fired on up/down/drain/undrain/power
        # transitions, async (never under the RPC lock's critical
        # path); plus a bounded in-RAM event log for observability
        self.node_event_hook = None
        self.node_events: list[dict] = []
        self._node_event_queue = None  # lazily-started ordered worker
        # observability (reference per-phase wall-clock trace,
        # JobScheduler.cpp:1444-1447,1723-1903)
        self.stats = {
            "cycles": 0, "skipped_cycles": 0, "jobs_started_total": 0,
            "jobs_submitted_total": 0, "jobs_finished_total": 0,
            "last_cycle": {}, "last_cycle_walltime": 0.0,
        }
        # structured per-cycle traces (obs/trace.py); _cur_trace is the
        # in-flight cycle's mutable accumulator — cycles are serialized
        # by the server lock, so one slot suffices
        self.cycle_trace = CycleTraceRing(config.cycle_trace_ring)
        self._cur_trace: dict = {}
        # per-job lifecycle tracing + SLO plane (obs/jobtrace.py,
        # obs/slo.py): None when JobTrace is off — every stamp site
        # guards on it, so "off" removes the whole layer from the hot
        # path, not just the output
        self.slo_engine = SloEngine.from_config(config.slo)
        self.jobtrace = (JobTraceRecorder(
            capacity=config.job_trace_capacity, slo=self.slo_engine)
            if config.job_trace else None)
        # structured cluster event log (obs/events.py): this ctld emits
        # locally; a follower additionally ingests the leader's events
        # via the HaFetchWal piggyback, so cevents works on standbys
        self.events = EventLog()
        if self.slo_engine is not None:
            self.slo_engine.event_sink = self._slo_event
        # introspection plane (obs/introspect.py): per-cycle recompile
        # attribution is delta-based off the process-wide counter; the
        # profiler window is armed by the CaptureProfile RPC and ticked
        # at cycle boundaries
        self._cycle_compile_base = introspect.total_compiles()
        self.profiler_window = introspect.ProfilerWindow(
            event_sink=lambda type, sev, detail="": self.events.emit(
                type, sev, detail=detail),
            namespace=lambda: self.shard_name)
        # stall forensics (obs/flight.py): always-on phase ring the
        # cycle stamps (~6 appends/cycle), plus the stall sentry the
        # server's cycle loop arms around every cycle — a wedged cycle
        # lands with all-thread stacks in flight.last_stall instead of
        # a silent hang
        self.flight = FlightRecorder(
            event_sink=lambda type, sev, detail="": self.events.emit(
                type, sev, detail=detail))
        # the in-flight cycle's ``now``: the dispatch-ring drain runs
        # lock-released and stamps committed_durable/dispatched on the
        # same clock the cycle used (virtual in sims, wall in daemons)
        self._cycle_now = 0.0
        # timed preemption's deferred evictions: victim job_id ->
        # (due time, preemptor job_id).  Victims of a future-start
        # preemption survive until the preemptor's start bucket
        # (reference JobScheduler.cpp:6378-6505); the prelude drains
        # entries whose due time passed, next_wake_time() wakes the
        # event loop for the earliest one.  Deliberately NOT
        # WAL-persisted: after a failover the preemption solve
        # re-derives any eviction still worth making.
        self._deferred_evictions: dict[int, tuple[float, int]] = {}
        # federated control plane (fed/): this controller's shard name
        # ("" outside a federation) and the lease plane grafted on by
        # fed.shard.FedShardPlane.attach — None for single-controller
        # clusters, so every fed hook is a cheap attribute check
        self.shard_name = ""
        self.fed = None
        if archive is not None:
            self.attach_archive(archive)

    def emit_node_event(self, event: str, node_name: str,
                        detail: str = "", now: float = 0.0) -> None:
        """Record + fan out one node lifecycle event.  The hook runs on
        ONE worker thread draining a queue — operator code never blocks
        a cycle, and back-to-back transitions (drain then undrain)
        reach the hook in ORDER, never concurrently (a per-event thread
        would let the undrain overtake the drain and leave the
        operator's external system with the wrong final state)."""
        record = {"event": event, "node": node_name, "detail": detail,
                  "time": now}
        self.node_events.append(record)
        if len(self.node_events) > 200:
            del self.node_events[: len(self.node_events) - 200]
        # mirror into the typed event ring (flap detection included)
        self.events.emit_node_transition(event, node_name, detail=detail,
                                         now=now)
        if self.node_event_hook is None:
            return
        if self._node_event_queue is None:
            import queue
            import threading
            self._node_event_queue = queue.Queue()

            def worker():
                while True:
                    rec = self._node_event_queue.get()
                    hook = self.node_event_hook
                    if hook is None:
                        continue
                    try:
                        hook(rec)
                    except Exception:
                        import logging
                        import traceback
                        logging.getLogger("cranesched.ctld").error(
                            "node event hook raised:\n%s",
                            traceback.format_exc())

            threading.Thread(target=worker, daemon=True).start()
        self._node_event_queue.put(record)

    def _slo_event(self, name: str, window: float, burn: float,
                   breaching: bool) -> None:
        """SloEngine breach-edge sink -> typed event ring."""
        if breaching:
            self.events.emit(
                "slo_breach", "error",
                detail="%s window=%ds burn=%.2f" % (name, window, burn))
        else:
            self.events.emit(
                "slo_clear",
                detail="%s window=%ds recovered" % (name, window))

    def explain_pending(self, job_id: int, now: float) -> dict:
        """First-failing-gate decomposition for one job (``cexplain``).
        Caller holds the server lock."""
        from cranesched_tpu.ctld.explain import explain_pending
        return explain_pending(self, job_id, now)

    # history the RAM dict may hold with an archive attached (the
    # durable store serves the rest; without an archive RAM is the only
    # record and must not be evicted)
    HISTORY_CACHE_MAX = 10_000

    # cycles before a fresh jit compile counts as a steady-state
    # violation (the first cycles after boot/failover legitimately
    # populate the cache for each padded-shape bucket)
    WARMUP_CYCLES = 3

    def attach_archive(self, archive) -> None:
        """Wire the durable history store (also used by ctld_main after
        construction).  Seeds the job-id counter past every archived id:
        a restart whose WAL was auto-compacted would otherwise reuse ids
        and INSERT OR REPLACE over history."""
        self.archive = archive
        self._next_job_id = max(getattr(self, "_next_job_id", 1),
                                archive.max_job_id() + 1)

    # ------------------------------------------------------------------
    # incremental cycle state (ARCHITECTURE.md "Incremental cycle
    # state"): membership hooks, the PendingTable row derivation, the
    # no-op-cycle fingerprint, and the event-driven loop's sleep seam
    # ------------------------------------------------------------------

    def _kick(self) -> None:
        """Wake the server's event-driven cycle loop (no-op standalone)."""
        kick = self.cycle_kick
        if kick is not None:
            kick()

    def _on_pending_set(self, job_id: int, job: Job) -> None:
        self._table_upsert(job)
        if job.spec.array is not None:
            self._array_templates.add(job_id)
        _MET_PENDING.set(len(self.pending))
        self._kick()

    def _on_pending_del(self, job_id: int, job: Job) -> None:
        self._ptable.remove(job_id)
        self._array_templates.discard(job_id)
        _MET_PENDING.set(len(self.pending))
        self._kick()

    def _on_running_set(self, job_id: int, job: Job) -> None:
        if job.spec.alloc_only:
            self._alloc_only.add(job_id)
        self._run_epoch += 1
        _MET_RUNNING.set(len(self.running))
        if self.global_usage is not None:
            self.global_usage.note_run(job.spec.user, job.spec.account, 1)
            if job.global_run_reserved:
                # admission's held slot becomes the real running count
                self.global_usage.unreserve_run(job.spec.user,
                                                job.spec.account)
                job.global_run_reserved = False

    def _on_running_del(self, job_id: int, job: Job) -> None:
        self._alloc_only.discard(job_id)
        self._run_epoch += 1
        _MET_RUNNING.set(len(self.running))
        if self.global_usage is not None:
            self.global_usage.note_run(job.spec.user, job.spec.account, -1)

    def _dep_cols(self, job: Job) -> tuple[float, bool]:
        """``(dep_ready_time, never)`` table columns mirroring
        ``_deps_runnable`` exactly: the row is dep-blocked while
        ``dep_ready_time > now``; ``never`` selects the
        DEPENDENCY_NEVER_SATISFIED reason.  Edges still waiting on an
        event map to +inf with never=False — only ``_trigger_dep_event``
        (which refreshes the row) can unblock them."""
        if not job.dep_state:
            return float("-inf"), False
        states = list(job.dep_state.values())
        if job.spec.deps_is_or:
            finite = [v for v in states
                      if v is not None and v != DEP_NEVER]
            if finite:
                return min(finite), False
            if all(v == DEP_NEVER for v in states):
                return float("inf"), True
            return float("inf"), False
        if any(v == DEP_NEVER for v in states):
            return float("inf"), True
        if any(v is None for v in states):
            return float("inf"), False
        return max(states), False

    def _table_upsert(self, job: Job) -> None:
        """Derive one PendingTable row from the Job (the table owns
        storage; the scheduler owns JobSpec semantics).  Every value the
        cycle's vectorized passes gather must be re-derived here on any
        event that can change it."""
        spec = job.spec
        dep, dep_never = self._dep_cols(job)
        req, node_num, time_limit = self._job_row(job)
        part = self.meta.partitions.get(spec.partition)
        packed = bool(spec.exclusive or spec.task_res is not None
                      or (spec.ntasks is not None
                          and spec.ntasks != spec.node_num)
                      or spec.ntasks_per_node_max > 1)
        self._ptable.upsert(
            job.job_id,
            template=spec.array is not None,
            held=job.held,
            begin=(spec.begin_time if spec.begin_time is not None
                   else float("-inf")),
            dep=dep, dep_never=dep_never,
            lic=self._ptable.lic_key(spec.licenses),
            submit=job.submit_time,
            qos=job.qos_priority,
            part=part.priority if part is not None else 0,
            nnum=node_num,
            cpus=float(req[DIM_CPU]) / 256.0 * spec.node_num,
            mem=float(req[DIM_MEM]) * spec.node_num,
            acct=self._account_id(spec.account),
            tlimit=time_limit,
            packed=packed,
            req=req)

    def _table_refresh(self, job: Job) -> None:
        """Re-derive a pending job's row after an IN-PLACE mutation
        (hold / modify / dep trigger — paths that don't re-insert into
        the dict) and wake the loop."""
        if job.job_id in self.pending:
            self._table_upsert(job)
            self._kick()

    def _cycle_fingerprint(self) -> tuple:
        """Everything a zero-placement solve's outcome depends on, as
        epochs: queue content (table), node availability/liveness
        (meta), license seats, reservation set.  Time-dependent gates
        (begin/dep/reservation windows) are handled by ``_noop_edge``,
        not the fingerprint."""
        return (self._ptable.epoch, self.meta.meta_epoch,
                self.licenses.epoch, self.meta.resv_epoch)

    def _arm_noop(self, now: float) -> None:
        """Arm the no-op short-circuit after a cycle that placed
        nothing, preempted nothing, and queued no dispatch: until an
        epoch moves or the nearest time edge passes, an identical cycle
        would place nothing again (every candidate failed against the
        same snapshot, and aging alone cannot create a placement when
        zero jobs placed — order among non-placing jobs is moot).
        Never armed with preemption enabled: a preemptor's eligibility
        depends on running-set age, which no epoch tracks."""
        if not self.config.incremental:
            return
        if self.config.preempt_mode != "off" and self.accounts is not None:
            return
        if (self.global_usage is not None
                and self.global_usage.denied
                != self._cycle_usage_denied0):
            # a candidate was refused by the cluster-wide usage gate
            # this cycle; that gate's answer depends on gossip state
            # (publish throttle, peer summaries) no epoch tracks —
            # the next cycle may well place it
            return
        fp = self._cycle_fp0
        if fp is None or self._cycle_fingerprint() != fp:
            return   # something moved mid-cycle; next cycle must look
        edge = self._ptable.next_edge(now)
        for resv in self.meta.reservations.values():
            # activity flips don't bump resv_epoch — cover them by edge
            if resv.start_time > now:
                edge = min(edge, resv.start_time)
            if resv.end_time > now:
                edge = min(edge, resv.end_time)
        self._noop_fp = fp
        self._noop_edge = edge

    def _skip_cycle(self, t0, now: float, reason: str) -> list[int]:
        """The short-circuited cycle: count it, refresh watchdog
        liveness, and coalesce consecutive skips into ONE trace-ring
        row (an idle night must not flush real cycles out of the
        ring).  The queue drains already ran — only the snapshot /
        sort / solve / commit machinery is skipped."""
        import time as _time
        self._in_cycle = False
        self.stats["cycles"] += 1
        _MET_CYCLES.inc()
        self.stats["skipped_cycles"] = (
            self.stats.get("skipped_cycles", 0) + 1)
        _MET_SKIPS.inc(reason=reason)
        self.flight.stamp("skip", detail=reason)
        ms = round((_time.perf_counter() - t0) * 1e3, 3)
        self.stats["last_cycle_walltime"] = _time.time()
        self.stats["last_cycle"] = {
            "solver": "skip", "prelude_ms": ms, "total_ms": ms,
            "pending": 0, "started": 0, "running": len(self.running)}
        st = self._skip_trace
        if st is not None:
            st["skips"] = st.get("skips", 0) + 1
            st["now"] = now
            st["total_ms"] = ms
        else:
            trace = {
                "now": now, "queue_depth": len(self.pending),
                "solver": "skip", "skip_reason": reason, "skips": 1,
                "prelude_ms": ms, "solve_ms": 0.0, "commit_ms": 0.0,
                "dispatch_ms": 0.0, "total_ms": ms, "lock_held_ms": ms,
                "candidates": 0, "placed": 0, "preempted": 0,
                "backfilled": 0, "running": len(self.running)}
            self.cycle_trace.push(trace)
            self._skip_trace = trace
        return []

    def can_idle(self) -> bool:
        """True when the event-driven loop may sleep up to
        ``cycle_idle_sleep``: the no-op fingerprint is armed and still
        matches, and no queued work (dispatch ring, status/step
        reports, unconfirmed kill / time-limit intents) needs the next
        cycle.  Call under the server lock."""
        return (self.config.incremental
                and self._noop_fp is not None
                and self._cycle_fingerprint() == self._noop_fp
                and not self._dispatch_ring
                and not self._status_queue
                and not self._step_report_queue
                and not self._cancel_kill_sent
                and not self._step_cancel_sent
                and not self._limit_intents
                and not self._deferred_evictions)

    def next_wake_time(self, now: float) -> float:
        """Earliest future moment a sleeping loop must cycle even
        without an event: a begin/dep/reservation edge (_noop_edge),
        an alloc_only job's time limit (ctld enforces those itself),
        or the next craned ping-timeout sweep.  +inf when nothing is
        time-gated."""
        wake = self._noop_edge
        for job_id in self._alloc_only:
            job = self.running.get(job_id)
            if job is not None and job.status == JobStatus.RUNNING:
                wake = min(wake, self._effective_end(job, now))
        if any(node.alive and node.expect_pings
               for node in self.meta.nodes.values()):
            wake = min(wake, now + self.config.craned_timeout / 2)
        for due, _preemptor in self._deferred_evictions.values():
            wake = min(wake, due)
        return wake

    # ------------------------------------------------------------------
    # submit / cancel / hold (reference SubmitJobToScheduler :3405,
    # cancel/hold queues JobScheduler.h:1239-1320)
    # ------------------------------------------------------------------

    def submit(self, spec: JobSpec, now: float) -> int:
        """Validate and enqueue; returns job_id (0 = rejected)."""
        if self.submit_hook is not None:
            # operator code: a crashing or misbehaving hook rejects the
            # job, never the control plane (the reference's Lua seam
            # treats hook failure as reject-with-message) — but the
            # failure must stay diagnosable: log it and count it
            try:
                spec = self.submit_hook(spec)
            except Exception:
                import logging
                import traceback
                logging.getLogger("cranesched.ctld").error(
                    "submit hook raised:\n%s", traceback.format_exc())
                self.stats["submit_hook_failures"] = (
                    self.stats.get("submit_hook_failures", 0) + 1)
                return 0
            if spec is None:
                return 0
            if not isinstance(spec, JobSpec):
                import logging
                logging.getLogger("cranesched.ctld").error(
                    "submit hook returned %r (expected JobSpec or None)",
                    type(spec).__name__)
                self.stats["submit_hook_failures"] = (
                    self.stats.get("submit_hook_failures", 0) + 1)
                return 0
        if len(self.pending) >= self.config.pending_queue_max_size:
            return 0
        part = self.meta.partitions.get(spec.partition)
        if part is None or not part.account_allowed(spec.account):
            return 0
        if spec.partition in self.sealed_partitions:
            return 0  # mid-migration: the successor map owns it now
        # gangs beyond the configured bound (or the partition size) can
        # never be placed — reject at submit rather than leaving the job
        # pending forever with a transient-looking reason
        if not (1 <= spec.node_num
                <= min(self.config.max_nodes_per_job, len(part.node_ids))):
            return 0
        # unknown GRES pairs can never be satisfied (the layout is the
        # cluster's configured inventory) — clean rejection, not a crash
        known_gres = set(self.meta.layout.gres_dims)
        for res in (spec.res, spec.task_res):
            if res is not None and res.gres:
                if not set(res.gres) <= known_gres:
                    return 0
        # CheckJobValidity analog: the per-node minimum request (base +
        # task_res * min tasks, reference min_res_view cpp:6152) must fit
        # at least one node's *total* in the partition.
        req = spec.res.encode(self.meta.layout)
        if spec.task_res is not None:
            req = req + (spec.task_res.encode(self.meta.layout)
                         * spec.ntasks_per_node_min)
        if not (req <= self.meta.partition_max_total(spec.partition)).all():
            return 0
        if spec.ntasks is not None:
            nt_max = max(spec.ntasks_per_node_max,
                         spec.ntasks_per_node_min)
            nt_min = spec.ntasks_per_node_min
            if not (max(spec.node_num, spec.node_num * nt_min)
                    <= spec.ntasks <= spec.node_num * nt_max):
                return 0  # every chosen node must host at least
                          # ntasks_per_node_min tasks (>= 1) and the
                          # gang's combined per-node cap must cover ntasks

        if spec.reservation:
            resv = self.meta.reservations.get(spec.reservation)
            if resv is None or not resv.account_allowed(spec.account):
                return 0
        if spec.licenses and self.licenses.legal(spec.licenses):
            return 0  # unknown license or count beyond the total
        if spec.array is not None and not spec.array.task_ids():
            return 0

        qos_name, qos_priority = "", spec.qos_priority
        if self.accounts is not None:
            qos, err = self.accounts.resolve_submit(
                spec.user, spec.account, spec.partition, spec.qos or None)
            if err:
                return 0
            if qos is not None:
                err = self.account_meta.try_malloc_submit(
                    spec.user, spec.account, qos, spec)
                if err:
                    return 0
                qos_name, qos_priority = qos.name, qos.priority
        if self.global_usage is not None:
            # federation-wide MaxSubmitJobs (fed/usage.py): conservative
            # under bounded staleness — deny-early, never overshoot
            if self.global_usage.check_submit(spec.user, spec.account):
                if self.account_meta is not None and qos_name:
                    self.account_meta.free_submit(
                        spec.user, spec.account, qos_name)
                return 0
            self.global_usage.note_submit(spec.user, spec.account)

        job_id = self._next_job_id
        self._next_job_id += 1
        self.stats["jobs_submitted_total"] += 1
        job = Job(job_id=job_id, spec=spec, submit_time=now,
                  qos_name=qos_name, qos_priority=qos_priority,
                  held=spec.held)
        if spec.held:
            job.pending_reason = PendingReason.HELD
        if spec.array is not None:
            job.array_remaining = spec.array.task_ids()
        self._register_dependencies(job)
        self.pending[job_id] = job
        if self.wal is not None:
            self.wal.job_submitted(job)
        if self.jobtrace is not None:
            self.jobtrace.stamp(job_id, 0, "submit", now)
        return job_id

    # ------------------------------------------------------------------
    # dependencies (reference: event-driven, AddDependent
    # CtldPublicDefs.cpp:1750, start triggers AFTER JobScheduler.cpp:1873,
    # terminal triggers ANY/OK/NOT_OK with InfiniteFuture for the failed
    # branch :1768-1775)
    # ------------------------------------------------------------------

    def _register_dependencies(self, job: Job) -> None:
        for dep in job.spec.dependencies:
            target = self.job_info(dep.job_id)
            if target is None:
                job.dep_state[dep.job_id] = DEP_NEVER
                continue
            sat = self._dep_satisfied_time(dep, target)
            job.dep_state[dep.job_id] = sat
            if sat is None:   # still waiting on an event
                self._dependents.setdefault(dep.job_id, set()).add(
                    job.job_id)

    @staticmethod
    def _dep_satisfied_time(dep, target: Job) -> float | None:
        """Edge state from the dependee's CURRENT state: a timestamp
        (satisfiable from then + delay), DEP_NEVER, or None (waiting)."""
        if dep.type == DepType.AFTER:
            if target.start_time is not None:
                return target.start_time + dep.delay_seconds
            if target.status.is_terminal:   # never started and never will
                return (target.end_time or 0.0) + dep.delay_seconds \
                    if target.status == JobStatus.COMPLETED else DEP_NEVER
            return None
        if not target.status.is_terminal:
            return None
        end = target.end_time or 0.0
        if dep.type == DepType.AFTER_ANY:
            return end + dep.delay_seconds
        if dep.type == DepType.AFTER_OK:
            return (end + dep.delay_seconds
                    if target.status == JobStatus.COMPLETED else DEP_NEVER)
        # AFTER_NOT_OK
        return (end + dep.delay_seconds
                if target.status.is_failed_kind else DEP_NEVER)

    def _trigger_dep_event(self, target: Job) -> None:
        """Re-evaluate waiting edges of this job's dependents."""
        waiting = self._dependents.get(target.job_id)
        if not waiting:
            return
        done = set()
        for jid in waiting:
            dep_job = self.pending.get(jid)
            if dep_job is None:
                done.add(jid)
                continue
            changed = False
            for dep in dep_job.spec.dependencies:
                if dep.job_id != target.job_id:
                    continue
                sat = self._dep_satisfied_time(dep, target)
                if sat is not None:
                    dep_job.dep_state[dep.job_id] = sat
                    changed = True
            if changed:
                # dep_state mutated in place: the table row must see
                # the new dep-ready time / NEVER verdict
                self._table_refresh(dep_job)
            if all(v is not None
                   for v in dep_job.dep_state.values()):
                done.add(jid)
        if target.status.is_terminal:
            self._dependents.pop(target.job_id, None)
        else:
            waiting -= done

    def _deps_runnable(self, job: Job, now: float) -> PendingReason | None:
        """None = runnable; else the pending reason to surface."""
        if not job.dep_state:
            return None
        states = list(job.dep_state.values())
        if job.spec.deps_is_or:
            if any(v is not None and v != DEP_NEVER and v <= now
                   for v in states):
                return None
            if all(v == DEP_NEVER for v in states):
                return PendingReason.DEPENDENCY_NEVER_SATISFIED
            return PendingReason.DEPENDENCY
        # AND combination
        if any(v == DEP_NEVER for v in states):
            return PendingReason.DEPENDENCY_NEVER_SATISFIED
        if all(v is not None and v <= now for v in states):
            return None
        return PendingReason.DEPENDENCY

    def cancel(self, job_id: int, now: float) -> bool:
        if job_id in self.pending:
            job = self.pending.pop(job_id)
            job.status = JobStatus.CANCELLED
            job.end_time = now
            if job.spec.array is not None:
                # cancel the template: drop unmaterialized tasks and
                # cancel live children
                job.array_remaining = []
                for c in list(job.array_children):
                    self.cancel(c, now)
            self._finalize_terminal(job)
            return True
        if job_id in self.running:
            job = self.running[job_id]
            job.cancel_requested = True
            if job.spec.alloc_only:
                # no batch step will ever report: ctld owns the
                # allocation's lifecycle, so finalize synchronously and
                # free the allocation on the craneds (best-effort;
                # re-registration reconciles a missed FreeJob)
                self._teardown_alloc_job(job, now, JobStatus.CANCELLED,
                                         130)
                return True
            # real system: TerminateSteps RPC → craned kills → status
            # change flows back.  The dispatch seam owns the kill; the
            # status change arrives via step_status_change.  The intent is
            # recorded on the job AND WAL-logged so neither a node death
            # racing the kill nor a ctld crash can resurrect the job.
            for step in job.steps.values():
                if not step.status.is_terminal:
                    step.cancel_requested = True
            if self.wal is not None:
                self.wal.job_updated(job)
            self._cancel_kill_sent[job_id] = now
            self.dispatch_terminate(job_id, now)
            self._kick()   # kill-intent renewal runs on the cycle thread
            return True
        return False

    def dispatch_terminate(self, job_id: int, now: float,
                           incarnation: int | None = None,
                           skip_node: int | None = None) -> None:
        """Overridden/patched by the transport layer; simulated clusters
        hook this to deliver a Cancelled status change.

        ``incarnation`` guards the kill to exactly that requeue_count
        (system-initiated kills that are followed by a same-cycle requeue
        must never touch the re-placed incarnation); None = user intent,
        kill whatever runs.  ``skip_node`` omits a node already declared
        dead (its steps died with the daemon; an RPC to it only blocks a
        pool worker for the full timeout)."""

    def hold(self, job_id: int, held: bool, now: float) -> bool:
        job = self.pending.get(job_id)
        if job is None:
            return False
        job.held = held
        job.pending_reason = (PendingReason.HELD if held
                              else PendingReason.NONE)
        if self.wal is not None:
            self.wal.job_updated(job)
        self._table_refresh(job)
        return True

    def requeue(self, job_id: int, now: float) -> str:
        """Operator-requested requeue (reference RequeueJob,
        Crane.proto:1407): kill the running incarnation and return the
        job to pending.  Returns "" on success, else the refusal reason.

        Held/pending jobs are refused (nothing to requeue); the kill is
        incarnation-guarded exactly like the node-death path so a late
        terminate can never touch the re-placed incarnation."""
        if job_id in self.pending:
            return "job is pending; nothing to requeue"
        job = self.running.get(job_id)
        if job is None:
            return "no such running job"
        if job.cancel_requested:
            return "cancel already requested"
        if job.status == JobStatus.SUSPENDED:
            return "job is suspended; resume it first"
        self.dispatch_terminate(job_id, now,
                                incarnation=job.requeue_count)
        self._release_job_resources(job)
        del self.running[job_id]
        self._cancel_kill_sent.pop(job_id, None)
        if self.jobtrace is not None:
            self.jobtrace.stamp(job_id, job.requeue_count, "requeue",
                                now)
        job.reset_for_requeue()
        if job.requeue_count > self.config.max_requeue_count:
            job.held = True
            job.pending_reason = PendingReason.HELD
        self.pending[job_id] = job
        self.events.emit("requeue", job_id=job_id, detail="operator",
                         time=now)
        if self.wal is not None:
            self.wal.job_requeued(job)
        return ""

    def job_summary(self, user: str = "", partition: str = ""
                    ) -> dict[str, int]:
        """Per-status job counts (reference QueryJobSummary,
        Crane.proto:1588) over pending + running + in-RAM history."""
        counts: dict[str, int] = {}
        for col in (self.pending, self.running, self.history):
            for job in col.values():
                if user and job.spec.user != user:
                    continue
                if partition and job.spec.partition != partition:
                    continue
                key = job.status.name
                counts[key] = counts.get(key, 0) + 1
        return counts

    def modify_job(self, job_id: int, now: float, *,
                   time_limit: float | None = None,
                   priority: int | None = None,
                   partition: str | None = None) -> str:
        """Modify a job in place (reference ModifyJob, Crane.proto:1447).
        Returns "" on success, else the refusal reason.

        time_limit applies to pending AND running jobs — for running
        jobs the new deadline propagates to the supervisors through
        ``dispatch_change_time_limit`` (the ChangeJobTimeConstraint
        path, Crane.proto:1654), so a job about to hit its old limit is
        NOT killed at it.  priority and partition change pending jobs
        only (the reference likewise refuses to migrate a running job)."""
        job = self.pending.get(job_id) or self.running.get(job_id)
        if job is None:
            return f"job {job_id} not found or already terminal"
        running = job_id in self.running
        if running and (priority is not None or partition is not None):
            return "only the time limit of a running job can change"
        if time_limit is not None:
            if time_limit <= 0:
                return "time limit must be positive"
            if self.accounts is not None and job.qos_name:
                qos = self.accounts.qos.get(job.qos_name)
                if qos is not None and (
                        time_limit > qos.max_time_limit_per_job
                        or time_limit > qos.max_wall):
                    return ("time limit exceeds qos "
                            f"{job.qos_name} bound")
        if partition is not None:
            # full submit-time validation against the NEW partition
            # (skipping it would let an owner bypass account ACLs or
            # strand a gang in a partition that can never host it)
            part = self.meta.partitions.get(partition)
            if part is None:
                return f"partition {partition} not found"
            if not part.node_ids:
                return f"partition {partition} has no nodes"
            if not part.account_allowed(job.spec.account):
                return (f"account {job.spec.account} not allowed in "
                        f"partition {partition}")
            if job.spec.node_num > len(part.node_ids):
                return (f"gang of {job.spec.node_num} exceeds "
                        f"partition {partition} size")
            req = job.spec.res.encode(self.meta.layout)
            if job.spec.task_res is not None:
                req = req + (job.spec.task_res.encode(self.meta.layout)
                             * job.spec.ntasks_per_node_min)
            if not (req <= self.meta.partition_max_total(partition)
                    ).all():
                return (f"request exceeds every node in partition "
                        f"{partition}")
            if self.accounts is not None:
                _qos, err = self.accounts.resolve_submit(
                    job.spec.user, job.spec.account, partition,
                    job.spec.qos or None)
                if err:
                    return err
        import dataclasses as _dc
        if time_limit is not None:
            job.spec = _dc.replace(job.spec,
                                   time_limit=float(time_limit))
            if running:
                # the incremental ledger's release row must follow the
                # new deadline, or every later time map would reserve
                # against a bucket the job will still occupy
                self._ledger.set_end_time(
                    job_id, self._effective_end(job, now))
                self._limit_intents[job_id] = (float(time_limit), now)
                self.dispatch_change_time_limit(job_id, float(time_limit),
                                                now)
                self._kick()   # intent re-sends run on the cycle thread
        if priority is not None:
            job.qos_priority = int(priority)
        if partition is not None:
            job.spec = _dc.replace(job.spec, partition=partition)
            job.pending_reason = PendingReason.NONE
        if self.wal is not None:
            self.wal.job_updated(job)
        if not running:
            self._table_refresh(job)
        return ""

    def dispatch_change_time_limit(self, job_id: int, time_limit: float,
                                   now: float) -> None:
        """Transport seam: push the new deadline to the job's craneds.
        The sim plane has no supervisors to update (deadlines re-read
        spec.time_limit), so the base seam just confirms the intent."""
        self._limit_intents.pop(job_id, None)

    # ------------------------------------------------------------------
    # status changes (reference StepStatusChangeAsync :5294 + batched
    # drain :5318)
    # ------------------------------------------------------------------

    def step_status_change(self, job_id: int, status: JobStatus,
                           exit_code: int, now: float,
                           node_id: int = -1,
                           incarnation: int | None = None) -> None:
        """node_id >= 0 is a per-node report from a real craned; the job
        is terminal only when every allocated node reported (or on the
        first failure, which kills the rest).  node_id == -1 is a
        whole-job report (simulated plane / dispatch failures)."""
        queue_incarnation = incarnation
        if node_id >= 0:
            job = self.running.get(job_id)
            if job is None:
                return
            if node_id not in job.node_ids:
                # stale report from a previous incarnation's node
                # (e.g. a preemption kill confirmed after the victim was
                # requeued and re-placed elsewhere)
                return
            if (incarnation is not None
                    and incarnation != job.requeue_count):
                # stale report from a pre-requeue step, even if the new
                # incarnation landed on the same node
                return
            is_failure = status not in (JobStatus.COMPLETED,
                                        JobStatus.CANCELLED)
            had_failure = any(
                st not in (JobStatus.COMPLETED, JobStatus.CANCELLED)
                for st, _ in job.node_reports.values())
            job.node_reports[node_id] = (status, exit_code)
            if is_failure and not had_failure:
                # first failure: kill the remaining steps; their
                # Cancelled reports complete the set.  Guarded by this
                # incarnation — if the job requeues before the async kill
                # lands, the new run must survive it.
                self.dispatch_terminate(job_id, now,
                                        incarnation=job.requeue_count)
            if not all(n in job.node_reports for n in job.node_ids):
                return
            # aggregate: worst status wins (any non-complete -> that)
            agg_status, agg_code = JobStatus.COMPLETED, 0
            for st, code in job.node_reports.values():
                if st != JobStatus.COMPLETED and st != JobStatus.CANCELLED:
                    agg_status, agg_code = st, code
                    break
            else:
                if any(st == JobStatus.CANCELLED
                       for st, _ in job.node_reports.values()) and \
                        not all(st == JobStatus.CANCELLED
                                for st, _ in job.node_reports.values()):
                    # mixed Cancelled (our kill) + Completed: the kill
                    # was collateral of another node's failure... or a
                    # user cancel; cancel_requested disambiguates
                    agg_status = (JobStatus.CANCELLED
                                  if job.cancel_requested
                                  else JobStatus.COMPLETED)
                elif all(st == JobStatus.CANCELLED
                         for st, _ in job.node_reports.values()):
                    agg_status, agg_code = JobStatus.CANCELLED, 130
            status, exit_code = agg_status, agg_code
            queue_incarnation = job.requeue_count
        self._status_queue.append(
            StatusChange(job_id, status, exit_code, now,
                         incarnation=queue_incarnation))
        self._kick()   # Event.set is thread-safe (transport threads)

    def record_remote_spans(self, job_id: int, incarnation: int,
                            spans) -> int:
        """Merge craned-side spans (craned_received / cgroup_ready /
        step_start) shipped back inside StepStatusChange into the job's
        timeline.  Each span keeps its original seq from the propagated
        trace context, so the merged timeline stays monotone; stamp-once
        drops duplicates from retried RPCs.  Thread-safe (recorder lock);
        returns the number of spans newly recorded."""
        if self.jobtrace is None:
            return 0
        n = 0
        for s in spans:
            edge = s["edge"] if isinstance(s, dict) else s.edge
            if isinstance(s, dict):
                t, seq = s["t"], s.get("seq")
                node_id = s.get("node_id", -1)
                skew = s.get("skew", 0.0)
            else:
                t, seq, node_id, skew = s.time, s.seq, s.node_id, s.skew
            if self.jobtrace.stamp(job_id, incarnation, edge, float(t),
                                   node_id=int(node_id),
                                   skew=float(skew), seq=int(seq)):
                n += 1
        return n

    def trace_seq(self, job_id: int, incarnation: int) -> int:
        """Next span seq for (job_id, incarnation) — the base the
        dispatcher embeds in the crane-trace gRPC metadata so craned
        numbers its local spans after the ctld-side ones."""
        if self.jobtrace is None:
            return 0
        return self.jobtrace.next_seq(job_id, incarnation)

    def step_report_async(self, job_id: int, step_id: int,
                          status: "StepStatus", exit_code: int,
                          now: float,
                          incarnation: int | None = None) -> None:
        """Thread-safe step report enqueue for transport pool threads
        (drained at the next process_status_changes)."""
        self._step_report_queue.append(
            (job_id, step_id, status, exit_code, now, incarnation))
        self._kick()

    def process_status_changes(self) -> int:
        """Drain the queue (cycle step 1).  Returns #processed.

        All WAL events from one drain (requeues, finalize tombstones)
        commit as one group — inside a cycle this nests into the
        cycle's group; called standalone (Tick RPC, tests) it opens its
        own, so a big drain still pays one fsync, not one per job."""
        self._wal_begin()
        try:
            return self._process_status_changes()
        finally:
            self._wal_flush()

    def _process_status_changes(self) -> int:
        while self._step_report_queue:
            args = self._step_report_queue.popleft()
            job_id, step_id, status, exit_code, now, incarnation = args
            self.step_report(job_id, step_id, status, exit_code, now,
                             incarnation=incarnation)
        n = 0
        while self._status_queue:
            ch = self._status_queue.popleft()
            job = self.running.get(ch.job_id)
            if job is None:
                continue
            if (ch.incarnation is not None
                    and ch.incarnation != job.requeue_count):
                continue  # stale report for a pre-requeue incarnation
            n += 1
            self._release_job_resources(job)
            del self.running[ch.job_id]
            self._cancel_kill_sent.pop(ch.job_id, None)
            job.end_time = ch.time
            job.exit_code = ch.exit_code
            job.status = ch.status
            if self._should_requeue(job, ch):
                if self.jobtrace is not None:
                    self.jobtrace.stamp(job.job_id, job.requeue_count,
                                        "requeue", ch.time)
                job.reset_for_requeue()
                if job.requeue_count > self.config.max_requeue_count:
                    # over the cap: requeued but held (reference keeps the
                    # job, operator must release)
                    job.held = True
                    job.pending_reason = PendingReason.HELD
                self.pending[job.job_id] = job
                if self.wal is not None:
                    self.wal.job_requeued(job)
            else:
                self._finalize_terminal(job)
        return n

    def _should_requeue(self, job: Job, ch: StatusChange) -> bool:
        """Reference ShouldRequeue (CtldPublicDefs tests :397-457):
        user-requested requeue-if-failed, or system failure (craned
        death), bounded by MaxRequeueCount."""
        if job.cancel_requested:
            return False
        if ch.status == JobStatus.FAILED and job.spec.requeue_if_failed:
            return True
        return False

    def _job_alloc(self, job: Job) -> list[np.ndarray]:
        """Per-node allocation vectors (exclusive jobs own whole nodes;
        packed jobs scale with their task layout).  Cached per incarnation
        — this is on the per-cycle hot path via _initial_cost."""
        if (job.alloc_cache is not None
                and len(job.alloc_cache) == len(job.node_ids)):
            return job.alloc_cache
        spec = job.spec
        if spec.exclusive:
            alloc = [self.meta.nodes[n].total.copy()
                     for n in job.node_ids]
        else:
            base = spec.res.encode(self.meta.layout)
            if spec.task_res is None:
                alloc = [base] * len(job.node_ids)
            else:
                task = spec.task_res.encode(self.meta.layout)
                layout = (job.task_layout
                          or [spec.ntasks_per_node_min]
                          * len(job.node_ids))
                alloc = [base + task * t for t in layout]
        job.alloc_cache = alloc
        return alloc

    def _release_job_resources(self, job: Job) -> None:
        self.meta.free_resource(job.job_id, job.node_ids,
                                self._job_alloc(job))
        self._ledger.remove(job.job_id)
        self.licenses.free(job.spec.licenses or {})
        self._free_run_limits(job)
        self._kick()   # freed capacity: pending jobs may now place

    def _ledger_add(self, job: Job, now: float) -> None:
        """Register a just-started (or re-adopted) job's allocation rows
        in the incremental ledger."""
        self._ledger.add(
            job.job_id, job.node_ids, self._job_alloc(job),
            self._effective_end(job, now),
            [self.meta.nodes[n].total[DIM_CPU] for n in job.node_ids])
        if job.status == JobStatus.SUSPENDED:
            self._ledger.suspend(job.job_id, now)

    def _ledger_add_batch(self, jobs: list[Job], now: float) -> None:
        """Batch form of _ledger_add for the commit hot path: the whole
        just-started set registers its rows in one ledger call (started
        jobs are RUNNING, so no suspend bookkeeping here)."""
        if not jobs:
            return
        nodes = self.meta.nodes
        self._ledger.add_batch(
            [(job.job_id, job.node_ids, self._job_alloc(job),
              self._effective_end(job, now),
              [nodes[n].total[DIM_CPU] for n in job.node_ids])
             for job in jobs])

    def _malloc_run_limits(self, job: Job) -> bool:
        """Schedule-time QoS limit check + usage take (reference
        CheckAndMallocMetaResource, AccountMetaContainer.h:113).  The
        take is recorded on the job so the free stays symmetric even if
        the QoS is deleted/re-created while the job runs."""
        job.run_usage_taken = False
        gu = self.global_usage
        if gu is not None and gu.check_run(job.spec.user,
                                           job.spec.account):
            # federation-wide MaxJobs: the job stays pending
            return False
        if self.account_meta is not None and job.qos_name:
            qos = self.accounts.qos.get(job.qos_name)
            if qos is not None:
                err = self.account_meta.check_and_malloc_run(
                    job.spec.user, job.spec.account, qos, job.spec)
                if err:
                    return False
                job.run_usage_taken = True
        if gu is not None:
            # hold the slot NOW: batch commits check every candidate
            # before any lands in the running dict, so later same-cycle
            # checks must see this admission (the dict hook converts
            # the reservation into the real count)
            gu.reserve_run(job.spec.user, job.spec.account)
            job.global_run_reserved = True
        return True

    def _free_run_limits(self, job: Job) -> None:
        if self.account_meta is not None and job.run_usage_taken:
            self.account_meta.free_run(job.spec.user, job.spec.account,
                                       job.qos_name, job.spec)
            job.run_usage_taken = False
        if self.global_usage is not None and job.global_run_reserved:
            self.global_usage.unreserve_run(job.spec.user,
                                            job.spec.account)
            job.global_run_reserved = False

    def _finalize_terminal(self, job: Job) -> None:
        """Full terminal processing: archive + fire dependency events +
        array-parent bookkeeping.  Every path that moves a job to a
        terminal state outside process_status_changes must use this (a
        bare _finalize drops the event hooks and dependents would wait
        forever — dependency edges are event-driven, never polled)."""
        # close the step records with the allocation: the implicit batch
        # step 0 mirrors the job's outcome; any other live step died
        # with the allocation
        for step in job.steps.values():
            if step.status.is_terminal:
                continue
            if step.step_id == 0 and not job.spec.alloc_only:
                step.status = StepStatus(job.status.value)
                step.exit_code = (job.exit_code
                                  if job.exit_code is not None else 0)
            else:
                step.status = StepStatus.CANCELLED
                step.exit_code = 130
            step.end_time = job.end_time
        if self.jobtrace is not None:
            t = (job.end_time if job.end_time is not None
                 else (job.start_time or job.submit_time))
            self.jobtrace.stamp(job.job_id, job.requeue_count, "end", t,
                                epoch=self.fencing_epoch)
        self._finalize(job)
        self._trigger_dep_event(job)
        if job.array_parent_id is not None:
            self._on_array_child_terminal(job)

    def _finalize(self, job: Job) -> None:
        self.stats["jobs_finished_total"] += 1
        # array children never took a submit slot (the template owns it)
        if (self.account_meta is not None and job.qos_name
                and job.array_parent_id is None):
            self.account_meta.free_submit(job.spec.user, job.spec.account,
                                          job.qos_name)
        if self.global_usage is not None and job.array_parent_id is None:
            self.global_usage.note_release_submit(job.spec.user,
                                                  job.spec.account)
        self.history[job.job_id] = job
        if self.archive is not None:
            # archive BEFORE the WAL tombstone: once both exist the job
            # survives compaction and restart in the durable store
            self.archive.append(job)
            # with the durable store in place, RAM history is a bounded
            # recency cache — evict oldest-inserted beyond the cap
            # (without an archive the dict is the ONLY record: no evict)
            while len(self.history) > self.HISTORY_CACHE_MAX:
                self.history.pop(next(iter(self.history)))
        if self.wal is not None:
            self.wal.job_finalized(job)
            # periodic purge of finalized rows (the reference compacts
            # the embedded DB only after the Mongo transfer): safe to
            # automate ONLY with a durable archive — without one the
            # tombstones are the entire history
            if self.archive is not None:
                self._finalized_since_compact += 1
                if self._finalized_since_compact >= 1000:
                    self._finalized_since_compact = 0
                    self.wal.compact()

    # ------------------------------------------------------------------
    # suspend / resume (reference SuspendJobByCgroup/ResumeJobByCgroup,
    # JobManager.h:150-152; suspended time credited back to the limit,
    # JobScheduler.cpp:118-126)
    # ------------------------------------------------------------------

    def suspend(self, job_id: int, now: float) -> bool:
        job = self.running.get(job_id)
        if job is None or job.status != JobStatus.RUNNING:
            return False
        job.status = JobStatus.SUSPENDED
        job.suspend_time = now
        self._ledger.suspend(job_id, now)
        if self.wal is not None:
            self.wal.job_updated(job)
        self.dispatch_suspend(job_id, now)
        return True

    def resume(self, job_id: int, now: float) -> bool:
        job = self.running.get(job_id)
        if job is None or job.status != JobStatus.SUSPENDED:
            return False
        job.suspended_total += max(now - (job.suspend_time or now), 0.0)
        job.suspend_time = None
        job.status = JobStatus.RUNNING
        self._ledger.resume(job_id, now)
        if self.wal is not None:
            self.wal.job_updated(job)
        self.dispatch_resume(job_id, now)
        return True

    def dispatch_suspend(self, job_id: int, now: float) -> None:
        """Transport seam: freeze the job's cgroups on its nodes."""

    def dispatch_resume(self, job_id: int, now: float) -> None:
        """Transport seam: thaw the job's cgroups."""

    # ------------------------------------------------------------------
    # steps within a job allocation (reference StepInCtld +
    # StepScheduleThread_, CtldPublicDefs.h:521-782, JobScheduler.cpp:
    # 1985; AllocJobs = the allocation, AllocSteps/ExecuteStep = per-step
    # dispatch :1732-1839).  Batch jobs carry an implicit step 0; a
    # calloc-style ``alloc_only`` job holds the allocation while crun
    # steps are submitted, scheduled against the allocation's internal
    # capacity, and complete independently.
    # ------------------------------------------------------------------

    def _init_steps(self, job: Job, now: float) -> None:
        """Called when the allocation starts: batch jobs materialize
        their implicit step 0 (the batch script); alloc_only jobs start
        empty."""
        job.steps = {}
        if job.spec.alloc_only:
            job.next_step_id = 0
            return
        spec = job.spec
        job.steps[0] = Step(
            step_id=0,
            spec=StepSpec(name="batch", script=spec.script,
                          res=None, node_num=0,
                          time_limit=spec.time_limit,
                          output_path=spec.output_path,
                          interactive_address=spec.interactive_address,
                          pty=spec.pty,
                          interactive_token=spec.interactive_token,
                          sim_runtime=spec.sim_runtime,
                          sim_exit_code=spec.sim_exit_code),
            submit_time=now, status=StepStatus.RUNNING,
            start_time=now, node_ids=list(job.node_ids))
        job.next_step_id = 1

    def submit_step(self, job_id: int, spec: StepSpec,
                    now: float) -> int:
        """Add a step to a running allocation; returns step_id (-1 =
        rejected).  The step starts immediately if its per-node share
        fits in the allocation's remaining internal capacity, else waits
        PENDING until an earlier step finishes (the reference's step
        scheduling over the allocation)."""
        job = self.running.get(job_id)
        if job is None or job.status != JobStatus.RUNNING:
            return -1
        if job.cancel_requested:
            return -1
        if spec.node_num > len(job.node_ids):
            return -1
        if spec.res is not None:
            req = spec.res.encode(self.meta.layout)
            # must fit the allocation's per-node share at all (ignoring
            # other steps) or it can never start
            if not all((req <= alloc).all()
                       for alloc in self._job_alloc(job)):
                return -1
        step_id = job.next_step_id
        job.next_step_id += 1
        job.steps[step_id] = Step(step_id=step_id, spec=spec,
                                  submit_time=now)
        self._try_start_steps(job, now)
        if self.wal is not None:
            self.wal.job_updated(job)
        return step_id

    def _step_req(self, job: Job, step: Step) -> np.ndarray | None:
        """Per-node vector the step occupies, or None = whole allocation."""
        if step.spec.res is None:
            return None
        return step.spec.res.encode(self.meta.layout)

    def _try_start_steps(self, job: Job, now: float) -> list[int]:
        """Start pending steps (id order) that fit the allocation's free
        internal capacity.  A step with res=None takes whole nodes, so
        such steps serialize; sized steps pack."""
        started = []
        allocs = self._job_alloc(job)
        # free capacity per allocation node = alloc - sum(running steps)
        free = [a.astype(np.int64).copy() for a in allocs]
        whole_busy = [False] * len(job.node_ids)
        for st in job.steps.values():
            if st.status != StepStatus.RUNNING or st.spec.overlap:
                continue
            req = self._step_req(job, st)
            for n in st.node_ids:
                i = job.node_ids.index(n)
                if req is None:
                    whole_busy[i] = True
                else:
                    free[i] -= req
        for step_id in sorted(job.steps):
            step = job.steps[step_id]
            if step.status != StepStatus.PENDING:
                continue
            if step.spec.overlap:
                # observation channels (cattach): start immediately on
                # the step's span without holding any share (the Slurm
                # --overlap analog) — they neither block nor are
                # blocked by the allocation's internal packing.  A
                # follow_step targets the OBSERVED step's nodes (the
                # container lives there, not on the prefix).
                want = step.spec.node_num or len(job.node_ids)
                nodes = None
                if step.spec.follow_step is not None:
                    tgt = job.steps.get(step.spec.follow_step)
                    if tgt is not None and not tgt.status.is_terminal:
                        if tgt.status != StepStatus.RUNNING:
                            continue   # wait for the target to place
                        nodes = list(tgt.node_ids)[:want] \
                            if want < len(tgt.node_ids) \
                            else list(tgt.node_ids)
                step.status = StepStatus.RUNNING
                step.start_time = now
                step.node_ids = (nodes if nodes
                                 else job.node_ids[:want])
                started.append(step_id)
                self.dispatch_step(job, step)
                continue
            want = step.spec.node_num or len(job.node_ids)
            req = self._step_req(job, step)
            picked = []
            for i, n in enumerate(job.node_ids):
                if len(picked) == want:
                    break
                if whole_busy[i]:
                    continue
                if req is None:
                    if (free[i] == allocs[i]).all():
                        picked.append(i)
                elif (req <= free[i]).all():
                    picked.append(i)
            if len(picked) < want:
                continue
            step.status = StepStatus.RUNNING
            step.start_time = now
            step.node_ids = [job.node_ids[i] for i in picked]
            for i in picked:
                if req is None:
                    whole_busy[i] = True
                else:
                    free[i] -= req
            started.append(step_id)
            self.dispatch_step(job, step)
        return started

    def dispatch_step(self, job: Job, step: Step) -> None:
        """Transport seam: push the step to the allocation's craneds."""

    def dispatch_terminate_step(self, job_id: int, step_id: int,
                                now: float) -> None:
        """Transport seam: kill exactly one step."""

    def dispatch_free_alloc(self, job_id: int, now: float,
                            incarnation: int | None = None,
                            skip_node: int | None = None) -> None:
        """Transport seam: release the job's ALLOCATION on its craneds
        (kill remaining steps, drop cgroup + GRES).  Defaults to a plain
        terminate — the sim plane has no allocation state to free."""
        self.dispatch_terminate(job_id, now, incarnation=incarnation,
                                skip_node=skip_node)

    def cancel_step(self, job_id: int, step_id: int, now: float) -> bool:
        job = self.running.get(job_id)
        if job is None:
            return False
        step = job.steps.get(step_id)
        if step is None or step.status.is_terminal:
            return False
        step.cancel_requested = True
        if step.status == StepStatus.PENDING:
            step.status = StepStatus.CANCELLED
            step.end_time = now
            step.exit_code = 130
            if self.wal is not None:
                self.wal.job_updated(job)
            return True
        self.dispatch_terminate_step(job_id, step_id, now)
        self._step_cancel_sent[(job_id, step_id)] = now
        if self.wal is not None:
            self.wal.job_updated(job)
        self._kick()   # kill-intent renewal runs on the cycle thread
        return True

    def _teardown_alloc_job(self, job: Job, now: float,
                            status: JobStatus, exit_code: int) -> None:
        """Shared end-of-allocation path (cancel / cfree / time limit):
        free the allocation on the craneds, return the resources, and
        finalize with the given outcome.  Live steps are closed
        uniformly by _finalize_terminal (CANCELLED, 130) — callers must
        NOT pre-mark them, or the shared closer skips them and the
        exit code diverges between the paths."""
        self.dispatch_free_alloc(job.job_id, now,
                                 incarnation=job.requeue_count)
        self._release_job_resources(job)
        del self.running[job.job_id]
        self._cancel_kill_sent.pop(job.job_id, None)
        job.status = status
        job.end_time = now
        job.exit_code = exit_code
        self._finalize_terminal(job)

    def free_allocation(self, job_id: int, now: float) -> bool:
        """End an alloc_only job: kill running steps, release resources,
        finalize COMPLETED (the calloc exit path)."""
        job = self.running.get(job_id)
        if job is None or not job.spec.alloc_only:
            return False
        self._teardown_alloc_job(job, now, JobStatus.COMPLETED, 0)
        return True

    def step_report(self, job_id: int, step_id: int, status: StepStatus,
                    exit_code: int, now: float, node_id: int = -1,
                    incarnation: int | None = None,
                    cpu_seconds: float = 0.0,
                    max_rss_bytes: int = 0) -> None:
        """Per-step status report from a craned (or whole-step from the
        sim).  Steps aggregate per-node exactly like jobs; a terminal
        step frees its internal share and pulls the next pending step
        in.  Step 0 of a batch job closes the whole job (via the
        job-level status-change queue, preserving requeue semantics)."""
        job = self.running.get(job_id)
        if job is None:
            return
        if incarnation is not None and incarnation != job.requeue_count:
            return
        step = job.steps.get(step_id)
        if step is None or step.status.is_terminal:
            return

        def fold_usage():
            # efficiency accounting (ceff): cpu-seconds sum across
            # node reports, RSS keeps the peak; the job aggregates its
            # steps.  Folded only for ACCEPTED first-time reports —
            # a re-delivered or rejected report must not inflate ceff
            if cpu_seconds or max_rss_bytes:
                step.cpu_seconds += cpu_seconds
                step.max_rss_bytes = max(step.max_rss_bytes,
                                         max_rss_bytes)
                job.cpu_seconds += cpu_seconds
                job.max_rss_bytes = max(job.max_rss_bytes,
                                        max_rss_bytes)

        if node_id >= 0:
            if node_id not in step.node_ids:
                return
            if node_id not in step.node_reports:
                fold_usage()
            is_failure = status not in (StepStatus.COMPLETED,
                                        StepStatus.CANCELLED)
            had_failure = any(
                st not in (StepStatus.COMPLETED, StepStatus.CANCELLED)
                for st, _ in step.node_reports.values())
            step.node_reports[node_id] = (status, exit_code)
            if is_failure and not had_failure:
                self.dispatch_terminate_step(job_id, step_id, now)
            if not all(n in step.node_reports for n in step.node_ids):
                return
            status, exit_code = self._aggregate_step(step)
        else:
            fold_usage()   # whole-step (sim) form: accepted exactly
                           # once — the step turns terminal below
        step.status = status
        step.end_time = now
        step.exit_code = exit_code
        self._step_cancel_sent.pop((job_id, step_id), None)
        if self.wal is not None:
            self.wal.job_updated(job)
        if step_id == 0 and not job.spec.alloc_only:
            # the batch step IS the job: feed the job-level machine —
            # and wake the loop: the close runs on the cycle thread,
            # which may be deep in an idle sleep
            self._status_queue.append(StatusChange(
                job_id, JobStatus(status.value), exit_code, now,
                incarnation=job.requeue_count))
            self._kick()
            return
        self._try_start_steps(job, now)

    @staticmethod
    def _aggregate_step(step: Step) -> tuple[StepStatus, int]:
        """Worst-status-wins aggregation over the step's node reports
        (same rule as the job-level path)."""
        agg_status, agg_code = StepStatus.COMPLETED, 0
        for st, code in step.node_reports.values():
            if st not in (StepStatus.COMPLETED, StepStatus.CANCELLED):
                return st, code
        reports = list(step.node_reports.values())
        if any(st == StepStatus.CANCELLED for st, _ in reports):
            if (all(st == StepStatus.CANCELLED for st, _ in reports)
                    or step.cancel_requested):
                return StepStatus.CANCELLED, 130
        return agg_status, agg_code

    def _check_alloc_timeouts(self, now: float) -> None:
        """alloc_only jobs have no batch supervisor enforcing the time
        limit — the ctld cycle enforces it (reference: ctld-side
        termination timers for allocations).  Iterates the _alloc_only
        index, not the running map (the scan is per-cycle)."""
        for job_id in sorted(self._alloc_only):
            job = self.running.get(job_id)
            if job is None or not job.spec.alloc_only:
                continue
            if job.status != JobStatus.RUNNING:
                continue
            if now >= self._effective_end(job, now):
                self._teardown_alloc_job(job, now,
                                         JobStatus.EXCEED_TIME_LIMIT,
                                         124)

    def _effective_end(self, job: Job, now: float) -> float:
        """Expected end with suspended time credited back."""
        start = job.start_time if job.start_time is not None else now
        suspended = job.suspended_total
        if job.suspend_time is not None:   # currently frozen
            suspended += max(now - job.suspend_time, 0.0)
        return start + job.spec.time_limit + suspended

    # ------------------------------------------------------------------
    # node failure (reference CranedDown → TerminateJobsOnCraned,
    # JobScheduler.h:1076; EC_CRANED_DOWN requeue)
    # ------------------------------------------------------------------

    def on_craned_down(self, node_id: int, now: float) -> list[int]:
        """Node died: terminate its jobs; system-failure auto-requeue up
        to MaxRequeueCount, then held (CtldPublicDefs.h:101-102)."""
        node = self.meta.nodes.get(node_id)
        self.emit_node_event("node_down",
                             node.name if node else str(node_id),
                             now=now)
        victim_ids = self.meta.craned_down(node_id)
        for job_id in victim_ids:
            job = self.running.get(job_id)
            if job is None:
                continue
            # Kill the gang's steps on SURVIVING nodes before freeing the
            # resources (reference TerminateJobsOnCraned): without this a
            # multi-node job's live steps keep running while ctld re-places
            # work onto those nodes — orphaned workload + physical
            # oversubscription.  The node list is captured synchronously by
            # the dispatcher, so this must precede the running-map removal.
            # Incarnation-guarded (the requeue below bumps requeue_count;
            # an async kill racing the re-dispatch must miss the new run)
            # and skipping the dead node (RPCs to it only burn a worker).
            if len(job.node_ids) > 1:
                if job.spec.alloc_only:
                    # surviving nodes must also drop the explicit
                    # allocation (cgroup + GRES), not just kill steps —
                    # a lingering alloc would refuse the re-dispatch
                    self.dispatch_free_alloc(
                        job_id, now, incarnation=job.requeue_count,
                        skip_node=node_id)
                else:
                    self.dispatch_terminate(
                        job_id, now, incarnation=job.requeue_count,
                        skip_node=node_id)
            self._release_job_resources(job)
            del self.running[job_id]
            self._cancel_kill_sent.pop(job_id, None)
            if job.cancel_requested:
                # the kill we sent can no longer be confirmed; honor the
                # user's cancel instead of resurrecting the job
                job.status = JobStatus.CANCELLED
                job.end_time = now
                job.exit_code = 130
                self._finalize_terminal(job)
                continue
            job.reset_for_requeue()
            if job.requeue_count > self.config.max_requeue_count:
                # same terminal behavior as the status-change path:
                # requeued but held, operator must release
                job.held = True
                job.pending_reason = PendingReason.HELD
            self.pending[job_id] = job
            self.events.emit("requeue", job_id=job_id,
                             detail="node down", time=now)
            if self.wal is not None:
                self.wal.job_requeued(job)
        return victim_ids

    # minimum seconds between kill re-sends for one unconfirmed cancel:
    # each renewal is a full terminate fan-out whose RPCs can block up to
    # their timeout on an unresponsive craned, so renewing every 1 Hz
    # cycle would pile tasks onto the dispatcher pool faster than they
    # drain and starve healthy dispatches behind terminate retries
    CANCEL_RENEW_INTERVAL = 5.0

    def _renew_cancel_intents(self, now: float) -> None:
        """Re-send the kill for running jobs whose cancel intent is still
        unconfirmed.  A TerminateStep that reaches a craned before its
        ExecuteStep (both async on separate workers) is a no-op there, so
        a single kill can be lost and the cancelled job would run to
        completion; the intent is durable on the job, so re-dispatching
        (with backoff) until the Cancelled status change arrives closes
        the race (idempotent on the craned side)."""
        # keyed on the outstanding-cancel map (sized by cancels in
        # flight), NOT the running map — the latter would add an
        # O(running) scan to every cycle's prelude
        for job_id, last in list(self._cancel_kill_sent.items()):
            job = self.running.get(job_id)
            if job is None or not job.cancel_requested:
                self._cancel_kill_sent.pop(job_id, None)
                continue
            if now - last < self.CANCEL_RENEW_INTERVAL:
                continue
            self._cancel_kill_sent[job_id] = now
            self.dispatch_terminate(job_id, now)
        # step-level cancel intents renew identically (ADVICE r3: a lost
        # TerminateStep left a cancelled step running forever)
        for key, last in list(self._step_cancel_sent.items()):
            job_id, step_id = key
            job = self.running.get(job_id)
            step = job.steps.get(step_id) if job is not None else None
            if (step is None or step.status.is_terminal
                    or not step.cancel_requested):
                self._step_cancel_sent.pop(key, None)
                continue
            if now - last < self.CANCEL_RENEW_INTERVAL:
                continue
            self._step_cancel_sent[key] = now
            self.dispatch_terminate_step(job_id, step_id, now)
        # unconfirmed time-limit pushes renew every cycle (idempotent;
        # the dispatcher pops the intent once every node accepted) —
        # the update must land before the OLD deadline fires, so no
        # backoff: a modify is rare and the fan-out is tiny
        for job_id, (limit, _last) in list(self._limit_intents.items()):
            job = self.running.get(job_id)
            if job is None or job.spec.time_limit != limit:
                self._limit_intents.pop(job_id, None)
                continue
            self.dispatch_change_time_limit(job_id, limit, now)

    # ------------------------------------------------------------------
    # THE scheduling cycle (reference ScheduleThread_ :1321-1981)
    # ------------------------------------------------------------------

    def schedule_cycle(self, now: float) -> list[int]:
        """One cycle: drain status changes, snapshot, device solve, commit,
        dispatch.  Returns the job_ids started this cycle.  Per-phase
        wall-clock timings land in ``stats['last_cycle']`` (reference
        phase trace, JobScheduler.cpp:1444-1447).

        This driver runs every phase inline (single-threaded callers,
        tick mode, tests).  Concurrent servers use ``cycle_phases``
        directly and drop their lock around each yielded solve closure
        — see CtldServer._cycle_loop."""
        gen = self.cycle_phases(now)
        try:
            fn = next(gen)
            while True:
                fn = gen.send(fn())
        except StopIteration as stop:
            return stop.value or []

    def cycle_phases(self, now: float):
        """The cycle as a generator: code between yields mutates
        scheduler state and MUST run under the caller's lock; each
        yielded closure is pure compute over snapshot arrays (the
        device/native solve — the expensive 99%) and is safe to run
        with the lock released.  Mid-solve mutations are caught at
        commit: the meta event window (start_logging →
        ResReduceEvents, the reference's NodeSelect revalidation
        pattern, JobScheduler.cpp:1437-1540) flags touched nodes, and
        _commit re-checks pending membership, licenses, QoS and the
        authoritative ledger per job.

        WAL group commit: every lock-held segment of the cycle runs
        inside one WAL group (one write + one fsync for all its
        events), flushed BEFORE each yield — a group must never stay
        open across a lock release or RPC-path appends (submit acks)
        would buffer without their durability barrier.  The last
        yielded closure drains the post-commit dispatch ring, so no
        dispatch is issued until the group holding its job's ``start``
        record is durable."""
        wal = self.wal
        self._wal_cycle_base = ((wal.fsync_total, wal.groups_total)
                                if wal is not None else (0, 0))
        # introspection: per-cycle recompile attribution + the armed
        # profiler capture window tick (cheap no-ops when idle)
        self._cycle_compile_base = introspect.total_compiles()
        self.profiler_window.tick()
        self.flight.stamp("cycle_begin")
        self._wal_begin()
        try:
            started = yield from self._cycle_body(now)
            return started
        finally:
            # safety net for the watchdog's gen.close() and crashed
            # phases: no WAL event may sit buffered across cycles, and
            # a job committed to RUNNING must still get its dispatch
            # (drained inline here; the normal path drained lock-free)
            self._wal_flush()
            self._drain_dispatch_ring()
            self.flight.stamp("cycle_end")

    def _wal_begin(self) -> None:
        if self.wal is not None:
            self.wal.begin_batch()

    def _wal_flush(self) -> None:
        if self.wal is not None:
            self.wal.commit_batch()

    def _queue_dispatch(self, job: Job, node_ids: list[int]) -> None:
        """Ring entries capture incarnation + fencing epoch NOW, under
        the ctld lock at commit time: the ring drains lock-RELEASED, so
        a requeue or lease loss between queue and drain must not let a
        push go out stamped with the job's newer identity (the
        dispatcher's staleness guard and craned-side fencing both key
        off the values as of the commit).  The current WAL seq rides
        along as the durability watermark — the job's start record has
        seq <= it, so the drain can enforce durable-before-dispatch
        even on a failed barrier."""
        self._dispatch_ring.append((job, list(node_ids),
                                    job.requeue_count,
                                    self.fencing_epoch,
                                    self.wal.seq
                                    if self.wal is not None else 0))

    def _drain_dispatch_ring(self) -> int:
        """Issue every queued dispatch in commit order.  With a batched
        seam wired (GrpcDispatcher.dispatch_batch) the whole ring goes
        out in one call so the dispatcher can coalesce per craned.

        Entries whose WAL watermark is not yet durable are DROPPED, not
        dispatched: that only happens when the group's fsync failed
        (the daemon is about to die) — pushing work whose start record
        never hit disk would resurrect as a ghost allocation after the
        recovery replay requeues the job."""
        ring = self._dispatch_ring
        if not ring:
            return 0
        items: list[tuple] = []
        while ring:
            items.append(ring.popleft())
        if self.wal is not None:
            durable = self.wal.durable_seq
            items = [it for it in items if it[4] <= durable]
            if not items:
                return 0
        trace = self.jobtrace
        if trace is not None:
            # past the durability filter == the WAL group-commit
            # watermark covers each job's start record.  "dispatched"
            # is stamped as the push is ISSUED (the grpc dispatcher
            # pushes from pool threads; the sim plane runs inline and
            # stamps its craned-side spans during the call below, which
            # must sequence after these two).
            t = self._cycle_now
            for job, _nodes, inc, epoch, _seq in items:
                if job is None:  # dropped entry (cancelled at commit)
                    continue
                trace.stamp(job.job_id, inc, "committed_durable", t,
                            epoch=epoch)
                trace.stamp(job.job_id, inc, "dispatched", t,
                            epoch=epoch)
        self.flight.stamp("dispatch", detail=str(len(items)))
        if self.dispatch_batch is not None:
            self.dispatch_batch(items)
        else:
            for job, node_ids, *_ in items:
                self.dispatch(job, node_ids)
        return len(items)

    def _dispatch_phase(self):
        """The cycle's final yielded closure: drain the dispatch ring
        with the lock RELEASED.  Only built after _wal_flush — the
        durable-before-dispatch boundary."""
        import time as _time

        def run():
            t0 = _time.perf_counter()
            n = self._drain_dispatch_ring()
            return n, (_time.perf_counter() - t0) * 1e3

        return run

    def _note_dispatch(self, result) -> None:
        n, ms = result
        self._cur_trace["dispatch_ms"] = round(ms, 3)
        lc = self.stats.get("last_cycle")
        if isinstance(lc, dict):
            lc["dispatch_ms"] = round(ms, 3)
        _MET_PHASE.observe(ms / 1e3, phase="dispatch")

    def _cycle_body(self, now: float):
        import time as _time
        t0 = _time.perf_counter()
        # guards _initial_cost_reference (reference-only oracle) from
        # ever running inside a cycle; cleared by _record_cycle_stats /
        # _skip_cycle / the empty-candidates return
        self._in_cycle = True
        self._cur_trace = {
            "now": now, "queue_depth": len(self.pending),
            "solver": "", "solve_ms": 0.0,
            "preempted": 0, "backfilled": 0, "num_streams": 1,
        }
        _MET_PENDING.set(len(self.pending))
        self._cycle_now = now
        self.process_status_changes()
        self._check_craned_timeouts(now)
        self._check_alloc_timeouts(now)
        self._drain_deferred_evictions(now)
        self._renew_cancel_intents(now)
        self.meta.purge_expired_reservations(now)
        self._materialize_array_children(now)
        t_prelude = _time.perf_counter()
        self.flight.stamp("prelude")

        # no-op short-circuit: the drains above already ran (they are
        # the event sinks), so if no epoch moved since the last armed
        # zero-placement solve and no time edge passed, this cycle
        # would rebuild the identical inputs and place nothing — skip
        # before building anything
        fp = self._cycle_fingerprint()
        if (self.config.incremental and self._noop_fp is not None
                and fp == self._noop_fp and now < self._noop_edge
                and not self._dispatch_ring):
            return self._skip_cycle(t0, now, "fingerprint")
        self._cycle_fp0 = fp
        self._noop_fp = None
        self._cycle_usage_denied0 = (self.global_usage.denied
                                     if self.global_usage is not None
                                     else 0)

        self.stats["cycles"] += 1
        _MET_CYCLES.inc()
        candidates = self._pending_candidates(now)
        if self.jobtrace is not None and candidates:
            # first-sight "eligible" stamp per incarnation; the Job
            # attribute guard keeps repeat cycles at one attr probe per
            # candidate (the recorder's set probe would already be
            # cheap, but this avoids even its lock on the common path)
            fresh = []
            for job in candidates:
                if getattr(job, "_trace_eligible", -1) != \
                        job.requeue_count:
                    job._trace_eligible = job.requeue_count
                    fresh.append((job.job_id, job.requeue_count))
            if fresh:
                self.jobtrace.stamp_many("eligible", fresh, now)
        if not candidates:
            # empty cycles still refresh the liveness timestamp (the
            # watchdog's stall detection keys off it) but don't enter
            # the trace ring — an idle cluster would otherwise flush
            # every interesting trace out of the ring
            self.stats["last_cycle_walltime"] = _time.time()
            self.stats["last_cycle"] = {
                "prelude_ms": round((t_prelude - t0) * 1e3, 3),
                "pending": 0, "started": 0,
                "running": len(self.running)}
            self._skip_trace = None
            self._arm_noop(now)
            self._in_cycle = False
            return []
        limit = self.config.schedule_batch_size
        if len(candidates) > limit:
            for job in candidates[limit:]:
                job.pending_reason = PendingReason.PRIORITY
            candidates = candidates[:limit]
            if self._cand_rows is not None:
                self._cand_rows = self._cand_rows[:limit]

        # snapshot + event capture window (cpp:1437)
        self.meta.start_logging()
        avail, total, alive = self.meta.snapshot()

        ordered = self._priority_sort(candidates, now)
        for job in ordered:
            # spec epoch for the lock-free solve window: modify_job
            # REPLACES job.spec (dataclasses.replace), so object
            # identity detects any mid-solve modification — _commit
            # voids the placement of a job whose spec changed (e.g. a
            # partition move validated against the NEW partition while
            # the solve placed it in the OLD one)
            job._plan_spec = job.spec
        jobs_batch, max_nodes = self._build_batch(ordered, avail.shape[0],
                                                  now)
        cost0 = self._ledger.cost0(now, total.shape[0])

        # cycles containing packed/exclusive jobs route to the
        # full-fidelity packed solver (immediate-fit; such jobs don't get
        # backfill reservations this round)
        orows = self._ordered_rows
        if orows is not None and len(orows) == len(ordered):
            packed = bool(self._ptable.packed[orows].any())
        else:
            packed = any(j.spec.exclusive or j.spec.task_res is not None
                         or (j.spec.ntasks is not None
                             and j.spec.ntasks != j.spec.node_num)
                         or j.spec.ntasks_per_node_max > 1
                         for j in ordered)
        if packed:
            state = make_cluster_state(avail, total, alive, cost0)
            pbatch = self._packed_batch(jobs_batch.dense, ordered)
            self._wal_flush()
            placements = yield self._traced_solve(
                "packed", lambda: solve_packed(
                    state, pbatch, max_nodes=max_nodes)[0])
            self._wal_begin()
            started = self._commit(ordered, placements, now,
                                   tasks=np.asarray(placements.tasks))
            started += self._try_preemption(ordered, now)
            self._wal_flush()
            self._record_cycle_stats(t0, t_prelude, candidates, started,
                                     _time.perf_counter(), "packed")
            if self._dispatch_ring:
                self._note_dispatch((yield self._dispatch_phase()))
            return started

        topo = self._active_topology()
        if topo is not None:
            self._update_topo_fragmentation(topo, avail, total, alive)
        if topo is not None and (
                bool((self._ptable.nnum[orows] > 1).any())
                if orows is not None and len(orows) == len(ordered)
                else any(j.spec.node_num > 1 for j in ordered)):
            # gang cycle with a topology configured: route through the
            # best-fit-block solve (topo/place.py).  Backfill is skipped
            # for this cycle — locality dominates reservation lookahead
            # for gangs, and single-node cycles keep the full backfill
            # path (plus the block-major permutation, see
            # _immediate_solve).
            state = make_cluster_state(avail, total, alive, cost0)
            dense = (jobs_batch.dense
                     if isinstance(jobs_batch, FactoredJobBatch)
                     else jobs_batch)
            levels = topo.jnp_levels
            self._wal_flush()
            placements, _, topo_info = yield self._traced_solve(
                "topo", lambda: solve_greedy_topo(
                    state, dense, levels, max_nodes=max_nodes))
            self._wal_begin()
            self._note_topo(topo, ordered, topo_info)
            started = self._commit(ordered, placements, now)
            started += self._try_preemption(ordered, now)
            self._wal_flush()
            self._record_cycle_stats(t0, t_prelude, candidates, started,
                                     _time.perf_counter(), "topo")
            if self._dispatch_ring:
                self._note_dispatch((yield self._dispatch_phase()))
            return started

        if self.config.backfill:
            bf_max = max(1, self.config.backfill_max_jobs)
            if len(ordered) > bf_max:
                started = yield from self._split_backfill_phases(
                    ordered, jobs_batch, avail, total, alive, cost0,
                    max_nodes, now)
                started += self._try_preemption(ordered, now)
                self._wal_flush()
                self._record_cycle_stats(t0, t_prelude, candidates,
                                         started,
                                         _time.perf_counter(),
                                         "backfill-split")
                if self._dispatch_ring:
                    self._note_dispatch((yield self._dispatch_phase()))
                return started
            state = self._timed_state(now, avail, total, alive, cost0)
            tbatch = self._timed_batch(jobs_batch.dense, ordered)
            self._wal_flush()
            placements = yield self._traced_solve(
                "backfill", lambda: solve_backfill(
                    state, tbatch, edges=self._grid.jnp_edges,
                    max_nodes=max_nodes)[0])
            self._wal_begin()
            start_buckets = np.asarray(placements.start_bucket)
            self._cur_trace["backfilled"] = int(np.sum(
                np.asarray(placements.placed) & (start_buckets > 0)))
        else:
            self._wal_flush()
            placements, solver_name = yield self._traced_solve(
                None, lambda: self._immediate_solve(
                    avail, total, alive, cost0, jobs_batch, max_nodes,
                    resident_ok=True))
            self._wal_begin()
            start_buckets = None

        started = self._commit(ordered, placements, now, start_buckets)
        started += self._try_preemption(ordered, now)
        self._wal_flush()
        # double buffer: pre-upload the rows this commit dirtied so the
        # next cycle's resident patch finds them already on device
        self._resident.stage()
        self._record_cycle_stats(
            t0, t_prelude, candidates, started, _time.perf_counter(),
            "backfill" if self.config.backfill else solver_name)
        if self._dispatch_ring:
            self._note_dispatch((yield self._dispatch_phase()))
        return started

    def _immediate_solve(self, avail, total, alive, cost0, jobs_batch,
                         max_nodes, resident_ok=False):
        """Route one immediate-fit solve through the configured backend
        (auto/native/device/pallas/sharded — all bit-identical).

        When a topology is configured, the node axis is presented to the
        backend in block-major order (Topology.perm): the backends'
        ascending-cost / first-fit walks then cluster picks inside
        blocks — locality with zero kernel changes — and the chosen
        indices are mapped back to real node ids before commit.

        ``resident_ok=True`` (only the plain immediate cycle passes it —
        never the backfill-split tail solve, whose ``avail`` is the
        min-over-horizon array, and never under a topology permutation)
        lets the device/pallas/sharded backends use the cross-cycle
        resident ClusterState instead of rebuilding from host arrays."""
        topo = self._active_topology()
        perm = None
        if topo is not None:
            perm = topo.perm
            avail = np.asarray(avail)[perm]
            total = np.asarray(total)[perm]
            alive = np.asarray(alive)[perm]
            cost0 = np.asarray(cost0)[perm]
            jobs_batch = self._permute_batch(jobs_batch, topo)
            # permuted rows don't line up with meta node ids — the
            # resident dirty feed would patch the wrong rows
            self._resident.invalidate()
            resident_ok = False
        placements = None
        solver_name = "immediate"
        if self.config.solver in ("auto", "native"):
            placements = self._solve_native(avail, total, alive, cost0,
                                            jobs_batch, max_nodes)
            if placements is not None:
                solver_name = "native"
            elif self.config.solver == "native":
                raise RuntimeError("native solver unavailable")
        if placements is None and self.config.solver == "sharded":
            placements = self._solve_sharded(avail, total, alive, cost0,
                                             jobs_batch, max_nodes,
                                             resident_ok=resident_ok)
            solver_name = "sharded"
        if placements is None and self.config.solver == "pallas":
            placements, solver_name = self._solve_pallas(
                avail, total, alive, cost0, jobs_batch, max_nodes,
                resident_ok=resident_ok)
        if placements is None:
            dense = (jobs_batch.dense
                     if isinstance(jobs_batch, FactoredJobBatch)
                     else jobs_batch)
            if resident_ok and self._resident.enabled:
                state, _mode = self._resident.acquire(
                    avail, total, alive, cost0,
                    key=("device", int(np.asarray(avail).shape[0]),
                         int(np.asarray(avail).shape[1]),
                         self._mask_table.generation))
                import jax as _jax
                fn = (solve_greedy_donating
                      if _jax.default_backend() == "tpu" else solve_greedy)
                placements, new_state = fn(state, dense,
                                           max_nodes=max_nodes)
                self._resident.adopt(new_state)
            else:
                state = make_cluster_state(avail, total, alive, cost0)
                placements, _ = solve_greedy(state, dense,
                                             max_nodes=max_nodes)
        if perm is not None:
            nodes = np.asarray(placements.nodes)
            real = np.where(nodes >= 0, perm[np.maximum(nodes, 0)],
                            np.int32(-1)).astype(np.int32)
            placements = Placements(placed=np.asarray(placements.placed),
                                    nodes=real,
                                    reason=np.asarray(placements.reason))
        return placements, solver_name

    # ---- topology-aware placement (topo/) ----

    def _active_topology(self):
        """The attached Topology, or None when absent/stale (nodes
        registered after it was built — size mismatch means its arrays
        no longer line up with the snapshot)."""
        topo = getattr(self.meta, "topology", None)
        if topo is not None and topo.num_nodes != len(self.meta.nodes):
            return None
        return topo

    def _permute_batch(self, jobs_batch, topo):
        """Job batch with the node axis in block-major order."""
        jperm = topo.jnp_perm
        if isinstance(jobs_batch, FactoredJobBatch):
            node_class = jobs_batch.node_class_np
            return FactoredJobBatch(
                req=jobs_batch.req, node_num=jobs_batch.node_num,
                time_limit=jobs_batch.time_limit, valid=jobs_batch.valid,
                job_class=jobs_batch.job_class,
                class_masks=jobs_batch.class_masks[:, jperm],
                job_class_np=jobs_batch.job_class_np,
                class_rows_np=np.asarray(
                    jobs_batch.class_rows_np)[:, topo.perm],
                node_class_np=(np.asarray(node_class)[topo.perm]
                               if node_class is not None else None))
        return jobs_batch.replace(part_mask=jobs_batch.part_mask[:, jperm])

    def _update_topo_fragmentation(self, topo, avail, total, alive):
        """Per-level free-capacity fragmentation gauge + trace field,
        computed from the cycle snapshot (a free node is alive with its
        full capacity available)."""
        free = alive & (avail == total).all(axis=1)
        frags = topo.fragmentation(free)
        for name, frag in frags:
            _MET_TOPO_FRAG.set(frag, level=name)
        self._cur_trace["topo_frag"] = frags[0][1]

    def _note_topo(self, topo, ordered, info) -> None:
        """Record per-gang locality verdicts: trace fields, the
        cross-block counter, and each job's topo_block/cross_block."""
        import jax as _jax
        info = _jax.device_get(info)  # one transfer for all three
        in_b = info.in_block.tolist()
        crs = info.cross.tolist()
        blocks = info.block.tolist()
        n_in = sum(in_b)
        n_cross = sum(crs)
        self._cur_trace["topo_in_block"] = n_in
        self._cur_trace["topo_cross"] = n_cross
        self.stats["topo_in_block_total"] = (
            self.stats.get("topo_in_block_total", 0) + n_in)
        self.stats["topo_cross_block_total"] = (
            self.stats.get("topo_cross_block_total", 0) + n_cross)
        if n_cross:
            _MET_TOPO_CROSS.inc(n_cross)
        for i, job in enumerate(ordered):
            job.cross_block = bool(crs[i])
            job.topo_block = (
                topo.block_names[int(blocks[i])]
                if in_b[i] and blocks[i] >= 0
                else ("spanning" if crs[i] else ""))

    def _split_backfill_phases(self, ordered, jobs_batch, avail, total,
                               alive, cost0, max_nodes, now):
        """Bounded backfill lookahead (Slurm's sched/bf split): the
        timed solve with full reservation semantics covers only the top
        ``backfill_max_jobs`` priority jobs; the tail is placed by the
        fast immediate solver against the MIN-over-horizon availability
        of the post-reservation time map, so no tail placement can ever
        violate a head reservation (it fits even the tightest bucket —
        strictly conservative, like the rest of the grid design)."""
        bf_max = max(1, self.config.backfill_max_jobs)
        head, tail = ordered[:bf_max], ordered[bf_max:]

        # slice the already-built batch — rebuilding it would pay the
        # prelude twice per cycle in exactly the regime this split
        # exists to keep fast.  The head needs dense rows anyway (the
        # timed solver gathers per-job masks), so slice the device-side
        # gather; the tail STAYS factored — the immediate solve it feeds
        # is exactly the path the [C, N] table exists for.
        import jax

        hb = self._bucket(len(head))
        head_batch = jax.tree.map(lambda x: x[:hb], jobs_batch.dense)
        # rows past len(head) in the bucketed slice are REAL tail jobs —
        # invalidate them or they would place in both passes
        head_batch = head_batch.replace(valid=head_batch.valid & (
            jnp.arange(hb) < len(head)))
        tail_valid = jobs_batch.valid & (
            jnp.arange(jobs_batch.valid.shape[0]) >= bf_max)
        tail_batch = jobs_batch.with_valid(tail_valid)

        state = self._timed_state(now, avail, total, alive, cost0)
        tb = self._timed_batch(head_batch, head)
        self._wal_flush()
        placements, tstate = yield self._traced_solve(
            "backfill", lambda: solve_backfill(
                state, tb, edges=self._grid.jnp_edges,
                max_nodes=max_nodes))
        self._wal_begin()
        head_start = np.asarray(placements.start_bucket)
        self._cur_trace["backfilled"] = int(np.sum(
            np.asarray(placements.placed) & (head_start > 0)))
        started = self._commit(head, placements, now, head_start)

        # pass 2: the tail against the tightest bucket of the horizon
        self.meta.start_logging()   # fresh event window for this commit

        def _tail_solve():
            min_avail = np.asarray(jnp.min(tstate.time_avail, axis=1))
            cost1 = np.asarray(tstate.cost)
            return self._immediate_solve(
                min_avail, total, alive, cost1, tail_batch, max_nodes)

        self._wal_flush()
        placements2, _ = yield self._traced_solve(None, _tail_solve)
        self._wal_begin()
        tail_placements = Placements(
            placed=placements2.placed[bf_max:],
            nodes=placements2.nodes[bf_max:],
            reason=placements2.reason[bf_max:])
        started += self._commit(tail, tail_placements, now)
        return started

    def _traced_solve(self, backend, fn):
        """Wrap a yielded solve closure: time it (this is the
        lock-RELEASED span), tag it with a jax.profiler span so device
        traces line up with cycle phases, and record backend + latency
        into the in-flight cycle trace.  ``backend=None`` derives the
        label from an ``(placements, solver_name)`` result tuple
        (the _immediate_solve contract)."""
        import time as _time
        trace = self._cur_trace

        def run():
            label = backend or "immediate"
            t0 = _time.perf_counter()
            # the cycle's PRELUDE ends when the first solve starts:
            # priority sort + batch build + stream planning all count
            # toward it (that is the span the device-resident tables
            # exist to shrink, and what bench/tier1-perf assert on)
            trace.setdefault("_prelude_end", t0)
            with solve_span(f"crane:solve:{label}"):
                out = fn()
            # settle async device work before stopping the clock —
            # otherwise jax's deferred execution charges the whole
            # solve to the commit phase (the np.asarray sync there)
            first = out[0] if isinstance(out, tuple) else out
            sync = getattr(first, "placed", None)
            if hasattr(sync, "block_until_ready"):
                sync.block_until_ready()
            dt = _time.perf_counter() - t0
            if (backend is None and isinstance(out, tuple)
                    and len(out) == 2 and isinstance(out[1], str)):
                label = out[1]
            trace["solve_ms"] = trace.get("solve_ms", 0.0) + dt * 1e3
            if not trace.get("solver"):
                trace["solver"] = label
            _MET_SOLVE.observe(dt, backend=label)
            return out

        return run

    def _record_cycle_stats(self, t0, t_prelude, candidates, started,
                            t_end, solver: str) -> None:
        import time as _time
        self.stats["jobs_started_total"] += len(started)
        _MET_STARTED.inc(len(started))
        self.flight.stamp("commit", detail=str(len(started)))
        total_ms = (t_end - t0) * 1e3
        drain_ms = (t_prelude - t0) * 1e3
        # prelude = everything before the FIRST solve closure started
        # (status drains + sort + batch build); cycles that never solved
        # fall back to the drain span
        prelude_end = self._cur_trace.pop("_prelude_end", None)
        prelude_ms = (drain_ms if prelude_end is None
                      else (prelude_end - t0) * 1e3)
        solve_ms = float(self._cur_trace.get("solve_ms", 0.0))
        # commit = everything after the prelude that ran under the
        # lock, i.e. total minus prelude minus the lock-released solves.
        # Dispatch is NOT in here: the ring drains post-lock and its
        # span lands separately in dispatch_ms (_note_dispatch).
        commit_ms = max(total_ms - prelude_ms - solve_ms, 0.0)
        base_fsync, base_groups = getattr(self, "_wal_cycle_base",
                                          (0, 0))
        wal = self.wal
        wal_fsyncs = (wal.fsync_total - base_fsync
                      if wal is not None else 0)
        wal_groups = (wal.groups_total - base_groups
                      if wal is not None else 0)
        self.stats["last_cycle"] = {
            "solver": solver,
            "prelude_ms": round(prelude_ms, 3),
            "solve_commit_ms": round((t_end - t_prelude) * 1e3, 3),
            "total_ms": round(total_ms, 3),
            "dispatch_ms": 0.0,
            "pending": len(candidates),
            "started": len(started),
            "running": len(self.running),
        }
        self.stats["last_cycle_walltime"] = _time.time()
        trace = self._cur_trace
        trace.update(
            solver=solver,
            drain_ms=round(drain_ms, 3),
            prelude_ms=round(prelude_ms, 3),
            solve_ms=round(solve_ms, 3),
            commit_ms=round(commit_ms, 3),
            # placeholder: the dispatch ring drains AFTER this push (the
            # cycle's last, lock-released phase) and _note_dispatch
            # updates the ringed dict in place
            dispatch_ms=0.0,
            total_ms=round(total_ms, 3),
            lock_held_ms=round(prelude_ms + commit_ms, 3),
            wal_fsyncs=wal_fsyncs,
            wal_groups=wal_groups,
            candidates=len(candidates),
            placed=len(started),
            dirty_jobs=self._ptable.last_dirty,
            dirty_nodes=self.meta.last_snapshot_dirty,
        )
        res = self._resident
        res_mode = res.pop_cycle_mode()
        if res_mode is not None:
            trace.update(
                resident=res_mode,
                h2d_rows=res.last_h2d_rows,
                h2d_bytes=res.last_h2d_bytes,
                patch_overlap=bool(res.last_overlap),
            )
            _MET_H2D.inc(res.last_h2d_bytes, mode=res_mode)
            _MET_RESIDENT.inc(mode=res_mode)
            _MET_OVERLAP.set(res.overlap_share())
        # introspection plane: recompiles paid by THIS cycle (delta off
        # the process-wide observer) + device-memory gauges.  A warm
        # cycle paying a fresh compile breaks the bucketed-padding
        # contract — surface it as an event, not just a counter.
        recompiles = (introspect.total_compiles()
                      - getattr(self, "_cycle_compile_base", 0))
        mem = introspect.sample_device_memory()
        trace.update(
            recompiles=recompiles,
            device_bytes=mem["bytes"],
            device_peak_bytes=mem["peak_bytes"],
            device_buffers=mem["buffers"],
        )
        if recompiles > 0 and self.stats["cycles"] >= self.WARMUP_CYCLES:
            self.events.emit(
                "recompile_steady", "warning",
                detail="cycle %d paid %d recompile(s)" % (
                    self.stats["cycles"], recompiles))
        self._in_cycle = False
        self.cycle_trace.push(trace)
        self._skip_trace = None
        _MET_PHASE.observe(prelude_ms / 1e3, phase="prelude")
        _MET_PHASE.observe(solve_ms / 1e3, phase="solve")
        _MET_PHASE.observe(commit_ms / 1e3, phase="commit")
        _MET_LOCK.observe((prelude_ms + commit_ms) / 1e3)
        # a zero-placement solve with nothing preempted or in flight can
        # arm the no-op fingerprint: the next cycle seeing the same
        # epochs would rebuild identical inputs and place nothing
        if (not started and trace.get("preempted", 0) == 0
                and not self._dispatch_ring):
            self._arm_noop(trace.get("now", 0.0))

    def _solve_native(self, avail, total, alive, cost0, jobs_batch,
                      max_nodes):
        """The C++ treap solver for immediate-fit cycles (bit-identical
        to solve_greedy; tests/test_native_solver.py).  Returns None when
        the library or shape is unsupported — caller falls back."""
        from cranesched_tpu.utils import native

        class _Shim:
            pass

        common = (avail, total, alive.astype(np.uint8), cost0,
                  np.asarray(jobs_batch.req),
                  np.asarray(jobs_batch.node_num),
                  np.asarray(jobs_batch.time_limit),
                  np.asarray(jobs_batch.valid).astype(np.uint8))
        if isinstance(jobs_batch, FactoredJobBatch):
            node_class = jobs_batch.node_class_np
            if node_class is not None:
                # factored fast path: class ids in, no [J, N] mask
                # materialized anywhere (partition-id mode)
                out = native.solve_greedy_native(
                    *common, max_nodes=max_nodes,
                    job_part=jobs_batch.job_class_np,
                    node_part=node_class)
            else:
                # overlapping classes: host gather of the C rows —
                # still no per-job _mask_for rebuild
                out = native.solve_greedy_native(
                    *common, max_nodes=max_nodes,
                    mask=jobs_batch.dense_mask_np())
        else:
            out = native.solve_greedy_native(
                *common, max_nodes=max_nodes,
                mask=np.asarray(jobs_batch.part_mask))
        if out is None:
            return None
        shim = _Shim()
        shim.placed, shim.nodes, shim.reason = out[0], out[1], out[2]
        return shim

    def _solve_sharded(self, avail, total, alive, cost0, jobs_batch,
                       max_nodes, resident_ok=False):
        """Node-axis-sharded multi-chip solve (parallel/sharded.py):
        cluster tensors are sharded over every visible device, the
        per-job candidate merge rides ICI all_gathers.  Bit-identical
        placements to solve_greedy (tests/test_sharded_parity.py);
        the multichip dryrun asserts the same through this exact path.

        With ``resident_ok`` the cluster state comes from the
        cross-cycle resident store: the dirty-row patch scatters into
        the node-sharded buffers (each row lands on its owning shard)
        instead of re-uploading the full [N, R] state.  The resident
        key carries the mesh descriptor (procs x local devices) so any
        mesh reshape — device count change, future multi-process
        attach — invalidates the state rather than patching buffers
        laid out for a different shard map."""
        from cranesched_tpu.parallel.sharded import (
            make_node_mesh,
            shard_cluster_state,
            solve_greedy_sharded,
            solve_greedy_sharded_classes,
        )

        if self._mesh is None:
            self._mesh = make_node_mesh()
        mesh = self._mesh
        d = mesh.devices.size
        # single-process scheduler: 1 process x d local devices (the
        # multi-process ProcessMesh path reports its own via describe())
        mesh_desc = f"1x{d}"
        self._cur_trace["mesh"] = mesh_desc
        n = avail.shape[0]
        pad = (-n) % d
        factored = isinstance(jobs_batch, FactoredJobBatch)
        class_masks = jobs_batch.class_masks if factored else None
        if pad:
            # pad with permanently-dead nodes so the node axis divides
            # the mesh; they are never eligible, so placements and the
            # trailing ledger rows are unaffected
            zrow = np.zeros((pad, avail.shape[1]), avail.dtype)
            avail = np.concatenate([avail, zrow])
            total = np.concatenate([total, zrow])
            alive = np.concatenate([alive, np.zeros(pad, bool)])
            cost0 = np.concatenate(
                [cost0, np.zeros(pad, cost0.dtype)])
            if factored:
                class_masks = jnp.pad(class_masks, ((0, 0), (0, pad)),
                                      constant_values=False)
            else:
                jobs_batch = jobs_batch.replace(part_mask=jnp.pad(
                    jobs_batch.part_mask, ((0, 0), (0, pad)),
                    constant_values=False))
        use_resident = resident_ok and self._resident.enabled
        if use_resident:
            # padded shape + mesh descriptor in the key: a node-count
            # change (different pad) or mesh reshape drops the state
            state, _mode = self._resident.acquire(
                avail, total, alive, cost0,
                key=("sharded", int(avail.shape[0]),
                     int(avail.shape[1]),
                     self._mask_table.generation, mesh_desc))
        else:
            state = make_cluster_state(avail, total, alive, cost0)
        # re-assert the node-axis sharding every cycle: a no-op when
        # the resident buffers already live on their shards (rebuild /
        # first cycle is the only real transfer)
        state = shard_cluster_state(state, mesh)
        if factored:
            # class-factored path: the [C, N] table is the only mask
            # that crosses the host→device boundary, and class-disjoint
            # batches decode S jobs per collective round (streamed)
            placements, new_state = solve_greedy_sharded_classes(
                state, jobs_batch.req, jobs_batch.node_num,
                jobs_batch.time_limit, jobs_batch.valid,
                jobs_batch.job_class, class_masks, mesh,
                max_nodes=max_nodes)
        else:
            placements, new_state = solve_greedy_sharded(
                state, jobs_batch, mesh, max_nodes=max_nodes)
        if use_resident:
            self._resident.adopt(new_state)
        return placements

    def _solve_pallas(self, avail, total, alive, cost0, jobs_batch,
                      max_nodes, resident_ok=False):
        """Single-kernel TPU solve (models/pallas_solver.py), returning
        ``(placements, label)``.  A factored batch feeds the kernel its
        class table directly (no dense mask anywhere); class-disjoint
        batches run the S-stream decomposition, labeled
        ``pallas-stream`` with ``num_streams`` in the cycle trace —
        both derived from the plan the auto dispatch ACTUALLY ran with,
        including the planner's internal decision when no cached plan
        exists.  On TPU the cluster-state buffers are donated; with
        ``resident_ok`` they come from the cross-cycle resident state
        (dirty-row scatter patch) instead of a fresh host upload.
        Non-TPU backends run in interpret mode (tests)."""
        import jax as _jax

        from cranesched_tpu.models.pallas_solver import (
            plan_streams,
            solve_greedy_pallas_auto,
            solve_greedy_pallas_from_batch,
        )

        on_tpu = _jax.default_backend() == "tpu"
        cfg = self.config
        if resident_ok and self._resident.enabled:
            state, _mode = self._resident.acquire(
                avail, total, alive, cost0,
                key=("pallas", int(np.asarray(avail).shape[0]),
                     int(np.asarray(avail).shape[1]),
                     self._mask_table.generation))
        else:
            state = make_cluster_state(avail, total, alive, cost0)
        if not isinstance(jobs_batch, FactoredJobBatch):
            placements, new_state, used_plan = (
                solve_greedy_pallas_from_batch(
                    state, jobs_batch, max_nodes=max_nodes,
                    block_jobs=cfg.block_jobs,
                    max_streams=cfg.max_streams,
                    interpret=not on_tpu, donate=on_tpu,
                    return_plan=True))
        else:
            plan = None
            if self._mask_table.disjoint:
                # the table already proved its rows disjoint (cached
                # per epoch) — the planner skips its [C, N] host
                # reduction
                plan = plan_streams(jobs_batch.job_class_np,
                                    jobs_batch.class_rows_np,
                                    max_streams=cfg.max_streams,
                                    block_jobs=cfg.block_jobs,
                                    known_disjoint=True)
            placements, new_state, used_plan = solve_greedy_pallas_auto(
                state, jobs_batch.req, jobs_batch.node_num,
                jobs_batch.time_limit, jobs_batch.valid,
                jobs_batch.job_class, jobs_batch.class_masks,
                max_nodes=max_nodes, block_jobs=cfg.block_jobs,
                max_streams=cfg.max_streams, interpret=not on_tpu,
                donate=on_tpu, plan=plan, return_plan=True)
        if resident_ok and self._resident.enabled:
            self._resident.adopt(new_state)
        num_streams = used_plan[1] if used_plan is not None else 1
        self._cur_trace["num_streams"] = num_streams
        return placements, ("pallas-stream" if num_streams > 1
                            else "pallas")

    def _initial_cost_reference(self, now: float,
                                total: np.ndarray) -> np.ndarray:
        """REFERENCE-ONLY implementation of the cost seed: the
        O(running × nodes) per-job Python loop the RunLedger replaced,
        kept solely so parity tests can assert the incremental ledger
        is bit-identical (reference NodeRater, JobScheduler.h:499-516:
        cost = Σ (end - now) * cpu / cpu_total).  Never called from the
        scheduling cycle — cycles seed costs from ``_ledger.cost0`` —
        and the assert below keeps it that way."""
        assert not getattr(self, "_in_cycle", False), (
            "_initial_cost_reference is a test-only oracle; the cycle "
            "seeds costs from RunLedger.cost0")
        cost = np.zeros(total.shape[0], np.int64)
        for job in self.running.values():
            end = self._effective_end(job, now)
            remaining = max(end - now, 0.0)
            for n, alloc in zip(job.node_ids, self._job_alloc(job)):
                cpus = float(alloc[DIM_CPU]) / CPU_SCALE
                cpu_total = max(float(total[n, DIM_CPU]) / CPU_SCALE, 1e-9)
                # int32 fixed-point ledger units (models/solver.py
                # COST_SCALE) so the seeded base keeps cost accumulation
                # associative across all solver implementations
                cost[n] += int(np.round(
                    np.float32(remaining) * np.float32(cpus)
                    * np.float32(COST_SCALE) / np.float32(cpu_total)))
        return cost.astype(np.int32)

    def _timed_state(self, now, avail, total, alive, cost0):
        res = self.config.time_resolution
        T = self.config.time_buckets
        # one release row per (job, node) straight from the incremental
        # ledger — O(rows) numpy, no Python loop over running jobs
        run_nodes, run_req, run_end = self._ledger.timed_rows(
            now, res, T, grid=self._grid)
        # bucket the row count: the running set changes by a few rows
        # every cycle, and each fresh shape recompiles the release
        # scatter (measured ~300 ms/cycle of prelude).  Padding rows use
        # node -1, which the scatter drops as out-of-bounds
        m = run_nodes.shape[0]
        mp = self._bucket(m)
        if mp != m:
            run_nodes = np.concatenate([run_nodes, np.full(
                (mp - m, run_nodes.shape[1]), -1, np.int32)])
            run_req = np.concatenate([run_req, np.zeros(
                (mp - m, run_req.shape[1]), np.int32)])
            run_end = np.concatenate([run_end, np.full(
                mp - m, T, np.int32)])
        return make_timed_state(avail, total, alive, run_nodes, run_req,
                                run_end, T, cost0)

    def _packed_batch(self, batch: JobBatch, ordered: list[Job]
                      ) -> PackedJobBatch:
        lay = self.meta.layout
        J = batch.req.shape[0]
        node_req = np.zeros((J, lay.num_dims), np.int32)
        task_req = np.zeros((J, lay.num_dims), np.int32)
        ntasks = np.ones(J, np.int32)
        nt_min = np.ones(J, np.int32)
        nt_max = np.ones(J, np.int32)
        exclusive = np.zeros(J, bool)
        for i, job in enumerate(ordered):
            spec = job.spec
            node_req[i] = spec.res.encode(lay)
            if spec.task_res is not None:
                task_req[i] = spec.task_res.encode(lay)
            ntasks[i] = (spec.ntasks if spec.ntasks is not None
                         else spec.node_num)
            nt_min[i] = spec.ntasks_per_node_min
            nt_max[i] = max(spec.ntasks_per_node_max,
                            spec.ntasks_per_node_min)
            exclusive[i] = spec.exclusive
        return PackedJobBatch(
            node_req=jnp.asarray(node_req), task_req=jnp.asarray(task_req),
            ntasks=jnp.asarray(ntasks), ntasks_min=jnp.asarray(nt_min),
            ntasks_max=jnp.asarray(nt_max), node_num=batch.node_num,
            time_limit=batch.time_limit, part_mask=batch.part_mask,
            exclusive=jnp.asarray(exclusive), valid=batch.valid)

    def _timed_batch(self, batch: JobBatch, ordered: list[Job]
                     ) -> TimedJobBatch:
        # time_limit stays in seconds; the solver derives occupancy
        # windows from the grid edges passed alongside the batch
        return TimedJobBatch(req=batch.req, node_num=batch.node_num,
                             time_limit=batch.time_limit,
                             part_mask=batch.part_mask, valid=batch.valid)

    # ------------------------------------------------------------------
    # job arrays (reference ArrayManager, Array.h:51-177: the parent is a
    # pending template; the scheduler materializes at most ONE child per
    # parent per cycle, bounded by the %N run limit)
    # ------------------------------------------------------------------

    def _materialize_array_children(self, now: float) -> None:
        # the _array_templates index replaces an O(pending) scan; id
        # order == the old dict-iteration order (ids are monotonic)
        for parent_id in sorted(self._array_templates):
            parent = self.pending.get(parent_id)
            if parent is None:
                continue
            if parent.spec.array is None or not parent.array_remaining:
                continue
            if parent.held:
                continue
            if self._deps_runnable(parent, now) is not None:
                continue
            limit = parent.spec.array.max_concurrent
            live = sum(1 for c in parent.array_children
                       if not (self.job_info(c) or parent).status
                       .is_terminal)
            if limit and live >= limit:
                continue
            task_id = parent.array_remaining.pop(0)
            child_spec = dataclasses.replace(
                parent.spec, array=None,
                name=f"{parent.spec.name}_{task_id}")
            child_id = self._next_job_id
            self._next_job_id += 1
            child = Job(job_id=child_id, spec=child_spec,
                        submit_time=parent.submit_time,
                        qos_name=parent.qos_name,
                        qos_priority=parent.qos_priority,
                        array_parent_id=parent.job_id,
                        array_task_id=task_id)
            parent.array_children.append(child_id)
            self.pending[child_id] = child
            if self.wal is not None:
                self.wal.job_submitted(child)
                self.wal.job_updated(parent)

    def _on_array_child_terminal(self, child: Job) -> None:
        """Reference OnChildTerminal: parent finishes when every task id
        has materialized and reached a terminal state."""
        parent = self.pending.get(child.array_parent_id)
        if parent is None:
            return
        if not parent.array_remaining and all(
                (self.job_info(c) is not None
                 and self.job_info(c).status.is_terminal)
                for c in parent.array_children):
            del self.pending[parent.job_id]
            statuses = [self.job_info(c).status
                        for c in parent.array_children]
            parent.status = (
                JobStatus.COMPLETED
                if all(st == JobStatus.COMPLETED for st in statuses)
                else JobStatus.FAILED)
            parent.end_time = child.end_time
            self._finalize_terminal(parent)

    # ------------------------------------------------------------------
    # QoS preemption (reference TryPreempt_, JobScheduler.cpp:6378-6505:
    # a blocked job whose QoS lists lower QoS as preemptable evicts their
    # running jobs; victims ordered lowest-qos-first then youngest-first)
    # ------------------------------------------------------------------

    def _preemptor_req(self, job: Job) -> tuple[np.ndarray, list[int]]:
        """Per-node requirement a preemptor needs freed, plus its task
        layout.  Packed jobs use the balanced layout's MAX per-node
        requirement in the what-if (the commit distributes floor tasks
        to later nodes, which can only use less)."""
        spec = job.spec
        base = spec.res.encode(self.meta.layout).astype(np.int64)
        ntasks = spec.ntasks if spec.ntasks is not None else \
            spec.node_num
        # balanced layout ALWAYS (for ntasks == node_num it is all
        # ones): an empty layout would make the dispatcher fall back to
        # one task per node and launch half the gang
        hi = int(np.ceil(ntasks / spec.node_num))
        lo = ntasks // spec.node_num
        n_hi = ntasks - lo * spec.node_num
        layout = [hi] * n_hi + [lo] * (spec.node_num - n_hi)
        if spec.task_res is None:
            return base, layout
        task = spec.task_res.encode(self.meta.layout).astype(np.int64)
        return base + task * hi, layout

    def _try_preemption(self, ordered: list[Job], now: float) -> list[int]:
        """Device-side what-if (models/preempt.solve_preempt — the
        prefix-sum formulation of the reference's PreemptSegTree) +
        host-authoritative commit.  Runs after the normal solve, so a
        job that got only a future-start backfill reservation can still
        preempt its way to an immediate start (the reference's ordering:
        TryPreempt_ before Backfill_, cpp:6369-6378)."""
        if self.config.preempt_mode == "off" or self.accounts is None:
            return []
        # blocked preemptor candidates, in priority order
        cands = []
        prey_sets = []
        for job in ordered:
            if job.job_id not in self.pending:
                continue  # it placed normally
            if job.pending_reason not in (PendingReason.RESOURCE,
                                          PendingReason.PRIORITY):
                continue
            qos = self.accounts.qos.get(job.qos_name)
            if qos is None or not qos.preempt:
                continue
            cands.append(job)
            prey_sets.append(qos.preempt)
        if not cands:
            return []
        # victim pool: only jobs SOME candidate may actually prey on —
        # the kernel builds [M, N, R] tensors per scan step, so the
        # pool must be bounded by preemptable jobs, not the whole
        # running set.  Sorted ONCE by the reference order (lowest qos
        # first, youngest first); the global sort induces the same
        # per-node prefix order the segment-tree walk used.
        prey_union = set().union(*prey_sets)
        victims = sorted(
            (j for j in self.running.values()
             if j.qos_name in prey_union),
            key=lambda v: (v.qos_priority, -(v.start_time or 0.0)))
        if not victims:
            return []

        from cranesched_tpu.models.preempt import (
            PreemptorBatch, VictimRows, solve_preempt)

        lay = self.meta.layout
        avail, total, alive = self.meta.snapshot()
        N = total.shape[0]
        # flat (victim, node) rows, padded to a bucketed size
        rows = [(vi, n, alloc) for vi, v in enumerate(victims)
                for n, alloc in zip(v.node_ids, self._job_alloc(v))]
        M = self._bucket(len(rows))
        V = self._bucket(len(victims))
        r_vid = np.zeros(M, np.int32)
        r_node = np.full(M, -1, np.int32)
        r_alloc = np.zeros((M, lay.num_dims), np.int32)
        r_valid = np.zeros(M, bool)
        for i, (vi, n, alloc) in enumerate(rows):
            r_vid[i], r_node[i], r_alloc[i] = vi, n, alloc
            r_valid[i] = True

        J = self._bucket(len(cands))
        req = np.zeros((J, lay.num_dims), np.int64)
        node_num = np.zeros(J, np.int32)
        time_limit = np.zeros(J, np.int32)
        part_mask = np.zeros((J, N), bool)
        exclusive = np.zeros(J, bool)
        can_prey = np.zeros((J, V), bool)
        valid = np.zeros(J, bool)
        layouts = []
        for i, (job, prey) in enumerate(zip(cands, prey_sets)):
            jr, layout = self._preemptor_req(job)
            layouts.append(layout)
            req[i] = jr
            node_num[i] = job.spec.node_num
            time_limit[i] = job.spec.time_limit
            part_mask[i] = self._mask_for(job, now)
            exclusive[i] = job.spec.exclusive
            valid[i] = True
            for vi, v in enumerate(victims):
                can_prey[i, vi] = v.qos_name in prey
        max_nodes = self._bucket(
            max(1, min(int(node_num.max(initial=1)),
                       self.config.max_nodes_per_job)), floor=1)

        batch = PreemptorBatch(
            req=jnp.asarray(req, jnp.int32),
            node_num=jnp.asarray(node_num),
            time_limit=jnp.asarray(time_limit),
            part_mask=jnp.asarray(part_mask),
            exclusive=jnp.asarray(exclusive),
            can_prey=jnp.asarray(can_prey),
            valid=jnp.asarray(valid))
        vrows = VictimRows(vid=jnp.asarray(r_vid),
                           node=jnp.asarray(r_node),
                           alloc=jnp.asarray(r_alloc),
                           valid=jnp.asarray(r_valid))
        start_buckets = None
        if self.config.backfill:
            # time-axis what-if (models/preempt_time — the reference's
            # PreemptSegTree capability): a preemptor may combine
            # eviction with waiting for natural releases.  Victim rows
            # carry their release bucket; decisions carry a start
            # bucket: s == 0 starts now, s > 0 kills the victims now
            # and leaves the preemptor pending (the next cycles' solve
            # re-reserves its window against the freed resources).
            from cranesched_tpu.models.preempt_time import (
                TimedPreemptorBatch, TimedVictimRows,
                solve_preempt_timed)

            T = self.config.time_buckets
            r_end = np.full(M, T + 1, np.int32)
            for i, (vi, _n, _a) in enumerate(rows):
                v = victims[vi]
                remain = max((v.start_time or now)
                             + v.spec.time_limit - now, 0.0)
                r_end[i] = min(int(self._grid.release_bucket(remain)),
                               T + 1)
            tstate = self._timed_state(now, avail, total, alive,
                                       self._ledger.cost0(now, N))
            tbatch = TimedPreemptorBatch(
                req=batch.req, node_num=batch.node_num,
                time_limit=batch.time_limit,
                part_mask=batch.part_mask, exclusive=batch.exclusive,
                can_prey=batch.can_prey, valid=batch.valid)
            decisions, _ = solve_preempt_timed(
                tstate.time_avail, total, alive, tstate.cost,
                TimedVictimRows(rows=vrows,
                                end_bucket=jnp.asarray(r_end)),
                tbatch, num_victims=V, max_nodes=max_nodes,
                edges=self._grid.jnp_edges)
            start_buckets = np.asarray(decisions.start_bucket)
        else:
            decisions, _ = solve_preempt(
                avail, total, alive, self._ledger.cost0(now, N),
                vrows, batch, num_victims=V, max_nodes=max_nodes)

        placed = np.asarray(decisions.placed)
        nodes_mat = np.asarray(decisions.nodes)
        evict_mat = np.asarray(decisions.evict)
        started: list[int] = []
        for i, job in enumerate(cands):
            if not placed[i]:
                continue
            chosen = [int(n) for n in nodes_mat[i] if n >= 0]
            evict_ids = [victims[vi].job_id
                         for vi in np.nonzero(evict_mat[i])[0]
                         if vi < len(victims)]
            if start_buckets is not None and start_buckets[i] > 0:
                # Future-start preemption: the preemptor cannot start
                # until its start bucket, so killing the victims NOW
                # would strand their resources idle for the whole gap
                # (the documented divergence in models/preempt_time.py;
                # reference JobScheduler.cpp:6378-6505 keeps victims
                # running).  Defer the eviction to the start-bucket
                # edge instead: the event-driven loop wakes via
                # next_wake_time and the cycle prelude drains due
                # entries.  Re-solving each cycle refreshes the due
                # time, and a preemptor that gets placed (or cancelled)
                # before then releases its victims unharmed.
                if evict_ids:
                    due = now + float(self._grid.edges[
                        min(int(start_buckets[i]), T)])
                    for victim_id in evict_ids:
                        self._deferred_evictions[victim_id] = (
                            due, job.job_id)
                    job.pending_reason = PendingReason.PRIORITY
                continue
            if self._commit_preemption(job, chosen, evict_ids,
                                       layouts[i], now):
                started.append(job.job_id)
            else:
                # the device sequenced later candidates assuming this
                # one placed; their decisions are now stale — stop here
                # (they retry next cycle against fresh state) rather
                # than kill victims for placements that cannot commit
                break
        return started

    def _commit_preemption(self, job: Job, chosen: list[int],
                           evict_ids: list[int], layout: list[int],
                           now: float) -> bool:
        """Host-authoritative commit of one device preemption decision:
        admission checks BEFORE any eviction (victims must never die for
        a preemptor that cannot start), then evict, then malloc with
        mid-cycle revalidation."""
        if len(chosen) < job.spec.node_num:
            return False
        if job.spec.licenses and not self.licenses.malloc(
                job.spec.licenses):
            job.pending_reason = PendingReason.LICENSE
            return False
        if not self._malloc_run_limits(job):
            self.licenses.free(job.spec.licenses or {})
            job.pending_reason = PendingReason.QOS_LIMIT
            return False

        for victim_id in evict_ids:
            self._evict(victim_id, now)
        job.node_ids = chosen
        job.task_layout = list(layout)
        job.alloc_cache = None
        if not self.meta.malloc_resource(job.job_id, chosen,
                                         self._job_alloc(job)):
            # only a mid-cycle reduce event can get here; undo admission
            self.licenses.free(job.spec.licenses or {})
            self._free_run_limits(job)
            job.node_ids = []
            job.task_layout = []
            job.alloc_cache = None
            job.pending_reason = PendingReason.RESOURCE
            return False
        del self.pending[job.job_id]
        job.status = JobStatus.RUNNING
        job.start_time = now
        job.pending_reason = PendingReason.NONE
        self._init_steps(job, now)
        self.running[job.job_id] = job
        self._ledger_add(job, now)
        if self.wal is not None:
            self.wal.job_started(job)
        if self.jobtrace is not None:
            self.jobtrace.stamp(job.job_id, job.requeue_count, "placed",
                                now, epoch=self.fencing_epoch)
        self._trigger_dep_event(job)
        # onto the ring: the push goes out post-lock, after the cycle's
        # WAL group (holding this start record) is durable
        self._queue_dispatch(job, chosen)
        return True

    def _drain_deferred_evictions(self, now: float) -> None:
        """Fire timed-preemption evictions whose start bucket arrived.

        Entries are claims, not commitments: each cycle's solve rewrites
        the due time, and a claim is void the moment its preemptor left
        the pending queue (placed, cancelled, held) or the victim ended
        on its own — void entries are dropped without killing anything.
        Not WAL-persisted: after a failover the promoted leader's first
        solve re-derives the same claims from the same pending state."""
        if not self._deferred_evictions:
            return
        for victim_id in list(self._deferred_evictions):
            due, preemptor_id = self._deferred_evictions[victim_id]
            preemptor = self.pending.get(preemptor_id)
            if (preemptor is None or preemptor.held
                    or victim_id not in self.running):
                del self._deferred_evictions[victim_id]
                continue
            if due <= now:
                del self._deferred_evictions[victim_id]
                self._evict(victim_id, now)

    def _evict(self, victim_id: int, now: float) -> None:
        """Evict a running job for a preemptor: kill its steps, free its
        resources, then requeue or cancel per PreemptMode."""
        victim = self.running.get(victim_id)
        if victim is None:
            return
        _MET_PREEMPTED.inc()
        self._cur_trace["preempted"] = (
            self._cur_trace.get("preempted", 0) + 1)
        self.events.emit("preemption", "warning", job_id=victim_id,
                         detail="mode=%s" % self.config.preempt_mode,
                         time=now)
        if victim.spec.alloc_only:
            self.dispatch_free_alloc(victim_id, now,
                                     incarnation=victim.requeue_count)
        else:
            self.dispatch_terminate(victim_id, now,
                                    incarnation=victim.requeue_count)
        self._release_job_resources(victim)
        del self.running[victim_id]
        self._cancel_kill_sent.pop(victim_id, None)
        if victim.cancel_requested:
            # the user already cancelled this job (kill in flight); honor
            # the cancel instead of resurrecting it as PREEMPTED — same
            # contract as the on_craned_down path
            victim.status = JobStatus.CANCELLED
            victim.end_time = now
            victim.exit_code = 130
            self._finalize_terminal(victim)
            return
        if self.config.preempt_mode == "requeue":
            if self.jobtrace is not None:
                self.jobtrace.stamp(victim_id, victim.requeue_count,
                                    "requeue", now)
            victim.reset_for_requeue()
            victim.pending_reason = PendingReason.PREEMPTED
            if victim.requeue_count > self.config.max_requeue_count:
                # same cap as every other requeue path: held, operator
                # must release
                victim.held = True
                victim.pending_reason = PendingReason.HELD
            self.pending[victim_id] = victim
            if self.wal is not None:
                self.wal.job_requeued(victim)
        else:  # cancel
            victim.status = JobStatus.CANCELLED
            victim.end_time = now
            victim.exit_code = 143
            self._finalize_terminal(victim)

    def _check_craned_timeouts(self, now: float) -> None:
        """Ping-miss failure detection (reference ping FSM + CranedDown,
        SURVEY §3.5): real craneds that stopped pinging are declared dead
        and their jobs requeued."""
        for node in self.meta.nodes.values():
            if (node.alive and node.expect_pings
                    and now - node.last_ping > self.config.craned_timeout):
                self.on_craned_down(node.node_id, now)

    def _pending_candidates(self, now: float) -> list[Job]:
        """Candidate scan: one vectorized pass over the PendingTable
        (incremental mode) or the legacy per-job Python walk.  Both
        produce the identical candidate list and pending_reason writes
        (oracle: tests/test_delta_cycle.py)."""
        if not self.config.incremental:
            self._cand_rows = None
            return self._pending_candidates_rebuild(now)
        pt = self._ptable
        lic_ok = pt.license_mask(self.licenses.sufficient)
        cand_rows, changed, gates = pt.candidates(now, lic_ok)
        pending = self.pending
        jid = pt.job_id
        for row, gate in zip(changed.tolist(), gates.tolist()):
            job = pending.get(int(jid[row]))
            if job is None or gate == GATE_CANDIDATE:
                # candidates never get a reason write here — the old
                # loop left stale reasons on runnable jobs too, and the
                # solve/batch-cut paths overwrite them downstream
                continue
            job.pending_reason = _GATE_REASON[gate]
        self._cand_rows = cand_rows
        return [pending[int(j)] for j in jid[cand_rows]]

    def _pending_candidates_rebuild(self, now: float) -> list[Job]:
        """Skip held / future-begin-time jobs (cpp:1374-1413); dependency
        gating joins here once dependencies land."""
        out = []
        for job in self.pending.values():  # id order == insertion order
            if job.spec.array is not None:
                continue  # templates never run; children materialize
            if job.held:
                job.pending_reason = PendingReason.HELD
                continue
            if job.spec.begin_time is not None and (
                    job.spec.begin_time > now):
                job.pending_reason = PendingReason.BEGIN_TIME
                continue
            dep_reason = self._deps_runnable(job, now)
            if dep_reason is not None:
                job.pending_reason = dep_reason
                continue
            if job.spec.licenses and not self.licenses.sufficient(
                    job.spec.licenses):
                # reference pre-checks licenses before NodeSelect
                # (CheckLicenseCountSufficient, cpp:6739) so a blocked
                # job never idles nodes the solver reserved for it
                job.pending_reason = PendingReason.LICENSE
                continue
            out.append(job)
        return out

    def _account_id(self, account: str) -> int:
        if account not in self._account_index:
            self._account_index[account] = len(self._account_index)
        return self._account_index[account]

    def _priority_sort(self, candidates: list[Job], now: float
                       ) -> list[Job]:
        if self.config.priority_type == "basic" or not candidates:
            self._ordered_rows = self._cand_rows
            return candidates  # FIFO: id order (JobScheduler.h:183-201)

        # vectorized path: gather priority attrs straight from the
        # PendingTable columns (O(1) numpy gathers) instead of touching
        # every Job object; priority output is invariant to the account
        # index permutation so upsert-time registration is parity-safe
        prows = self._cand_rows
        vec = prows is not None and len(prows) == len(candidates)
        if not vec:
            for job in candidates:
                self._account_id(job.spec.account)

        def job_row(job: Job):
            req = self._job_row(job)[0]   # spec-cached encode
            total_cpu = float(req[DIM_CPU]) / 256.0 * job.spec.node_num
            total_mem = float(req[DIM_MEM]) * job.spec.node_num
            return (job.qos_priority,
                    self.meta.partitions[job.spec.partition].priority,
                    job.spec.node_num, total_cpu, total_mem,
                    self._account_id(job.spec.account))

        def col(rows, k, dt, size):
            arr = np.zeros(size, dt)
            arr[: len(rows)] = [r[k] for r in rows]
            return jnp.asarray(arr)

        # running-set attrs: none of them change while a job RUNS (qos,
        # partition, shape and account are modify-refused for running
        # jobs; only run_time ages), so the padded device arrays are
        # cached until the running-set epoch moves — membership churn
        # rebuilds them, and job_row re-registers every running account
        # then, which is why this block precedes num_accounts
        ra = self._run_attrs
        if ra is None or ra[0] != self._run_epoch:
            r_jobs = list(self.running.values())
            nR = len(r_jobs)
            RP = self._bucket(nR) if r_jobs else 16
            r_rows = [job_row(j) for j in r_jobs]
            start = np.full(RP, np.inf)
            start[:nR] = [j.start_time if j.start_time is not None
                          else np.inf for j in r_jobs]
            r_valid = np.zeros(RP, bool)
            r_valid[:nR] = True
            ra = (self._run_epoch, nR, RP, start,
                  tuple(col(r_rows, k, dt, RP) for k, dt in (
                      (0, np.int32), (1, np.int32), (2, np.int32),
                      (3, np.float32), (4, np.float32), (5, np.int32))),
                  jnp.asarray(r_valid))
            self._run_attrs = ra
        # bucketed: num_accounts is a jit static arg, and the dense index
        # grows monotonically — pad so new accounts rarely recompile
        num_accounts = self._bucket(len(self._account_index))

        # pad both batches to bucketed shapes (same rationale as
        # _build_batch: keep the jit cache small)
        JP = self._bucket(len(candidates))

        p_valid = np.zeros(JP, bool)
        p_valid[: len(candidates)] = True
        if vec:
            pt = self._ptable
            kN = len(candidates)

            def pcol(src, dt):
                arr = np.zeros(JP, dt)
                arr[:kN] = src[prows]
                return jnp.asarray(arr)

            age = np.zeros(JP, np.int32)
            age[:kN] = np.maximum(now - pt.submit[prows], 0.0)
            pending = PendingPriorityAttrs(
                age=jnp.asarray(age),
                qos_prio=pcol(pt.qos, np.int32),
                part_prio=pcol(pt.part, np.int32),
                node_num=pcol(pt.nnum, np.int32),
                cpus=pcol(pt.cpus, np.float32),
                mem=pcol(pt.mem, np.float32),
                account=pcol(pt.acct, np.int32),
                valid=jnp.asarray(p_valid))
        else:
            p_rows = [job_row(j) for j in candidates]
            age = np.zeros(JP, np.int32)
            age[: len(candidates)] = [max(now - j.submit_time, 0.0)
                                      for j in candidates]
            pending = PendingPriorityAttrs(
                age=jnp.asarray(age),
                qos_prio=col(p_rows, 0, np.int32, JP),
                part_prio=col(p_rows, 1, np.int32, JP),
                node_num=col(p_rows, 2, np.int32, JP),
                cpus=col(p_rows, 3, np.float32, JP),
                mem=col(p_rows, 4, np.float32, JP),
                account=col(p_rows, 5, np.int32, JP),
                valid=jnp.asarray(p_valid))

        _, nR, RP, r_start, r_cols, r_valid = ra
        run_time = np.zeros(RP, np.int32)
        if nR:
            # start == +inf encodes "not started yet" → clamps to 0,
            # matching the old per-job `now - (start or now)`
            run_time[:nR] = np.maximum(now - r_start[:nR], 0.0)
        running = RunningPriorityAttrs(
            qos_prio=r_cols[0], part_prio=r_cols[1], node_num=r_cols[2],
            cpus=r_cols[3], mem=r_cols[4], account=r_cols[5],
            run_time=jnp.asarray(run_time),
            valid=r_valid)

        extra_service = None
        if self.global_usage is not None:
            remote = self.global_usage.remote_account_jobs()
            if remote:
                # cluster-wide fair-share: remote running-job counts per
                # account feed the service sum (fed/usage.py); accounts
                # the gossip names but this shard has never seen get no
                # dense index yet — they have no local jobs to sort, so
                # their remote burn cannot change this shard's order
                es = np.zeros(num_accounts, np.float32)
                for acct, jobs in remote.items():
                    idx = self._account_index.get(acct)
                    if idx is not None and idx < num_accounts:
                        es[idx] = float(jobs)
                if es.any():
                    extra_service = jnp.asarray(es)

        pri = np.asarray(multifactor_priority(
            pending, running, self.config.priority_weights, num_accounts,
            extra_service=extra_service))
        order = np.asarray(priority_order(jnp.asarray(pri)))
        order = order[order < len(candidates)]  # drop -inf padding rows
        for job, p in zip(candidates, pri):
            job.priority = float(p)
        self._ordered_rows = prows[order] if vec else None
        return [candidates[i] for i in order]

    @staticmethod
    def _bucket(n: int, floor: int = 16) -> int:
        """Pad counts to the next power of two so the jitted solve sees a
        small set of static shapes (a fresh XLA compile per distinct J
        would dominate every cycle)."""
        b = floor
        while b < n:
            b *= 2
        return b

    def warm_jit_buckets(self, max_pending: int,
                         max_running: int = 0) -> int:
        """Pre-trace the jitted priority model for every padded-shape
        bucket steady-state traffic is expected to hit.

        Boot-time only, no lock needed.  Without this, the per-bucket
        XLA compile (~0.5s on a CPU backend) fires inside the first
        cycle whose queue crosses the bucket — in the prelude, under
        the server lock, where it stalls every reader for the length of
        the compile and the query-plane p99 becomes the compiler's
        latency rather than the server's.

        Warms (pending, running) bucket pairs: every pending bucket up
        to ``max_pending`` crossed with running buckets {16,
        bucket(max_running)} — after the first full cycle the running
        bucket jumps straight to the cluster's slot count, so the
        intermediate running buckets are rarely seen in steady state.
        Returns the number of shape variants traced."""
        if self.config.priority_type == "basic":
            return 0  # FIFO path has no jitted priority solve
        num_accounts = self._bucket(len(self._account_index))
        rps = {16}
        if max_running > 0:
            rps.add(self._bucket(max_running))
        jps = [16]
        while jps[-1] < max_pending:
            jps.append(jps[-1] * 2)
        traced = 0
        for rp in sorted(rps):
            running = RunningPriorityAttrs(
                qos_prio=jnp.zeros(rp, jnp.int32),
                part_prio=jnp.zeros(rp, jnp.int32),
                node_num=jnp.zeros(rp, jnp.int32),
                cpus=jnp.zeros(rp, jnp.float32),
                mem=jnp.zeros(rp, jnp.float32),
                account=jnp.zeros(rp, jnp.int32),
                run_time=jnp.zeros(rp, jnp.int32),
                valid=jnp.zeros(rp, bool))
            for jp in jps:
                pending = PendingPriorityAttrs(
                    age=jnp.zeros(jp, jnp.int32),
                    qos_prio=jnp.zeros(jp, jnp.int32),
                    part_prio=jnp.zeros(jp, jnp.int32),
                    node_num=jnp.zeros(jp, jnp.int32),
                    cpus=jnp.zeros(jp, jnp.float32),
                    mem=jnp.zeros(jp, jnp.float32),
                    account=jnp.zeros(jp, jnp.int32),
                    valid=jnp.zeros(jp, bool))
                pri = multifactor_priority(
                    pending, running, self.config.priority_weights,
                    num_accounts)
                priority_order(pri).block_until_ready()
                traced += 1
        return traced

    def _mask_for(self, job: Job, now: float = 0.0) -> np.ndarray:
        if self._mask_cache_epoch != self.meta.resv_epoch:
            # reservation churn invalidates reservation-derived masks;
            # drop everything so stale epochs can't accumulate
            self._mask_cache.clear()
            self._mask_cache_epoch = self.meta.resv_epoch
        key = (job.spec.partition, tuple(job.spec.include_nodes),
               tuple(job.spec.exclude_nodes), len(self.meta.nodes),
               job.spec.reservation)
        mask = self._mask_cache.get(key)
        if mask is None:
            mask = self.meta.partition_mask(
                job.spec.partition, job.spec.include_nodes,
                job.spec.exclude_nodes)
            if job.spec.reservation:
                # reservation jobs run ONLY inside their carve-out
                # (reference: reservations are their own LocalScheduler
                # domain, JobScheduler.cpp:6624-6732)
                resv = self.meta.reservations.get(job.spec.reservation)
                rmask = np.zeros(len(self.meta.nodes), bool)
                if resv is not None:
                    for n in resv.node_ids:
                        rmask[n] = True
                mask = mask & rmask
            self._mask_cache[key] = mask
        if job.spec.reservation:
            resv = self.meta.reservations.get(job.spec.reservation)
            if resv is None or not resv.active(now):
                return np.zeros(len(self.meta.nodes), bool)
            return mask
        # non-reservation jobs must stay clear of any reservation whose
        # window overlaps this job's would-be runtime [now, now+limit]
        # (reference "Resource Reserved" check, cpp:6797-6810)
        if self.meta.reservations:
            mask = mask.copy()
            end = now + job.spec.time_limit
            for resv in self.meta.reservations.values():
                if now < resv.end_time and resv.start_time < end:
                    for n in resv.node_ids:
                        mask[n] = False
        return mask

    def _job_row(self, job: Job) -> tuple:
        """``(encoded req, node_num, time_limit)`` cached on the Job:
        modify_job REPLACES job.spec, so an ``is`` check on the cached
        spec invalidates exactly when the row could change.  Saves the
        per-cycle re-encode for every job that sits in the queue across
        many cycles (the common case at depth)."""
        cached = job.row_cache
        if cached is not None and cached[0] is job.spec:
            return cached[1]
        row = (job.spec.res.encode(self.meta.layout),
               int(job.spec.node_num), int(job.spec.time_limit))
        job.row_cache = (job.spec, row)
        return row

    def _class_key(self, job: Job, now: float) -> tuple:
        """Eligibility-class key: equal keys provably produce identical
        ``_mask_for`` rows within one resv_epoch, so the row is cacheable
        for the whole epoch.  The post-cache dynamic parts of _mask_for
        depend only on (a) the job's reservation being active at ``now``
        and (b) the set of reservations overlapping [now, now+limit] —
        both are folded into the key."""
        spec = job.spec
        base = (spec.partition, tuple(spec.include_nodes),
                tuple(spec.exclude_nodes), spec.reservation)
        if spec.reservation:
            resv = self.meta.reservations.get(spec.reservation)
            return base + (resv is not None and resv.active(now),)
        if not self.meta.reservations:
            return base
        end = now + spec.time_limit
        return base + (frozenset(
            name for name, r in self.meta.reservations.items()
            if now < r.end_time and r.start_time < end),)

    def _refresh_mask_table(self) -> None:
        """Same invalidation rule as ``_mask_cache`` (resv_epoch), plus a
        node-count guard (rows are [N]) and a size backstop: within one
        epoch the moving ``now`` can mint fresh overlap sets every cycle,
        and the table must not grow without bound.  Called ONCE per cycle
        (before the batch loop) — resetting mid-batch would orphan class
        ids already assigned to earlier jobs in the same batch."""
        table = self._mask_table
        if (table.epoch != self.meta.resv_epoch
                or table.num_nodes != len(self.meta.nodes)
                or len(table.rows) > 512):
            table.reset(self.meta.resv_epoch, len(self.meta.nodes))

    def _class_for(self, job: Job, now: float) -> int:
        return self._mask_table.class_for(
            self._class_key(job, now), lambda: self._mask_for(job, now))

    def _build_batch(self, ordered: list[Job], num_nodes: int,
                     now: float = 0.0) -> tuple[FactoredJobBatch, int]:
        lay = self.meta.layout
        J = self._bucket(len(ordered))
        req = np.zeros((J, lay.num_dims), np.int32)
        node_num = np.zeros(J, np.int32)
        time_limit = np.zeros(J, np.int32)
        # padding rows keep class 0 — the table's permanent all-False
        # row — so a dense gather reproduces the old zero-padded mask
        job_class = np.zeros(J, np.int32)
        valid = np.zeros(J, bool)
        self._refresh_mask_table()
        orows = self._ordered_rows
        if orows is not None and len(orows) == len(ordered):
            pt = self._ptable
            kN = len(ordered)
            req[:kN] = pt.req[orows]
            node_num[:kN] = pt.nnum[orows]
            time_limit[:kN] = pt.tlimit[orows]
            valid[:kN] = True
            if self.meta.reservations:
                # reservation-scoped class keys depend on now — can't
                # cache per mask-table generation
                for i, job in enumerate(ordered):
                    job_class[i] = self._class_for(job, now)
            else:
                gen = self._mask_table.generation
                stale = np.nonzero(pt.cls_gen[orows] != gen)[0]
                for i in stale.tolist():
                    r = int(orows[i])
                    pt.cls[r] = self._class_for(ordered[i], now)
                    pt.cls_gen[r] = gen
                job_class[:kN] = pt.cls[orows]
        else:
            for i, job in enumerate(ordered):
                req[i], node_num[i], time_limit[i] = self._job_row(job)
                job_class[i] = self._class_for(job, now)
                valid[i] = True
        max_nodes = max(1, min(int(node_num.max(initial=1)),
                               self.config.max_nodes_per_job))
        # bucket the static gang bound too (it is a jit static arg)
        max_nodes = self._bucket(max_nodes, floor=1)
        rows_np, table = self._mask_table.tables()
        batch = FactoredJobBatch(
            req=jnp.asarray(req), node_num=jnp.asarray(node_num),
            time_limit=jnp.asarray(time_limit),
            valid=jnp.asarray(valid), job_class=jnp.asarray(job_class),
            class_masks=table, job_class_np=job_class,
            class_rows_np=rows_np,
            node_class_np=self._mask_table.node_class())
        return batch, max_nodes

    def _commit(self, ordered: list[Job], placements: Placements,
                now: float, start_buckets=None, tasks=None) -> list[int]:
        """Host authoritative commit + dispatch (cpp:1557-1839): re-check
        against the live ledger and the cycle's reduce events; jobs whose
        nodes died mid-cycle simply stay pending for the next cycle.

        With the time axis, ``start_buckets`` marks future-start jobs:
        they hold in-cycle reservations and surface the "Priority" reason
        (the reference's flow at cpp:6795-6835) — only bucket-0 starts
        dispatch.

        The commit scales with BATCHES, not jobs: admission checks that
        are pure array functions (placed/reason rows, the mid-cycle
        dirty-node flag) run as one vectorized pre-pass; the per-job
        loop keeps only what must stay per-job (pending membership,
        spec-epoch void, license/QoS takes with their undo ordering);
        the ledger commit goes through meta.malloc_resource_batch +
        _ledger_add_batch over the whole placed set; WAL ``start``
        records land in the cycle's open group (one fsync for all);
        dispatch is QUEUED on the ring and issued post-lock, after the
        group's durability barrier."""
        events = self.meta.stop_logging()
        dirty_nodes = {ev.node_id for ev in events}

        placed = np.asarray(placements.placed)
        nodes_mat = np.asarray(placements.nodes)
        reasons = np.asarray(placements.reason)
        valid_nodes = nodes_mat >= 0
        # vectorized pre-pass: one gather flags every placement row
        # touching a node some mid-cycle event dirtied, replacing a
        # per-job set intersection
        dirty_row = None
        if dirty_nodes:
            size = max(len(self.meta.nodes), max(dirty_nodes) + 1)
            dirty_vec = np.zeros(size, dtype=bool)
            dirty_vec[list(dirty_nodes)] = True
            dirty_row = (dirty_vec[np.clip(nodes_mat, 0, size - 1)]
                         & valid_nodes).any(axis=1)
        started: list[int] = []
        admitted: list[Job] = []
        admitted_rows: list[int] = []
        # placement rows the SOLVER took on device but the host rejects
        # below: the device state subtracted resources the ledger never
        # allocated, and no host mutation will dirty those rows — feed
        # them to the resident state so it force-patches them next cycle
        rejected_rows: list[int] = []
        future_start: list[tuple[Job, list[int]]] = []
        for i, job in enumerate(ordered):
            if (job.job_id not in self.pending or job.held
                    or job.spec is not getattr(job, "_plan_spec",
                                               job.spec)):
                # canceled / finalized / held / modified while the
                # solve ran outside the lock (cycle_phases): its
                # placement is void; resources were never committed
                # so nothing to undo.  The job stays pending for the
                # next cycle, which sees the new spec.
                if placed[i]:
                    rejected_rows.append(i)
                continue
            if not placed[i]:
                job.pending_reason = _REASON_MAP.get(
                    int(reasons[i]), PendingReason.RESOURCE)
                continue
            if start_buckets is not None and start_buckets[i] > 0:
                # reference cpp:6797-6835: a future-start job reports
                # "Resource" when its chosen nodes lack free resources
                # right now, and "Priority" only when resources are free
                # but running would delay a higher-priority reservation.
                # The avail read must see this cycle's commits (the old
                # per-job loop interleaved it with earlier jobs'
                # mallocs), so it is DEFERRED until after the batch
                # malloc below.
                future_start.append(
                    (job, nodes_mat[i][valid_nodes[i]].tolist()))
                continue
            if dirty_row is not None and dirty_row[i]:
                job.pending_reason = PendingReason.RESOURCE
                rejected_rows.append(i)
                continue
            if job.spec.licenses and not self.licenses.malloc(
                    job.spec.licenses):
                job.pending_reason = PendingReason.LICENSE
                rejected_rows.append(i)
                continue
            if not self._malloc_run_limits(job):
                self.licenses.free(job.spec.licenses or {})
                job.pending_reason = PendingReason.QOS_LIMIT
                rejected_rows.append(i)
                continue
            job.node_ids = nodes_mat[i][valid_nodes[i]].tolist()
            job.task_layout = ([int(t) for t, n in
                                zip(tasks[i], nodes_mat[i]) if n >= 0]
                               if tasks is not None else [])
            admitted.append(job)
            admitted_rows.append(i)
        # batched ledger commit: ONE meta call checks and subtracts the
        # whole placed set in admission order (each entry sees earlier
        # subtractions exactly as per-job malloc_resource calls would)
        oks = self.meta.malloc_resource_batch(
            [(job.job_id, job.node_ids, self._job_alloc(job))
             for job in admitted])
        started_jobs: list[Job] = []
        for job, row, ok in zip(admitted, admitted_rows, oks):
            if not ok:
                self.licenses.free(job.spec.licenses or {})
                self._free_run_limits(job)
                job.node_ids = []
                job.task_layout = []
                job.alloc_cache = None  # never reuse a failed
                                        # placement's per-node amounts
                job.pending_reason = PendingReason.RESOURCE
                rejected_rows.append(row)
                continue
            del self.pending[job.job_id]
            job.status = JobStatus.RUNNING
            job.start_time = now
            job.pending_reason = PendingReason.NONE
            self._init_steps(job, now)
            self.running[job.job_id] = job
            started_jobs.append(job)
            started.append(job.job_id)
        for job, node_ids in future_start:
            req = job.spec.res.encode(self.meta.layout)
            fits_now = all(
                (req <= self.meta.nodes[n].avail).all()
                for n in node_ids) if node_ids else False
            job.pending_reason = (PendingReason.PRIORITY if fits_now
                                  else PendingReason.RESOURCE)
        if rejected_rows:
            bad = nodes_mat[rejected_rows]
            self._resident.mark_diverged(np.unique(bad[bad >= 0]))
        self._ledger_add_batch(started_jobs, now)
        _MET_COMMIT_BATCH.observe(len(started_jobs))
        wal = self.wal
        trace = self.jobtrace
        for job in started_jobs:
            if wal is not None:
                wal.job_started(job)  # buffered into the cycle's group
            if trace is not None:
                trace.stamp(job.job_id, job.requeue_count, "placed",
                            now, epoch=self.fencing_epoch)
            self._trigger_dep_event(job)   # AFTER edges fire on start
            self._queue_dispatch(job, job.node_ids)
        return started

    # ------------------------------------------------------------------
    # recovery (reference JobScheduler::Init, JobScheduler.cpp:191-1091:
    # re-queue pending via RequeueRecoveredJobIntoPendingQueueLock_ :1120,
    # re-adopt running via PutRecoveredJobIntoRunningQueueLock_ :1139)
    # ------------------------------------------------------------------

    def recover(self, replayed: dict, now: float = 0.0) -> None:
        """Rebuild queues from a WAL replay (``WriteAheadLog.replay``).

        Classification is by the job's recorded *status*, not the event
        name, so any durable mutation (cancel intent, hold) recovers too:
        terminal → history; RUNNING → re-adopted WITH resources re-applied
        to the ledger (the craneds still run them — the reference
        reconciles with each craned at re-registration; the simulated
        plane re-dispatches); anything else → pending.
        """
        for job_id, (event, job) in sorted(replayed.items()):
            self._next_job_id = max(self._next_job_id, job_id + 1)
            if not job.status.is_terminal and (
                    self.account_meta is not None and job.qos_name
                    and job.array_parent_id is None):
                self.account_meta.restore_submit(
                    job.spec.user, job.spec.account, job.qos_name)
            if not job.status.is_terminal and (
                    self.global_usage is not None
                    and job.array_parent_id is None):
                # restore without re-checking: the slot was legitimately
                # admitted before the crash (fed/usage.py note_submit)
                self.global_usage.note_submit(job.spec.user,
                                              job.spec.account)
            if job.status.is_terminal:
                self.history[job_id] = job
                if self.archive is not None and job_id not in \
                        self.archive:
                    # a crash between finalize and the archive write:
                    # the WAL tombstone still has the record
                    self.archive.append(job)
            elif job.status == JobStatus.RUNNING:
                if self.meta.malloc_resource(job_id, job.node_ids,
                                             self._job_alloc(job)):
                    if not job.spec.alloc_only and 0 not in job.steps:
                        # WAL record predates the step model: re-create
                        # the implicit batch step so step-level reports
                        # from the still-running supervisors land
                        self._init_steps(job, job.start_time or now)
                    self.licenses.restore(job.spec.licenses or {})
                    if (self.account_meta is not None and job.qos_name):
                        self.account_meta.restore_run(
                            job.spec.user, job.spec.account, job.qos_name,
                            job.spec)
                        job.run_usage_taken = True
                    self.running[job_id] = job
                    self._ledger_add(job, now)
                    if job.cancel_requested:
                        # the kill may have been lost with the crash;
                        # re-send it (seeding the renewal map so the
                        # cycle keeps retrying until confirmed)
                        self._cancel_kill_sent[job_id] = now
                        self.dispatch_terminate(job_id, now)
                else:
                    # node vanished while we were down -> requeue, unless
                    # the user had already cancelled
                    if job.cancel_requested:
                        job.status = JobStatus.CANCELLED
                        job.end_time = now
                        self._finalize(job)  # frees the submit slot too
                        continue
                    job.reset_for_requeue()
                    self.pending[job_id] = job
            elif job.status == JobStatus.SUSPENDED:
                # suspended jobs hold their allocation across the crash
                if self.meta.malloc_resource(job_id, job.node_ids,
                                             self._job_alloc(job)):
                    self.licenses.restore(job.spec.licenses or {})
                    if (self.account_meta is not None and job.qos_name):
                        self.account_meta.restore_run(
                            job.spec.user, job.spec.account, job.qos_name,
                            job.spec)
                        job.run_usage_taken = True
                    self.running[job_id] = job
                    self._ledger_add(job, now)
                else:
                    job.reset_for_requeue()
                    self.pending[job_id] = job
            else:
                job.status = JobStatus.PENDING
                self.pending[job_id] = job
        if self.jobtrace is not None:
            # Seed timelines for every replayed job: synthetic spans
            # back-date the edges the WAL proves were passed, so the
            # lost/doubled ledger and cstats --job stay meaningful
            # across a failover.  Stamp-once makes this a no-op for
            # spans a promoted standby already holds.
            for job_id, (_event, job) in sorted(replayed.items()):
                self.jobtrace.seed_recovered(job, now)
        # re-derive waiting edges against the CURRENT state of each
        # dependee (events that fired between the WAL snapshot and the
        # crash would otherwise be lost forever), then rebuild the
        # dependents map for edges still waiting
        for job in self.pending.values():
            for dep in job.spec.dependencies:
                if job.dep_state.get(dep.job_id) is not None:
                    continue
                target = self.job_info(dep.job_id)
                if target is None:
                    job.dep_state[dep.job_id] = DEP_NEVER
                    continue
                sat = self._dep_satisfied_time(dep, target)
                job.dep_state[dep.job_id] = sat
                if sat is None:
                    self._dependents.setdefault(dep.job_id, set()).add(
                        job.job_id)
        # the table rows written as jobs were inserted above predate the
        # dep re-derivation; re-upsert so dep columns match dep_state
        for job in self.pending.values():
            self._table_upsert(job)

    def rebuild_device_state(self) -> None:
        """Promotion-time rebuild of device-resident scheduler state.

        A standby's shadow apply only touches the job dicts; after
        ``recover()`` re-adopts the replicated state, the accelerator-
        side caches must be rebuilt from scratch before the first cycle:
        the ``_MaskTable`` [C, N] class-row table (its rows were computed
        against the OLD leader's device buffers), every per-job row/alloc
        cache, and the dense mask cache.  The run-ledger rows were
        re-added by ``recover``; timed-state buckets and the grid
        re-derive on the first cycle from the refreshed caches."""
        self._mask_table = _MaskTable()
        self._mask_cache.clear()
        self._mask_cache_epoch = -1
        self._mesh = None
        for col in (self.pending, self.running):
            for job in col.values():
                job.row_cache = None
                job.alloc_cache = None
        # caches are cleared FIRST so _table_upsert re-encodes rows
        # against the fresh layout; the incremental caches themselves
        # restart cold (the old leader's epochs mean nothing here)
        self._ptable = PendingTable(self.meta.layout.num_dims)
        for job in self.pending.values():
            self._table_upsert(job)
        self.meta._snap = None
        self._noop_fp = None
        self._cand_rows = None
        self._ordered_rows = None
        self._run_attrs = None
        # the resident ClusterState mirrors the OLD leader's ledger —
        # drop it; the first cycle pays one full rebuild
        self._resident.invalidate()

    def job_info(self, job_id: int) -> Job | None:
        return (self.pending.get(job_id) or self.running.get(job_id)
                or self.history.get(job_id))

    def queue(self) -> list[Job]:
        return list(self.pending.values()) + list(self.running.values())
