"""Authoritative cluster state: nodes, partitions, the resource ledger.

The TPU-native counterpart of the reference's CranedMetaContainer
(reference: src/CraneCtld/Node/CranedMetaContainer.h:31 — per-node alive/
drain state, resource malloc/free, partition membership, and the
ResReduceEvent log :162-196 that captures concurrent resource reductions
during a scheduling cycle so the cycle's decisions can be re-validated
before commit).

Host-side this is plain Python + NumPy (it is the *ledger*, mutated by
events); each cycle exports a dense device snapshot via ``snapshot()``.
The two-phase pattern — device solve on the snapshot, host re-validation
against the live ledger at commit — is exactly the reference's
NodeSelect-then-ResReduceEvent-check design (JobScheduler.cpp:1437-1540).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

import numpy as np

from cranesched_tpu.ops.resources import ResourceLayout


@dataclasses.dataclass
class Partition:
    """Reference PartitionMeta (NodeDefs.h:104-122): name, priority, node
    membership, account ACLs."""

    name: str
    priority: int = 0
    node_ids: set[int] = dataclasses.field(default_factory=set)
    allowed_accounts: set[str] | None = None   # None = all
    denied_accounts: set[str] = dataclasses.field(default_factory=set)

    def account_allowed(self, account: str) -> bool:
        if account in self.denied_accounts:
            return False
        return self.allowed_accounts is None or (
            account in self.allowed_accounts)


# NodeMeta fields that feed the device snapshot (avail/total rows and
# the schedulable flag): writes to these mark the node dirty so
# MetaContainer.snapshot() can patch its cached arrays instead of
# rebuilding.  last_ping/running_jobs are deliberately absent — a ping
# must not bump the meta epoch and wake an idle scheduler.
_SNAP_FIELDS = frozenset({"avail", "total", "alive", "drained",
                          "health_drained", "power_state", "fed_leased"})


@dataclasses.dataclass
class NodeMeta:
    """Reference CranedMeta (NodeDefs.h:59-81): static total + live avail,
    alive/drain flags, running job registry."""

    node_id: int
    name: str
    total: np.ndarray                    # int32[R], capacity encoding
    avail: np.ndarray                    # int32[R]
    alive: bool = False
    drained: bool = False
    partitions: set[str] = dataclasses.field(default_factory=set)
    running_jobs: set[int] = dataclasses.field(default_factory=set)
    # real node plane: craned's push address + liveness tracking
    # (reference CranedPing every 10 s, timeout 30 s, PublicHeader.h:145)
    address: str = ""
    last_ping: float = 0.0
    expect_pings: bool = False
    # power state (reference PublicDefs.proto:87-96: ACTIVE/IDLE/
    # SLEEPING/POWEREDOFF; transitions driven by control ops + plugins)
    power_state: str = "ACTIVE"
    # operator drain and health drain are SEPARATE flags (the reference
    # tracks distinct control/drain reasons): a recovering health check
    # must not clear a maintenance drain
    health_drained: bool = False
    health_message: str = ""          # last health-check report
    # interconnect position, stamped by MetaContainer.set_topology():
    # top-down group-name path (e.g. (switch, block)) and torus coords
    block_path: tuple = ()
    coords: tuple | None = None
    # federation: lease id while the node is reserved for the placement
    # arbiter's cross-partition gang solve (fed/shard.py).  Folding the
    # flag into ``schedulable`` excludes the node from snapshots AND
    # fails local malloc attempts for the lease's whole lifetime, so a
    # shard-local cycle can never race the arbiter onto the same node.
    fed_leased: str = ""

    @property
    def schedulable(self) -> bool:
        return (self.alive and not self.drained
                and not self.health_drained
                and not self.fed_leased
                and self.power_state != "POWEREDOFF")

    def __setattr__(self, name, value):
        # every mutation path in the tree (ledger, RPC handlers, HA
        # follower, health checks) is a plain attribute assignment of a
        # NEW value — never an in-place element write — so this hook is
        # the single chokepoint that keeps the container's cached
        # snapshot coherent.  During dataclass __init__ the owner
        # backref does not exist yet, so construction is a no-op here.
        object.__setattr__(self, name, value)
        if name in _SNAP_FIELDS:
            owner = self.__dict__.get("_owner")
            if owner is not None:
                owner._touch_node(self.node_id)


@dataclasses.dataclass
class Reservation:
    """Named time-windowed node carve-out (reference ResvMeta,
    NodeDefs.h:83-98; CreateReservationRequest Crane.proto:692-707):
    during [start_time, end_time) the nodes belong exclusively to jobs
    that name the reservation (and pass its ACL)."""

    name: str
    partition: str
    node_ids: set[int]
    start_time: float
    end_time: float
    allowed_accounts: set[str] | None = None   # None = all
    denied_accounts: set[str] = dataclasses.field(default_factory=set)

    def active(self, now: float) -> bool:
        return self.start_time <= now < self.end_time

    def expired(self, now: float) -> bool:
        return now >= self.end_time

    def account_allowed(self, account: str) -> bool:
        if account in self.denied_accounts:
            return False
        return (self.allowed_accounts is None
                or account in self.allowed_accounts)


@dataclasses.dataclass(frozen=True)
class ResReduceEvent:
    """A resource reduction that happened while a cycle was in flight
    (reference CranedMetaContainer.h:162-196): node died or was drained."""

    node_id: int


class MetaContainer:
    """Node/partition registry + resource ledger.

    Single-threaded by design: the gRPC layer serializes mutations onto the
    scheduler loop, so per-entry locks (the reference's AtomicHashMap) are
    unnecessary; the event log still exists because dispatch I/O can
    interleave with cycles.
    """

    def __init__(self, layout: ResourceLayout | None = None):
        self.layout = layout or ResourceLayout()
        self.nodes: dict[int, NodeMeta] = {}
        self.partitions: dict[str, Partition] = {}
        self._name_to_id: dict[str, int] = {}
        self._part_max_cache: dict[str, np.ndarray] = {}
        self._events: list[ResReduceEvent] = []
        self._logging = False
        self.reservations: dict[str, Reservation] = {}
        # bumped on any reservation change so mask caches invalidate
        self.resv_epoch = 0
        # bumped on any snapshot-relevant node mutation (see
        # _SNAP_FIELDS) — one term of the scheduler's no-op-cycle
        # fingerprint.  ``_dirty_nodes`` are the rows snapshot() must
        # patch in its cached arrays; ``delta_snapshot=False`` restores
        # the full per-node rebuild (oracle baseline for the parity
        # tests and bench --churn).
        self.meta_epoch = 0
        self._dirty_nodes: set[int] = set()
        self._snap: tuple | None = None
        self.delta_snapshot = True
        self.last_snapshot_dirty = 0
        # interconnect topology (topo.model.Topology), attached via
        # set_topology() once the node registry is complete
        self.topology = None
        # dirty-row fan-out beyond the snapshot cache: callables
        # ``fn(node_id)`` invoked from _touch_node on every
        # snapshot-relevant mutation.  The device-resident cluster
        # state (ctld/resident.py) registers here so it can scatter-
        # patch exactly the rows that moved instead of re-uploading
        # [N, R] every cycle.
        self.dirty_listeners: list = []

    # ---- partitions & node registry ----

    def add_partition(self, name: str, priority: int = 0,
                      allowed_accounts: Iterable[str] | None = None,
                      denied_accounts: Iterable[str] = ()) -> Partition:
        part = Partition(
            name=name, priority=priority,
            allowed_accounts=(set(allowed_accounts)
                              if allowed_accounts is not None else None),
            denied_accounts=set(denied_accounts))
        self.partitions[name] = part
        return part

    def add_node(self, name: str, total: np.ndarray,
                 partitions: Iterable[str] = ("default",)) -> NodeMeta:
        node_id = len(self.nodes)
        node = NodeMeta(node_id=node_id, name=name,
                        total=np.asarray(total, np.int32),
                        avail=np.asarray(total, np.int32).copy(),
                        partitions=set(partitions))
        self.nodes[node_id] = node
        self._name_to_id[name] = node_id
        node._owner = self        # arm the dirty-row hook (NodeMeta)
        self.meta_epoch += 1
        self._snap = None         # shape changed: next snapshot rebuilds
        for p in node.partitions:
            if p not in self.partitions:
                self.add_partition(p)
            self.partitions[p].node_ids.add(node_id)
            self._part_max_cache.pop(p, None)
        return node

    def node_by_name(self, name: str) -> NodeMeta:
        return self.nodes[self._name_to_id[name]]

    def partition_max_total(self, partition: str) -> np.ndarray:
        """Elementwise max of node totals in a partition — the submit-time
        'could this request ever fit one node' bound, cached so submit
        stays O(R) instead of O(nodes)."""
        cached = self._part_max_cache.get(partition)
        if cached is not None:
            return cached
        part = self.partitions.get(partition)
        out = np.zeros(self.layout.num_dims, np.int32)
        if part is not None:
            for i in part.node_ids:
                out = np.maximum(out, self.nodes[i].total)
        self._part_max_cache[partition] = out
        return out

    def update_node_total(self, node_id: int, new_total: np.ndarray) -> bool:
        """Apply a changed node capacity (dynamic craned re-registration
        with different hardware/cgroup limits).  ``avail`` moves by the
        delta so running allocations stay charged, and the per-partition
        max-total cache is invalidated — without that, a node
        re-registering with more (or fewer) resources would leave
        ``partition_max_total`` stale and submit-time feasibility wrong.
        Returns True iff the total actually changed."""
        node = self.nodes[node_id]
        new_total = np.asarray(new_total, np.int32)
        if new_total.shape != node.total.shape:
            raise ValueError(
                f"total shape {new_total.shape} != {node.total.shape}")
        if (new_total == node.total).all():
            return False
        delta = new_total - node.total
        shrank = bool((delta < 0).any())
        node.total = new_total
        node.avail = np.minimum(node.avail + delta, new_total)
        if shrank:
            # a shrink can invalidate an in-flight cycle's placements,
            # same as a node death — force commit-time revalidation
            self._log_event(ResReduceEvent(node_id))
        for p in node.partitions:
            self._part_max_cache.pop(p, None)
        return True

    # ---- interconnect topology (topo.model.Topology) ----

    def set_topology(self, topology) -> None:
        """Attach the interconnect topology and stamp each node's
        ``block_path``/``coords``.  Topology node ids must line up with
        the registry (build it after all nodes are added)."""
        if topology.num_nodes != len(self.nodes):
            raise ValueError(
                f"topology covers {topology.num_nodes} nodes but the "
                f"registry has {len(self.nodes)}")
        self.topology = topology
        for nid, node in self.nodes.items():
            node.block_path = topology.block_path(nid)
            node.coords = (
                tuple(int(c) for c in topology.coords[nid])
                if topology.coords is not None else None)

    # ---- reservations (reference CreateReservation handling +
    #      reservation scheduling domains, JobScheduler.cpp:6624-6732) ----

    def create_reservation(self, name: str, partition: str,
                           node_names: Iterable[str], start_time: float,
                           end_time: float,
                           allowed_accounts: Iterable[str] | None = None,
                           denied_accounts: Iterable[str] = ()
                           ) -> Reservation | None:
        """Returns None on conflict (name taken, unknown nodes, or node
        already in an overlapping reservation)."""
        if name in self.reservations or end_time <= start_time:
            return None
        ids = set()
        for nm in node_names:
            if nm not in self._name_to_id:
                return None
            ids.add(self._name_to_id[nm])
        part = self.partitions.get(partition)
        if part is None or not ids <= part.node_ids:
            return None
        for other in self.reservations.values():
            if (ids & other.node_ids
                    and start_time < other.end_time
                    and other.start_time < end_time):
                return None
        resv = Reservation(
            name=name, partition=partition, node_ids=ids,
            start_time=start_time, end_time=end_time,
            allowed_accounts=(set(allowed_accounts)
                              if allowed_accounts is not None else None),
            denied_accounts=set(denied_accounts))
        self.reservations[name] = resv
        self.resv_epoch += 1
        return resv

    def delete_reservation(self, name: str) -> bool:
        if name not in self.reservations:
            return False
        del self.reservations[name]
        self.resv_epoch += 1
        return True

    def purge_expired_reservations(self, now: float) -> list[str]:
        """Cycle-start cleanup (reference reservation cleanup thread +
        timers, JobScheduler.h:1471-1482)."""
        gone = [n for n, r in self.reservations.items() if r.expired(now)]
        for n in gone:
            del self.reservations[n]
        if gone:
            self.resv_epoch += 1
        return gone

    # ---- liveness (reference CranedUp/CranedDown,
    #      CranedMetaContainer.h:105-124) ----

    def craned_up(self, node_id: int) -> None:
        self.nodes[node_id].alive = True

    def craned_down(self, node_id: int) -> list[int]:
        """Mark dead; returns running jobs that must be terminated.  Logs a
        reduce event so an in-flight cycle revalidates."""
        node = self.nodes[node_id]
        node.alive = False
        self._log_event(ResReduceEvent(node_id))
        return sorted(node.running_jobs)

    def drain(self, node_id: int, drained: bool = True) -> None:
        self.nodes[node_id].drained = drained
        if drained:
            self._log_event(ResReduceEvent(node_id))

    # ---- ledger (reference MallocResourceFromNode :126 / free) ----

    @staticmethod
    def _per_node(req, count: int) -> list[np.ndarray]:
        """Normalize a single vector or a per-node list to a list."""
        if isinstance(req, np.ndarray) and req.ndim == 1:
            return [req] * count
        return list(req)

    def malloc_resource(self, job_id: int, node_ids: Iterable[int],
                        req) -> bool:
        """Atomically subtract from every node or none (host authoritative
        commit; the device solve already believed it fits).  ``req`` is a
        single [R] vector or a per-node list (task packing / exclusive
        allocations differ per node)."""
        node_ids = list(node_ids)
        nodes = [self.nodes[i] for i in node_ids]
        reqs = self._per_node(req, len(nodes))
        if not all(n.schedulable and (r <= n.avail).all()
                   for n, r in zip(nodes, reqs)):
            return False
        for n, r in zip(nodes, reqs):
            n.avail = n.avail - r
            n.running_jobs.add(job_id)
        return True

    def malloc_resource_batch(self, entries) -> list[bool]:
        """Commit a whole placed set in one call: ``entries`` is a list
        of (job_id, node_ids, req) handled sequentially in order, so an
        entry sees every earlier entry's subtraction exactly as
        per-entry ``malloc_resource`` calls would.  Returns the
        per-entry all-or-none outcomes.  This is the commit hot path at
        10^4–10^5 placements per cycle — one call, hoisted lookups,
        instead of a method call per job."""
        nodes = self.nodes
        per_node = self._per_node
        out: list[bool] = []
        for job_id, node_ids, req in entries:
            ns = [nodes[i] for i in node_ids]
            reqs = per_node(req, len(ns))
            if not all(n.schedulable and (r <= n.avail).all()
                       for n, r in zip(ns, reqs)):
                out.append(False)
                continue
            for n, r in zip(ns, reqs):
                n.avail = n.avail - r
                n.running_jobs.add(job_id)
            out.append(True)
        return out

    def free_resource(self, job_id: int, node_ids: Iterable[int],
                      req) -> None:
        node_ids = list(node_ids)
        reqs = self._per_node(req, len(node_ids))
        for i, r in zip(node_ids, reqs):
            node = self.nodes[i]
            if job_id in node.running_jobs:
                node.running_jobs.discard(job_id)
                node.avail = np.minimum(node.avail + r, node.total)

    # ---- mid-cycle event capture (reference StartLogging /
    #      GetResReduceEvents, consumed at JobScheduler.cpp:1466-1540) ----

    def start_logging(self) -> None:
        self._events.clear()
        self._logging = True

    def stop_logging(self) -> list[ResReduceEvent]:
        self._logging = False
        events, self._events = list(self._events), []
        return events

    def _log_event(self, ev: ResReduceEvent) -> None:
        if self._logging:
            self._events.append(ev)

    # ---- device snapshot ----

    def _touch_node(self, node_id: int) -> None:
        """NodeMeta.__setattr__ hook: a snapshot-relevant field moved."""
        self.meta_epoch += 1
        if self._snap is not None:
            self._dirty_nodes.add(node_id)
        for fn in self.dirty_listeners:
            fn(node_id)

    def snapshot(self):
        """Dense SoA arrays for the device solve, aligned by node_id.

        Returns (avail[N,R], total[N,R], alive[N]) as NumPy; the scheduler
        owns moving them to device and building per-job masks.

        Delta-based: the arrays are cached and only the rows dirtied
        since the last call are re-read from the ledger (O(dirty), not
        O(nodes)).  Callers must treat the result as read-only — the
        same arrays are returned every cycle (``jnp.asarray`` copies to
        device, and host-side consumers never write).
        """
        n = len(self.nodes)
        if (not self.delta_snapshot or self._snap is None
                or len(self._snap[2]) != n):
            r = self.layout.num_dims
            avail = np.zeros((n, r), np.int32)
            total = np.zeros((n, r), np.int32)
            alive = np.zeros(n, bool)
            for i, node in self.nodes.items():
                avail[i] = node.avail
                total[i] = node.total
                alive[i] = node.schedulable
            self.last_snapshot_dirty = n
            if self.delta_snapshot:
                self._snap = (avail, total, alive)
                self._dirty_nodes.clear()
            return avail, total, alive
        avail, total, alive = self._snap
        dirty = self._dirty_nodes
        self.last_snapshot_dirty = len(dirty)
        if dirty:
            nodes = self.nodes
            for i in dirty:
                node = nodes[i]
                avail[i] = node.avail
                total[i] = node.total
                alive[i] = node.schedulable
            dirty.clear()
        return avail, total, alive

    def partition_mask(self, partition: str, include: Iterable[str] = (),
                       exclude: Iterable[str] = ()) -> np.ndarray:
        """bool[N] eligibility from partition membership and
        include/exclude nodelists (precomputed host-side, reference
        GetNodesAndTrySchedule_ include/exclude handling)."""
        n = len(self.nodes)
        mask = np.zeros(n, bool)
        part = self.partitions.get(partition)
        if part is None:
            return mask
        for i in part.node_ids:
            mask[i] = True
        include = list(include)
        if include:
            inc = np.zeros(n, bool)
            for name in include:
                if name in self._name_to_id:
                    inc[self._name_to_id[name]] = True
            mask &= inc
        for name in exclude:
            if name in self._name_to_id:
                mask[self._name_to_id[name]] = False
        return mask
