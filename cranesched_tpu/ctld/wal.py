"""Write-ahead log of job runtime state + restart recovery.

The reference keeps every pending/running job's runtime attributes in an
embedded KV store (unqlite/BerkeleyDB behind IEmbeddedDb, reference:
src/CraneCtld/Database/EmbeddedDbClient.h:85-204), written BEFORE dispatch
and updated on every status change, then purged once the job is terminal
and archived to MongoDB.  On restart, JobScheduler::Init
(JobScheduler.cpp:191-1091) replays it: pending jobs re-queue, running
jobs are re-adopted.

Here the WAL is an append-only JSON-lines file — human-debuggable, crash
append-atomic (one line per event), and replayable in one pass.  Events
are durable before they take effect: a lone append fsyncs immediately,
while a ``group()``/``begin_batch()`` batch buffers its encoded lines
and commits them with one write + one fsync (classic group commit — the
durability barrier is amortized over the batch, and no dispatch happens
for any job in the group until that barrier returns).
Terminal jobs are retained as ``finalized`` tombstones; ``compact()``
rewrites the live prefix the way the reference purges finalized rows.

HA additions: every record carries a monotonically increasing ``seq``
(the replication cursor), recent records are kept in an in-memory tail
buffer the leader serves to a polling standby, and ``rotate()`` seals
the active file into a ``.seg.<lastseq>`` segment so a snapshot can
absorb the prefix and recovery replays snapshot + tail instead of the
full history.  Records written before the seq field replay as seq 0.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import glob
import json
import os
from typing import IO

from cranesched_tpu.obs import REGISTRY as _OBS

from cranesched_tpu.ctld.defs import (
    ArraySpec,
    Dependency,
    DepType,
    Job,
    JobSpec,
    JobStatus,
    PendingReason,
    ResourceSpec,
    Step,
    StepSpec,
    StepStatus,
)


def _res_to_dict(res: dict) -> dict:
    gres = res.pop("gres")
    res["gres"] = ([[list(k), v] for k, v in gres.items()]
                   if gres else None)
    return res


def _spec_to_dict(spec: JobSpec) -> dict:
    d = dataclasses.asdict(spec)
    d["res"] = _res_to_dict(d.pop("res"))
    task_res = d.pop("task_res")
    d["task_res"] = _res_to_dict(task_res) if task_res else None
    d["include_nodes"] = list(spec.include_nodes)
    d["exclude_nodes"] = list(spec.exclude_nodes)
    d["dependencies"] = [[dep.job_id, dep.type.name, dep.delay_seconds]
                         for dep in spec.dependencies]
    d["array"] = (dataclasses.asdict(spec.array)
                  if spec.array is not None else None)
    return d


def _res_from_dict(res: dict) -> ResourceSpec:
    res = dict(res)
    gres = res.pop("gres")
    res["gres"] = ({tuple(k): v for k, v in gres} if gres else None)
    return ResourceSpec(**res)


_SPEC_FIELDS = {f.name for f in dataclasses.fields(JobSpec)}


def _spec_from_dict(d: dict) -> JobSpec:
    d = dict(d)
    d["res"] = _res_from_dict(d.pop("res"))
    task_res = d.pop("task_res", None)
    d["task_res"] = _res_from_dict(task_res) if task_res else None
    d["include_nodes"] = tuple(d.get("include_nodes") or ())
    d["exclude_nodes"] = tuple(d.get("exclude_nodes") or ())
    d["dependencies"] = tuple(
        Dependency(job_id=dep[0], type=DepType[dep[1]],
                   delay_seconds=dep[2])
        for dep in (d.get("dependencies") or ()))
    arr = d.get("array")
    d["array"] = ArraySpec(**arr) if arr else None
    # forward compatibility: records written by older versions may carry
    # fields the current JobSpec no longer has — drop, don't crash
    return JobSpec(**{k: v for k, v in d.items() if k in _SPEC_FIELDS})


def _job_to_dict(job: Job) -> dict:
    return {
        "job_id": job.job_id,
        "spec": _spec_to_dict(job.spec),
        "submit_time": job.submit_time,
        "status": job.status.name,
        "qos_name": job.qos_name,
        "qos_priority": job.qos_priority,
        "held": job.held,
        "cancel_requested": job.cancel_requested,
        "pending_reason": job.pending_reason.name,
        "start_time": job.start_time,
        "end_time": job.end_time,
        "exit_code": job.exit_code,
        "node_ids": job.node_ids,
        "task_layout": job.task_layout,
        "node_reports": {str(k): [v[0].name, v[1]]
                         for k, v in job.node_reports.items()},
        "requeue_count": job.requeue_count,
        "dep_state": {str(k): (None if v is None
                               else ("never" if v == float("inf") else v))
                      for k, v in job.dep_state.items()},
        "array_parent_id": job.array_parent_id,
        "array_task_id": job.array_task_id,
        "array_remaining": job.array_remaining,
        "array_children": job.array_children,
        "suspend_time": job.suspend_time,
        "suspended_total": job.suspended_total,
        "next_step_id": job.next_step_id,
        "cpu_seconds": job.cpu_seconds,
        "max_rss_bytes": job.max_rss_bytes,
        "steps": [_step_to_dict(s) for s in job.steps.values()],
    }


def _step_to_dict(step: Step) -> dict:
    sp = dataclasses.asdict(step.spec)
    res = sp.pop("res")
    sp["res"] = _res_to_dict(res) if res else None
    return {
        "step_id": step.step_id,
        "spec": sp,
        "submit_time": step.submit_time,
        "status": step.status.name,
        "start_time": step.start_time,
        "end_time": step.end_time,
        "exit_code": step.exit_code,
        "node_ids": step.node_ids,
        "node_reports": {str(k): [v[0].name, v[1]]
                         for k, v in step.node_reports.items()},
        "cancel_requested": step.cancel_requested,
        "cpu_seconds": step.cpu_seconds,
        "max_rss_bytes": step.max_rss_bytes,
    }


def _step_from_dict(d: dict) -> Step:
    sp = dict(d["spec"])
    res = sp.pop("res", None)
    sp["res"] = _res_from_dict(res) if res else None
    return Step(
        step_id=d["step_id"],
        spec=StepSpec(**sp),
        submit_time=d["submit_time"],
        status=StepStatus[d["status"]],
        start_time=d["start_time"],
        end_time=d["end_time"],
        exit_code=d["exit_code"],
        node_ids=list(d["node_ids"]),
        node_reports={int(k): (StepStatus[v[0]], v[1])
                      for k, v in (d.get("node_reports") or {}).items()},
        cancel_requested=d.get("cancel_requested", False),
        cpu_seconds=d.get("cpu_seconds", 0.0),
        max_rss_bytes=d.get("max_rss_bytes", 0),
    )


def _job_from_dict(d: dict) -> Job:
    return Job(
        job_id=d["job_id"],
        spec=_spec_from_dict(d["spec"]),
        submit_time=d["submit_time"],
        status=JobStatus[d["status"]],
        qos_name=d.get("qos_name", ""),
        # records written before the effective-qos field carried the
        # priority on the spec — fall back there, not to 0
        qos_priority=d.get("qos_priority",
                           d.get("spec", {}).get("qos_priority", 0)),
        held=d["held"],
        cancel_requested=d.get("cancel_requested", False),
        pending_reason=PendingReason[d["pending_reason"]],
        start_time=d["start_time"],
        end_time=d["end_time"],
        exit_code=d["exit_code"],
        node_ids=list(d["node_ids"]),
        task_layout=list(d.get("task_layout") or ()),
        node_reports={int(k): (JobStatus[v[0]], v[1])
                      for k, v in (d.get("node_reports") or {}).items()},
        requeue_count=d["requeue_count"],
        dep_state={int(k): (None if v is None
                            else (float("inf") if v == "never" else v))
                   for k, v in (d.get("dep_state") or {}).items()},
        array_parent_id=d.get("array_parent_id"),
        array_task_id=d.get("array_task_id"),
        array_remaining=list(d.get("array_remaining") or ()),
        array_children=list(d.get("array_children") or ()),
        suspend_time=d.get("suspend_time"),
        suspended_total=d.get("suspended_total", 0.0),
        next_step_id=d.get("next_step_id", 0),
        cpu_seconds=d.get("cpu_seconds", 0.0),
        max_rss_bytes=d.get("max_rss_bytes", 0),
        steps={s["step_id"]: _step_from_dict(s)
               for s in (d.get("steps") or ())},
    )


_MET_WAL_FSYNC = _OBS.counter(
    "crane_wal_fsync_total", "WAL durability barriers (os.fsync calls)")
_MET_WAL_GROUP = _OBS.histogram(
    "crane_wal_group_records", "records committed per WAL group",
    buckets=tuple(float(2 ** k) for k in range(17)))


def _fsync_dir(path: str) -> None:
    """fsync the directory holding ``path`` so a rename/unlink survives
    a host crash (an os.replace alone is only durable once the directory
    entry itself is)."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return  # e.g. O_RDONLY on a dir unsupported — best-effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _segment_files(path: str) -> list[str]:
    """Sealed segments of ``path``, oldest first (the suffix is the
    zero-padded last seq in the segment, so lexical order is seq order)."""
    return sorted(glob.glob(glob.escape(path) + ".seg.*"))


class WriteAheadLog:
    """Append-only event log; each event carries the job's full runtime
    record so replay is last-writer-wins per job_id."""

    # records the leader keeps in memory for follower catch-up; a
    # follower further behind than this re-pulls a full snapshot
    TAIL_BUFFER = 4096

    def __init__(self, path: str, fsync: bool = True):
        """``fsync`` defaults to True: the daemon path must not lose
        acknowledged submits/status transitions to a host crash (the
        reference's embedded WAL writes before dispatch).  Tests and
        benchmarks that only need crash-*process* durability may pass
        fsync=False."""
        self.path = path
        self.fsync = fsync
        # resume the seq counter past everything durable (sealed
        # segments may hold the max when the active file is fresh)
        self.seq = 0
        for f in _segment_files(path) + [path]:
            self.seq = max(self.seq, self._scan_max_seq(f))
        # last seq known to be on disk; inside an open group, seq runs
        # ahead of durable_seq until the group's single fsync returns
        self.durable_seq = self.seq
        self._tail: collections.deque = collections.deque(
            maxlen=self.TAIL_BUFFER)
        self._group_depth = 0
        self._group_buf: list[tuple[int, str]] = []
        self.fsync_total = 0    # actual os.fsync calls (fsync=True only)
        self.groups_total = 0   # non-empty group flushes
        self._fh: IO[str] = open(path, "a", encoding="utf-8")

    @staticmethod
    def _scan_max_seq(path: str) -> int:
        last = 0
        if not os.path.exists(path):
            return last
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    last = max(last, json.loads(line).get("seq", 0))
                except json.JSONDecodeError:
                    continue  # torn tail
        return last

    def close(self) -> None:
        self._flush_group()
        self._fh.close()

    def _append(self, event: str, job: Job) -> None:
        self._append_rec(event, {"job": _job_to_dict(job)})

    def fed_event(self, event: str, payload: dict) -> int:
        """Durable federation record (``fed_reserve`` / ``fed_confirm``
        / ``fed_release``): carries a lease payload instead of a job, so
        :meth:`replay` skips it and :meth:`replay_fed` reconstructs the
        lease table.  Returns the record's seq (the arbiter's durability
        watermark — it must not act on the lease until
        ``durable_seq >= seq``)."""
        self._append_rec(event, {"fed": dict(payload)})
        return self.seq

    def _append_rec(self, event: str, body: dict) -> None:
        self.seq += 1
        rec = {"seq": self.seq, "ev": event, **body}
        line = json.dumps(rec, separators=(",", ":"))
        if self._group_depth > 0:
            # group commit: buffer the encoded line; seq numbers stay
            # contiguous (we are under the server lock), the write and
            # the single fsync happen at commit_batch
            self._group_buf.append((self.seq, line))
            return
        self._fh.write(line + "\n")
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
            self.fsync_total += 1
            _MET_WAL_FSYNC.inc()
        self.durable_seq = self.seq
        self._tail.append((self.seq, line))

    # -- group commit (one durability barrier per batch) --

    def begin_batch(self) -> None:
        """Open (or nest into) a commit group: subsequent appends buffer
        in memory and become durable together at ``commit_batch``."""
        self._group_depth += 1

    def commit_batch(self) -> None:
        """Close one nesting level; at depth zero, write every buffered
        record with one ``write`` + one ``fsync``.  Tolerates being
        called with no open group (flushes any residue) so safety-net
        callers can invoke it unconditionally."""
        if self._group_depth > 0:
            self._group_depth -= 1
        if self._group_depth == 0:
            self._flush_group()

    @contextlib.contextmanager
    def group(self):
        self.begin_batch()
        try:
            yield self
        finally:
            self.commit_batch()

    def _flush_group(self) -> None:
        if not self._group_buf:
            return
        buf = self._group_buf
        self._group_buf = []
        self._fh.write("".join(line + "\n" for _seq, line in buf))
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
            self.fsync_total += 1
            _MET_WAL_FSYNC.inc()
        # the tail buffer feeds HaFetchWal: records enter it only after
        # the barrier, so a follower can never observe a non-durable seq
        self.durable_seq = buf[-1][0]
        self._tail.extend(buf)
        self.groups_total += 1
        _MET_WAL_GROUP.observe(len(buf))

    # -- replication feed (leader side) --

    def tail_since(self, after_seq: int, limit: int = 512
                   ) -> list[tuple[int, str]] | None:
        """Records with seq > ``after_seq`` from the in-memory buffer,
        or None when the cursor fell off the buffer (or points past our
        history — a diverged follower): the caller must resync from a
        snapshot."""
        if after_seq > self.durable_seq:
            return None
        floor = self._tail[0][0] if self._tail else self.durable_seq + 1
        if after_seq + 1 < floor:
            return None
        out = [(s, line) for s, line in self._tail if s > after_seq]
        return out[:limit] if limit else out

    # -- segment rotation --

    def rotate(self) -> int:
        """Seal the active file into a ``.seg.<lastseq>`` segment and
        start a fresh one.  Returns the sealed-through seq.  No-op on an
        empty active file."""
        self._flush_group()
        self._fh.flush()
        if self._fh.tell() == 0:
            return self.seq
        self._fh.close()
        sealed = f"{self.path}.seg.{self.seq:016d}"
        os.replace(self.path, sealed)
        _fsync_dir(self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        return self.seq

    def prune_segments(self, upto_seq: int) -> int:
        """Delete sealed segments fully covered by a durable snapshot
        (last seq <= ``upto_seq``).  Returns #segments removed."""
        n = 0
        for f in _segment_files(self.path):
            try:
                last = int(f.rsplit(".", 1)[1])
            except ValueError:
                continue
            if last <= upto_seq:
                try:
                    os.unlink(f)
                except FileNotFoundError:
                    continue  # a concurrent compact absorbed it
                n += 1
        if n:
            _fsync_dir(self.path)
        return n

    # -- the lifecycle hooks the scheduler calls --

    def job_submitted(self, job: Job) -> None:
        self._append("submit", job)

    def job_started(self, job: Job) -> None:
        self._append("start", job)

    def job_requeued(self, job: Job) -> None:
        self._append("requeue", job)

    def job_updated(self, job: Job) -> None:
        """Any other durable mutation: cancel intent, hold/release."""
        self._append("update", job)

    def job_finalized(self, job: Job) -> None:
        self._append("finalize", job)

    # -- recovery --

    @staticmethod
    def replay(path: str, after_seq: int = 0
               ) -> dict[int, tuple[str, Job]]:
        """Last-writer-wins replay: job_id -> (last event, job record).

        Reads sealed segments (oldest first) then the active file.
        ``after_seq`` skips records a snapshot already covers (records
        predating the seq field count as seq 0 and are only applied on a
        full replay)."""
        state: dict[int, tuple[str, Job]] = {}
        for rec in WriteAheadLog._iter_records(path):
            if after_seq and rec.get("seq", 0) <= after_seq:
                continue
            if "job" not in rec:
                continue  # federation record — replay_fed's domain
            job = _job_from_dict(rec["job"])
            state[job.job_id] = (rec["ev"], job)
        return state

    @staticmethod
    def replay_fed(path: str, after_seq: int = 0
                   ) -> dict[str, tuple[str, dict]]:
        """Last-writer-wins replay of federation lease records:
        lease_id -> (last event, payload).  A lease whose last record is
        ``fed_reserve`` was never confirmed nor released — recovery must
        drop it (release the nodes) because only a ``fed_confirm``
        record creates a job; this is what makes a shard crash mid-gang
        safe against double placement."""
        state: dict[str, tuple[str, dict]] = {}
        for rec in WriteAheadLog._iter_records(path):
            if after_seq and rec.get("seq", 0) <= after_seq:
                continue
            fed = rec.get("fed")
            if fed is None or "lease_id" not in fed:
                continue  # migration records replay separately
            state[str(fed.get("lease_id", ""))] = (rec["ev"], fed)
        return state

    @staticmethod
    def replay_migrations(path: str) -> dict[str, dict]:
        """Reconstruct partition-migration state: mid -> merged payload
        with ``ev`` = the LAST recorded phase (``fed_migrate_begin`` /
        ``fed_migrate_import`` / ``fed_migrate_commit`` /
        ``fed_migrate_abort``).  Payload fields accumulate across the
        phases so a ``commit`` entry still carries the ``begin``
        record's job_ids — recovery needs them to drop migrated-away
        jobs the ordinary job replay just resurrected."""
        state: dict[str, dict] = {}
        for rec in WriteAheadLog._iter_records(path):
            fed = rec.get("fed")
            if fed is None or "mid" not in fed:
                continue
            entry = state.setdefault(str(fed["mid"]), {})
            entry.update(fed)
            entry["ev"] = rec["ev"]
            # first-record seq: imports re-apply in arrival order on
            # recovery so adopted node ids re-number identically
            entry.setdefault("seq", rec.get("seq", 0))
        return state

    @staticmethod
    def _iter_records(path: str):
        for f in _segment_files(path) + [path]:
            if not os.path.exists(f):
                continue
            with open(f, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail write from a crash

    def compact(self, live: dict[int, tuple[str, Job]] | None = None
                ) -> None:
        """Rewrite the log keeping only non-terminal jobs (the purge the
        reference does after archiving to MongoDB).

        Crash-safe: the survivors are written to a temp file, fsync'd,
        atomically renamed over the active file, and the directory entry
        itself fsync'd — a kill at any point leaves either the old log
        (plus an ignorable ``.tmp``) or the complete new one.  Sealed
        segments are absorbed into the rewrite and deleted.

        With sealed segments present the rewrite keeps every job's LAST
        record — terminal tombstones included.  Dropping a terminal job
        while its older (non-terminal) records still sit in a segment
        would resurrect it as RUNNING if the process dies between the
        active-file rename and the segment unlink (replay reads segments
        first and nothing in the new active file would supersede them).
        The tombstones fall out on the next segment-free compact."""
        # an open group's records would be silently dropped by the
        # rewrite (they exist only in memory) — make them durable first;
        # the group stays open for appends that follow the compact
        self._flush_group()
        segments = _segment_files(self.path)
        keep: list[tuple[int, str]] = []
        if live is not None and not segments:
            for job_id, (ev, job) in sorted(live.items()):
                if job.status.is_terminal:
                    continue
                keep.append((job_id, json.dumps(
                    {"seq": self.seq, "ev": ev, "job": _job_to_dict(job)},
                    separators=(",", ":"))))
        else:
            # re-read raw records so each survivor keeps its original
            # seq (follower cursors and segment ordering stay valid)
            last: dict[int, tuple[int, dict]] = {}
            for rec in self._iter_records(self.path):
                if "job" not in rec:
                    continue  # federation records survive separately
                last[rec["job"]["job_id"]] = (rec.get("seq", 0), rec)
            for job_id, (seq, rec) in sorted(last.items()):
                if not segments and \
                        JobStatus[rec["job"]["status"]].is_terminal:
                    continue
                keep.append((job_id, json.dumps(
                    rec, separators=(",", ":"))))
        # federation lease records: keep each lease's last record unless
        # it is resolved (confirmed or released) — dropping an
        # unresolved fed_reserve would resurrect its nodes on recovery
        # while the arbiter may still confirm against the lease.
        # Migration records key by mid.  ``fed_migrate_abort`` is the
        # only droppable migration state: a commit must survive forever
        # on the source (it is what filters the migrated-away jobs out
        # of replay) and an import must survive on the destination (the
        # source's crash recovery resolves begin-without-commit by
        # asking whether the import happened).
        fed_last: dict[str, dict] = {}
        for rec in self._iter_records(self.path):
            fed = rec.get("fed")
            if fed is None:
                continue
            key = (str(fed["lease_id"]) if "lease_id" in fed
                   else "mig:" + str(fed.get("mid", "")))
            fed_last[key] = rec
        for key in sorted(fed_last):
            rec = fed_last[key]
            if not segments and rec["ev"] in ("fed_confirm",
                                              "fed_release",
                                              "fed_migrate_abort"):
                continue
            keep.append((key, json.dumps(
                rec, separators=(",", ":"))))
        self._fh.close()
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as out:
            for _job_id, line in keep:
                out.write(line + "\n")
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, self.path)
        _fsync_dir(self.path)
        for f in segments:
            try:
                os.unlink(f)
            except FileNotFoundError:
                pass  # a concurrent prune got it first
        _fsync_dir(self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
