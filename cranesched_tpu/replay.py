"""Replay harness: the five BASELINE.json benchmark configurations as
runnable end-to-end workloads (SURVEY.md §7 artifact 3 — "trace
generators for the five BASELINE.json configs, differential tests").

Each config builds a cluster + job trace, drives it through the full
control plane on the simulated node plane (virtual clock — drain time is
measured in cycles, not wall seconds), and reports scheduling metrics:

    python -m cranesched_tpu.replay fifo --scale 0.1
    python -m cranesched_tpu.replay all --scale 0.02 --json

Configs (full-scale shapes from BASELINE.md):
  fifo        FIFO, 10k jobs x 1k nodes, cpu+mem
  minload     MinCpuTimeRatioFirst order, 50k jobs x 5k nodes,
              multi-partition
  backfill    priority + backfill around long blockers
  gres        GRES gang jobs (gpu slots + multi-node gangs)
  qos         QoS/fair-share mix with run limits (scaled from the 1M
              trace shape)
  topo        gang-heavy mix on a generated torus (topology-aware
              best-fit-block placement; not part of BASELINE.json)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _build(num_nodes, cpu, mem_gb, layout_gres=(), partitions=("default",),
           accounts=None, config_kw=None):
    from cranesched_tpu.craned.sim import SimCluster
    from cranesched_tpu.ctld.meta import MetaContainer
    from cranesched_tpu.ctld.scheduler import JobScheduler, SchedulerConfig
    from cranesched_tpu.ops.resources import ResourceLayout

    meta = MetaContainer(ResourceLayout.from_gres_names(list(layout_gres)))
    for i in range(num_nodes):
        part = partitions[i % len(partitions)]
        gres = ({("gpu", "a100"): 4} if layout_gres and i % 2 == 0
                else None)
        meta.add_node(
            f"n{i:05d}",
            meta.layout.encode(cpu=cpu, mem_bytes=mem_gb << 30,
                               memsw_bytes=mem_gb << 30, gres=gres,
                               is_capacity=True),
            partitions=(part,))
        meta.craned_up(i)
    sched = JobScheduler(meta, SchedulerConfig(**(config_kw or {})),
                         accounts=accounts)
    sim = SimCluster(sched)
    sim.wire(sched)
    return meta, sched, sim


def _drain(sched, sim, max_cycles=100_000):
    t0 = time.perf_counter()
    end = sim.run_until_drained(start=0.0, max_cycles=max_cycles)
    wall = time.perf_counter() - t0
    total = len(sched.history)
    return dict(
        jobs_finished=total,
        completed=sum(1 for j in sched.history.values()
                      if j.status.value == "Completed"),
        virtual_drain_s=end,
        wall_s=round(wall, 3),
        cycles=sched.stats["cycles"],
        skipped_cycles=sched.stats.get("skipped_cycles", 0),
        jobs_per_wall_s=round(total / wall, 1) if wall else 0.0,
    )


def _run_direct(sched, sim, specs, max_cycles=100_000):
    """Library-call path: submit synchronously, drain on the virtual
    clock (the round-1..3 replay shape)."""
    for spec in specs:
        sched.submit(spec, now=0.0)
    return _drain(sched, sim, max_cycles=max_cycles)


def _run_rpc(sched, sim, specs, wal_path: str | None = None,
             max_cycles=100_000):
    """The FULL control-plane path (VERDICT r3 #10): every job enters
    through SubmitBatchJobs over gRPC, lands in the WAL, is placed by
    the cycle, and dispatches to the sim plane; cycles advance through
    the Tick RPC."""
    from cranesched_tpu.ctld.wal import WriteAheadLog
    from cranesched_tpu.rpc import CtldClient, serve
    from cranesched_tpu.rpc.convert import spec_to_pb

    specs = [spec_to_pb(s) for s in specs]
    if wal_path:
        # fresh WAL per run: the log opens append-mode, and replay
        # configs restart job ids at 1 — mixing runs in one file would
        # merge unrelated benchmarks under last-writer-wins
        open(wal_path, "w").close()
        sched.wal = WriteAheadLog(wal_path)
    server, port = serve(sched, sim=sim, tick_mode=True)
    client = CtldClient(f"127.0.0.1:{port}", timeout=300.0)
    t0 = time.perf_counter()
    submitted = 0
    for lo in range(0, len(specs), 1000):
        replies = client.submit_many(specs[lo:lo + 1000]).replies
        submitted += sum(1 for r in replies if r.job_id)
    t_submit = time.perf_counter() - t0
    cycle_ms = []
    now = 0.0
    try:
        for _ in range(max_cycles):
            c0 = time.perf_counter()
            client.tick(now)
            cycle_ms.append((time.perf_counter() - c0) * 1e3)
            if not sched.running and not sched.pending:
                break
            now += 1.0
    finally:
        client.close()
        server.stop()
        if sched.wal is not None:
            sched.wal.close()
            sched.wal = None
    wall = time.perf_counter() - t0
    total = len(sched.history)
    arr = np.asarray(cycle_ms) if cycle_ms else np.zeros(1)
    return dict(
        mode="rpc+wal" if wal_path else "rpc",
        jobs_submitted=submitted,
        submit_wall_s=round(t_submit, 3),
        submit_jobs_per_s=round(submitted / t_submit, 1)
        if t_submit else 0.0,
        jobs_finished=total,
        completed=sum(1 for j in sched.history.values()
                      if j.status.value == "Completed"),
        virtual_drain_s=now,
        wall_s=round(wall, 3),
        cycles=len(cycle_ms),
        cycle_ms_mean=round(float(arr.mean()), 2),
        cycle_ms_p99=round(float(np.percentile(arr, 99)), 2),
        cycle_ms_max=round(float(arr.max()), 2),
        jobs_per_wall_s=round(total / wall, 1) if wall else 0.0,
    )


# SLO targets for the closed-loop mode (virtual-clock seconds).  The
# windows are sized to the replay drains (hundreds to thousands of
# virtual seconds) so the final evaluate() still sees every sample;
# the queue-wait target is deliberately loose — the assertion is about
# the plumbing (gauges exported, burn math running), not queue policy.
REPLAY_SLOS = (
    ("submit-to-start", "submit", "step_start", 99.0, 86400.0,
     (3600.0, 86400.0)),
    ("commit-to-node", "committed_durable", "craned_received", 99.0,
     5.0, (3600.0, 86400.0)),
)


def _run_closed_loop(sched, sim, specs, wal_path: str | None = None,
                     max_cycles=100_000):
    """SLO-asserted closed loop (REPLAY_r06): the full RPC path, after
    which the run audits itself from its own telemetry — the timeline
    ledger proves no job was lost or double-finalized, every finished
    job's span sum matches the wall clock within its recorded skew
    bound, and the burn-rate gauges are live on /metrics."""
    from cranesched_tpu.obs.metrics import REGISTRY
    from cranesched_tpu.obs.slo import SloEngine

    if sched.jobtrace is None:
        raise RuntimeError("closed-loop replay needs JobTrace on")
    eng = SloEngine.from_config(REPLAY_SLOS)
    sched.slo_engine = eng
    sched.jobtrace.slo = eng
    # the audit reads every timeline back, so the rings must outlive
    # the whole trace (the default capacity is sized for a live ctld)
    sched.jobtrace.capacity = max(sched.jobtrace.capacity,
                                  4 * len(specs))
    out = _run_rpc(sched, sim, specs, wal_path=wal_path,
                   max_cycles=max_cycles)

    ids = sorted(sched.history)
    ledger = sched.jobtrace.ledger(ids)
    checked = matched = 0
    worst = 0.0
    for jid, job in sched.history.items():
        doc = sched.jobtrace.timeline(jid)
        if (doc is None or job.end_time is None
                or job.submit_time is None):
            continue
        first = doc["incarnations"][0]["spans"]
        last = doc["incarnations"][-1]["spans"]
        t_submit = next((s["t"] for s in first
                         if s["edge"] == "submit"), None)
        t_end = next((s["t"] for s in last if s["edge"] == "end"),
                     None)
        if t_submit is None or t_end is None:
            continue
        skew = max((s.get("skew", 0.0)
                    for inc in doc["incarnations"]
                    for s in inc["spans"]), default=0.0)
        err = abs((t_end - t_submit)
                  - (job.end_time - job.submit_time))
        checked += 1
        worst = max(worst, err)
        if err <= skew + 1e-6:
            matched += 1
    table = eng.evaluate(sim.now)
    text = REGISTRY.expose()
    out["slo_assert"] = {
        "ledger": ledger,
        "span_sum_checked": checked,
        "span_sum_matched": matched,
        "span_sum_worst_err_s": round(worst, 6),
        "slo": table,
        "burn_gauge_exported": "crane_slo_burn_rate" in text,
        "latency_hist_exported": "crane_job_latency_seconds" in text,
        "ok": bool(
            not ledger["lost"] and not ledger["doubled"]
            and checked == len(ids) and matched == checked
            and "crane_slo_burn_rate" in text
            and "crane_job_latency_seconds" in text),
    }
    return out


def replay_fifo(scale: float, rng, run=_run_direct):
    """BASELINE config #1: FIFO 10k jobs x 1k nodes (cpu+mem)."""
    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    n_nodes = max(int(1000 * scale), 4)
    n_jobs = max(int(10_000 * scale), 20)
    meta, sched, sim = _build(
        n_nodes, cpu=16, mem_gb=64,
        config_kw=dict(priority_type="basic", backfill=False))
    specs = [JobSpec(
        res=ResourceSpec(cpu=float(rng.integers(1, 9)),
                         mem_bytes=int(rng.integers(1, 17)) << 30,
                         memsw_bytes=int(rng.integers(1, 17)) << 30),
        time_limit=3600,
        sim_runtime=float(rng.integers(10, 300)))
        for _ in range(n_jobs)]
    return run(sched, sim, specs)


def replay_minload(scale: float, rng, run=_run_direct):
    """BASELINE config #2: MinCpuTimeRatioFirst, 50k x 5k,
    multi-partition."""
    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    n_nodes = max(int(5000 * scale), 8)
    n_jobs = max(int(50_000 * scale), 40)
    parts = ("alpha", "beta", "gamma")
    meta, sched, sim = _build(
        n_nodes, cpu=32, mem_gb=128, partitions=parts,
        config_kw=dict(priority_type="multifactor", backfill=False))
    specs = [JobSpec(
        partition=parts[int(rng.integers(0, len(parts)))],
        res=ResourceSpec(cpu=float(rng.integers(1, 17)),
                         mem_bytes=int(rng.integers(1, 33)) << 30,
                         memsw_bytes=int(rng.integers(1, 33)) << 30),
        qos_priority=int(rng.integers(0, 4)) * 100,
        time_limit=7200,
        sim_runtime=float(rng.integers(30, 600)))
        for _ in range(n_jobs)]
    return run(sched, sim, specs)


def replay_backfill(scale: float, rng, run=_run_direct):
    """BASELINE config #3: priority + backfill — short jobs around
    long high-priority blockers."""
    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    n_nodes = max(int(500 * scale), 4)
    n_jobs = max(int(5000 * scale), 30)
    meta, sched, sim = _build(
        n_nodes, cpu=16, mem_gb=64,
        config_kw=dict(priority_type="multifactor", backfill=True,
                       time_resolution=60.0, time_buckets=32))
    specs = []
    for i in range(n_jobs):
        big = i % 10 == 0
        specs.append(JobSpec(
            res=ResourceSpec(cpu=16.0 if big else
                             float(rng.integers(1, 5)),
                             mem_bytes=(32 if big else 2) << 30,
                             memsw_bytes=(32 if big else 2) << 30),
            qos_priority=1000 if big else 0,
            time_limit=1800 if big else 300,
            sim_runtime=float(rng.integers(600, 1800)) if big
            else float(rng.integers(10, 120))))
    return run(sched, sim, specs)


def replay_gres(scale: float, rng, run=_run_direct):
    """BASELINE config #4: GRES gang jobs (gpu slots, multi-node)."""
    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    n_nodes = max(int(1000 * scale), 8)
    n_jobs = max(int(5000 * scale), 30)
    meta, sched, sim = _build(
        n_nodes, cpu=32, mem_gb=128, layout_gres=[("gpu", "a100")],
        config_kw=dict(priority_type="multifactor", backfill=False,
                       max_nodes_per_job=4))
    specs = []
    for _ in range(n_jobs):
        wants_gpu = rng.random() < 0.4
        specs.append(JobSpec(
            res=ResourceSpec(
                cpu=float(rng.integers(1, 9)),
                mem_bytes=int(rng.integers(1, 17)) << 30,
                memsw_bytes=int(rng.integers(1, 17)) << 30,
                gres=({("gpu", "a100"): int(rng.integers(1, 5))}
                      if wants_gpu else None)),
            node_num=int(rng.integers(1, 4)) if rng.random() < 0.2
            else 1,
            time_limit=3600,
            sim_runtime=float(rng.integers(30, 300))))
    return run(sched, sim, specs)


def replay_qos(scale: float, rng, run=_run_direct):
    """BASELINE config #5 (scaled from the 1M x 100k trace shape):
    QoS/fair-share mix with run limits across accounts."""
    from cranesched_tpu.ctld.accounting import (
        Account, AccountManager, AdminLevel, Qos, User)
    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    mgr = AccountManager()
    mgr.users["root"] = User(name="root", admin_level=AdminLevel.ROOT)
    mgr.add_qos("root", Qos(name="high", priority=1000,
                            max_jobs_per_user=64))
    mgr.add_qos("root", Qos(name="low", priority=0,
                            max_jobs_per_user=32))
    for acc in ("physics", "biology", "ml"):
        mgr.add_account("root", Account(
            name=acc, allowed_qos={"high", "low"}, default_qos="low"))
        for u in range(3):
            mgr.add_user("root", User(name=f"{acc}-u{u}",
                                      uid=1000 + u), acc)
    n_nodes = max(int(1000 * scale), 8)
    n_jobs = max(int(20_000 * scale), 60)
    meta, sched, sim = _build(
        n_nodes, cpu=16, mem_gb=64, accounts=mgr,
        config_kw=dict(priority_type="multifactor", backfill=False))
    accounts = ("physics", "biology", "ml")
    specs = []
    for _ in range(n_jobs):
        acc = accounts[int(rng.integers(0, 3))]
        specs.append(JobSpec(
            user=f"{acc}-u{int(rng.integers(0, 3))}", account=acc,
            qos="high" if rng.random() < 0.2 else "low",
            res=ResourceSpec(cpu=float(rng.integers(1, 5)),
                             mem_bytes=int(rng.integers(1, 9)) << 30,
                             memsw_bytes=int(rng.integers(1, 9)) << 30),
            time_limit=1800,
            sim_runtime=float(rng.integers(10, 120))))
    return run(sched, sim, specs, max_cycles=200_000)


def replay_topo(scale: float, rng, run=_run_direct):
    """Locality config (topo/): gang-heavy mix on a generated torus
    carved into aligned sub-tori (TPU v4-style slices), exercising the
    best-fit-block solve + cross-block fallback end to end."""
    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    from cranesched_tpu.topo.model import Topology
    # torus shapes must stay slice-aligned, so scale picks a shape
    # instead of multiplying node counts
    if scale >= 0.5:
        shape, slice_shape = (8, 8, 8), (4, 4, 4)    # 512 nodes, 8 blocks
    else:
        shape, slice_shape = (4, 4, 4), (2, 2, 2)    # 64 nodes, 8 blocks
    n_nodes = int(np.prod(shape))
    n_jobs = max(int(2000 * scale), 30)
    meta, sched, sim = _build(
        n_nodes, cpu=32, mem_gb=128,
        config_kw=dict(priority_type="multifactor", backfill=False,
                       max_nodes_per_job=8))
    meta.set_topology(Topology.from_torus(shape, slice_shape))
    specs = []
    for _ in range(n_jobs):
        gang = rng.random() < 0.6
        specs.append(JobSpec(
            res=ResourceSpec(cpu=float(rng.integers(1, 9)),
                             mem_bytes=int(rng.integers(1, 17)) << 30,
                             memsw_bytes=int(rng.integers(1, 17)) << 30),
            node_num=int(rng.integers(2, 9)) if gang else 1,
            time_limit=3600,
            sim_runtime=float(rng.integers(30, 300))))
    out = run(sched, sim, specs)
    out["topo_in_block_gangs"] = int(
        sched.stats.get("topo_in_block_total", 0))
    out["topo_cross_block_gangs"] = int(
        sched.stats.get("topo_cross_block_total", 0))
    return out


def replay_federation(scale: float, rng, wal_dir: str | None = None,
                      kill_shard: str = "east"):
    """Closed-loop federation drill (REPLAY_r07): two WAL-backed shards
    + the placement arbiter on one virtual clock, a submit storm that is
    40% cross-partition gangs, and one shard SIGKILL'd mid-storm at the
    worst possible moment — immediately after a durable gang reserve,
    before any confirm.  The run audits itself: the cross-shard jobtrace
    ledger must show zero lost and zero double-dispatched jobs, and
    every committed gang member must appear exactly once."""
    import collections
    import shutil
    import tempfile

    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    from cranesched_tpu.fed.arbiter import GangRequest
    from cranesched_tpu.fed.sim import FederatedCluster

    n_per_part = max(int(100 * scale), 4)
    n_jobs = max(int(2000 * scale), 60)
    tmp = wal_dir or tempfile.mkdtemp(prefix="crane-fed-replay-")
    fc = FederatedCluster(
        {"east": {"batch": n_per_part,
                  "debug": max(n_per_part // 2, 2)},
         "west": {"gpu": n_per_part}},
        cpu=16.0, mem_gb=64, wal_dir=tmp)
    parts = ("batch", "debug", "gpu")
    events = []
    for i in range(n_jobs):
        res = ResourceSpec(cpu=float(rng.integers(1, 5)),
                           mem_bytes=int(rng.integers(1, 9)) << 30,
                           memsw_bytes=int(rng.integers(1, 9)) << 30)
        runtime = float(rng.integers(5, 60))
        if rng.random() < 0.4:
            events.append(GangRequest(
                name=f"g{i:05d}",
                node_num=int(rng.integers(2, 5)),
                partitions=("batch", "gpu"),
                spec=JobSpec(user="u", res=res, sim_runtime=runtime)))
        else:
            events.append(JobSpec(
                name=f"j{i:05d}", user="u",
                partition=parts[int(rng.integers(0, 3))],
                res=res, sim_runtime=runtime))

    wave = max(n_jobs // 40, 1)
    kill_at = n_jobs // 2
    backlog = collections.deque(events)
    t0 = time.perf_counter()
    submitted = gangs = 0
    killed_t = recovered_t = None
    while backlog:
        # one wave per tick; a refused submit (shard down) stays queued
        # exactly as a retrying client would hold it
        for _ in range(min(wave, len(backlog))):
            ev = backlog[0]
            if isinstance(ev, GangRequest):
                fc.submit_gang(ev)
                gangs += 1
            else:
                try:
                    fc.submit(ev)
                except RuntimeError:
                    break  # owning shard is down — retry next tick
            backlog.popleft()
            submitted += 1
        if killed_t is None and submitted >= kill_at:
            # arm the worst-case SIGKILL: it lands right after the next
            # durable fed_reserve on this shard, before any confirm
            fc.shards[kill_shard].crash_after_lease = True
            killed_t = fc.now
        if (recovered_t is None and killed_t is not None
                and not fc.shards[kill_shard].alive
                and fc.now >= killed_t + 10.0):
            fc.recover(kill_shard)
            recovered_t = fc.now
        fc.tick()
    if not fc.shards[kill_shard].alive:
        fc.recover(kill_shard)
        recovered_t = fc.now
    fc.run_until_drained()
    wall = time.perf_counter() - t0

    ledger = fc.ledger()
    # every committed gang member exists exactly once across the
    # federation, and no gang was silently dropped
    member_counts = collections.Counter(
        j.spec.name
        for s in fc.shards.values()
        for j in list(s.scheduler.history.values())
        + list(s.scheduler.running.values())
        if j.spec.name.startswith("g"))
    stats = fc.arbiter.stats
    finished = sum(len(s.scheduler.history)
                   for s in fc.shards.values())
    completed = sum(
        1 for s in fc.shards.values()
        for j in s.scheduler.history.values()
        if j.status.value == "Completed")
    ok = bool(
        ledger["lost"] == 0 and ledger["doubled"] == 0
        and stats["failed"] == 0 and not fc.arbiter.queue
        and stats["commits"] == gangs
        and all(c == 1 for c in member_counts.values()))
    if wal_dir is None:
        shutil.rmtree(tmp, ignore_errors=True)
    return dict(
        mode="federation",
        shards={name: dict(s.partitions)
                for name, s in fc.shards.items()},
        jobs_submitted=n_jobs,
        gangs=gangs,
        gang_share=round(gangs / n_jobs, 3),
        gang_commits=stats["commits"],
        gang_aborts=stats["aborts"],
        killed_shard=kill_shard,
        killed_at=killed_t,
        recovered_at=recovered_t,
        jobs_finished=finished,
        completed=completed,
        cycles=int(fc.now),
        virtual_drain_s=fc.now,
        wall_s=round(wall, 3),
        jobs_per_wall_s=round(finished / wall, 1) if wall else 0.0,
        ledger=ledger,
        ok=ok,
    )


def replay_rebalance(scale: float, rng, wal_dir: str | None = None):
    """Elastic-federation drill (REPLAY_r08): a two-shard submit storm
    with global per-user limits gossiping under bounded staleness, then
    a LIVE migration of the loaded partition mid-storm — with the
    source shard SIGKILL'd at the worst moment of the handoff (begin
    durable, payload exported, commit never acknowledged).  The source
    recovers from its WAL, the coordinator resolves the bare begin
    against the destination's adopted import, the storm finishes, and
    the run audits itself BY NAME across shards (ids renumber on
    import): every submitted job must reach exactly one terminal state
    federation-wide — zero lost, zero doubled."""
    import collections
    import shutil
    import tempfile

    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    from cranesched_tpu.fed.sim import FederatedCluster
    from cranesched_tpu.fed.usage import GlobalLimits

    n_per_part = max(int(100 * scale), 4)
    n_jobs = max(int(2000 * scale), 80)
    limit = max(n_jobs // 2, 20)
    tmp = wal_dir or tempfile.mkdtemp(prefix="crane-rebalance-replay-")
    fc = FederatedCluster(
        {"east": {"batch": n_per_part,
                  "debug": max(n_per_part // 2, 2)},
         "west": {"gpu": n_per_part}},
        cpu=16.0, mem_gb=64, wal_dir=tmp,
        global_limits=GlobalLimits(max_submit_jobs_per_user=limit),
        publish_slack=4)
    parts = ("batch", "batch", "debug", "gpu")  # batch-heavy: the
    events = []                                 # shard we will unload
    for i in range(n_jobs):
        events.append(JobSpec(
            name=f"r{i:05d}", user="u",
            partition=parts[int(rng.integers(0, 4))],
            res=ResourceSpec(cpu=float(rng.integers(1, 5)),
                             mem_bytes=int(rng.integers(1, 9)) << 30,
                             memsw_bytes=int(rng.integers(1, 9)) << 30),
            sim_runtime=float(rng.integers(5, 60))))

    wave = max(n_jobs // 40, 1)
    migrate_at = n_jobs // 2
    backlog = collections.deque(events)
    t0 = time.perf_counter()
    submitted = admitted = denied = 0
    names: list[str] = []
    migration = None
    resolved = None
    while backlog:
        for _ in range(min(wave, len(backlog))):
            ev = backlog[0]
            try:
                _, jid = fc.submit(ev)
            except RuntimeError:
                break  # owning shard down mid-handoff — client retries
            backlog.popleft()
            submitted += 1
            if jid:
                admitted += 1
                names.append(ev.name)
            else:
                denied += 1  # sealed partition or global limit gate
        if migration is None and submitted >= migrate_at:
            # the storm's hot shard hands off its loaded partition —
            # and dies right after the export leaves (the WAL has the
            # begin; the dest adopts; the commit can never be served)
            migration = fc.migrate(
                "batch", "west",
                on_exported=lambda payload: fc.kill("east"))
            assert migration["committed"] is False
            fc.recover("east")
            resolved = fc.resolve_migrations("east")
        fc.tick()
        fc.pump_usage(fc.now)
    fc.run_until_drained()
    wall = time.perf_counter() - t0

    audit = fc.ledger_by_name(names)
    in_book = sum(
        c.submit_jobs
        for s in fc.shards.values()
        for c in [s.scheduler.global_usage._user.get("u")] if c)
    ok = bool(
        migration is not None
        and [r["resolution"] for r in resolved] == ["commit"]
        and audit["lost"] == [] and audit["doubled"] == []
        and audit["still_live"] == []
        and admitted <= n_jobs
        and in_book == 0  # every slot released on terminal
        and fc.shard_map.shard_for_partition("batch") == "west")
    if wal_dir is None:
        shutil.rmtree(tmp, ignore_errors=True)
    finished = sum(len(s.scheduler.history)
                   for s in fc.shards.values())
    completed = sum(
        1 for s in fc.shards.values()
        for j in s.scheduler.history.values()
        if j.status.value == "Completed")
    return dict(
        mode="rebalance",
        shards={name: dict(s.partitions)
                for name, s in fc.shards.items()},
        jobs_submitted=submitted,
        admitted=admitted,
        denied_at_gate=denied,
        global_submit_limit=limit,
        migration=migration,
        resolved=[r["resolution"] for r in (resolved or [])],
        map_epoch=fc.shard_map.epoch,
        jobs_finished=finished,
        completed=completed,
        cycles=int(fc.now),
        virtual_drain_s=fc.now,
        wall_s=round(wall, 3),
        jobs_per_wall_s=round(finished / wall, 1) if wall else 0.0,
        audit={k: (len(v) if isinstance(v, list) else v)
               for k, v in audit.items()},
        ok=ok,
    )


CONFIGS = {
    "fifo": replay_fifo,
    "minload": replay_minload,
    "backfill": replay_backfill,
    "gres": replay_gres,
    "qos": replay_qos,
    "topo": replay_topo,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="crane-replay")
    ap.add_argument("config", nargs="?", choices=[*CONFIGS, "all"])
    ap.add_argument("--scale", type=float, default=0.1,
                    help="fraction of the full BASELINE shape")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--rpc", action="store_true",
                    help="drive the FULL path: SubmitBatchJobs over "
                         "gRPC -> WAL -> cycle -> dispatch")
    ap.add_argument("--wal", default="",
                    help="WAL path for --rpc (empty = no WAL)")
    ap.add_argument("--slo", action="store_true",
                    help="closed-loop mode: drive --rpc, then assert "
                         "the SLO/ledger contract from the run's own "
                         "exported telemetry")
    ap.add_argument("--federation", action="store_true",
                    help="closed-loop federation drill: 2 WAL-backed "
                         "shards + the arbiter, 40%% cross-partition "
                         "gangs, one shard SIGKILL'd mid-storm; "
                         "asserts zero lost/doubled via the jobtrace "
                         "ledger")
    ap.add_argument("--rebalance", action="store_true",
                    help="elastic-federation drill: live-migrate the "
                         "loaded partition mid-storm with the source "
                         "SIGKILL'd during the handoff, recover, "
                         "resolve; asserts exactly-once by job name "
                         "and the global submit limit")
    args = ap.parse_args(argv)
    if args.config is None and not (args.federation or args.rebalance):
        ap.error("a config is required unless --federation or "
                 "--rebalance is given")

    run = _run_direct
    if args.slo:
        import functools
        run = functools.partial(_run_closed_loop,
                                wal_path=args.wal or None)
    elif args.rpc:
        import functools
        run = functools.partial(_run_rpc, wal_path=args.wal or None)

    names = ([] if args.config is None else
             list(CONFIGS) if args.config == "all" else [args.config])
    results = {}
    for name in names:
        rng = np.random.default_rng(args.seed)
        results[name] = CONFIGS[name](args.scale, rng, run=run)
    if args.federation:
        rng = np.random.default_rng(args.seed)
        results["federation"] = replay_federation(args.scale, rng)
    if args.rebalance:
        rng = np.random.default_rng(args.seed)
        results["rebalance"] = replay_rebalance(args.scale, rng)
    if args.json:
        print(json.dumps(results))
    else:
        for name, r in results.items():
            print(f"{name:9s} finished={r['jobs_finished']} "
                  f"completed={r['completed']} "
                  f"cycles={r['cycles']} "
                  f"virtual_drain={r['virtual_drain_s']:.0f}s "
                  f"wall={r['wall_s']}s "
                  f"({r['jobs_per_wall_s']} jobs/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
