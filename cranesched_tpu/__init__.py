"""cranesched-tpu: a TPU-native cluster job scheduling framework.

A from-scratch rebuild of the capability surface of PKUHPC/CraneSched
(reference: /root/reference) designed TPU-first:

- ``ops/``      JAX primitives for the scheduler's resource algebra
                (fixed-point cpu, feasibility masks, fit counts).
- ``models/``   jit-compiled solvers mapping (cluster state, job batch) ->
                placements: the greedy scan, the time-axis backfill grid,
                task packing/exclusive, the fast exact speculative paths,
                and the multifactor priority sort (reference:
                src/CraneCtld/JobScheduler.cpp:6507,7606).
- ``parallel/`` Mesh/sharding layer: shard_map'd solvers splitting the node
                axis across devices with ICI collectives for the merges.
- ``ctld/``     Host control plane: job lifecycle, queues, accounting/QoS,
                licenses, reservations, dependencies, arrays, preemption,
                WAL persistence + recovery (reference: src/CraneCtld/).
- ``craned/``   Node plane: the real daemon (registration FSM, supervisor
                processes, cgroups, health checks) and the simulated
                cluster used by tests and replays.
- ``rpc/``      gRPC control fabric + CLI client (protos/crane.proto).
- ``utils/``    Hostlist grammar, YAML config, native C++ bridge.

See ARCHITECTURE.md for the full component map against the reference.
"""

__version__ = "0.1.0"
