"""cranesched-tpu: a TPU-native cluster job scheduling framework.

A from-scratch rebuild of the capability surface of PKUHPC/CraneSched
(reference: /root/reference) designed TPU-first:

- ``ops/``      JAX primitives for the scheduler's resource algebra
                (fixed-point cpu, feasibility masks, fit counts).
- ``models/``   Scheduler "models": jit-compiled solve() functions mapping
                (cluster state, job batch) -> placements. The flagship model
                is the per-cycle constraint solve that replaces the C++
                NodeSelect loop (reference: src/CraneCtld/JobScheduler.cpp:6507).
- ``parallel/`` Mesh/sharding layer: shard_map'd solvers that split the node
                axis across devices with ICI collectives for the argmin merge.
- ``ctld/``     Host control plane: job lifecycle, queues, accounting,
                persistence (WAL), dispatch (reference: src/CraneCtld/).
- ``craned/``   Node plane: simulated in-process craneds for tests plus the
                interface the real C++ daemon implements.
- ``utils/``    Hostlist grammar, config parsing, logging.
"""

__version__ = "0.1.0"
