"""cranectld: the control-plane daemon entry point.

Mirrors the reference's CraneCtld bootstrap (reference:
src/CraneCtld/CraneCtld.cpp:1019-1279 — config parse, global init in
dependency order, recovery from the embedded DB, then serve):

    python -m cranesched_tpu.ctld_main -c etc/config.yaml
    python -m cranesched_tpu.ctld_main -c etc/config.yaml --sim

``--sim`` attaches the in-process simulated node plane (every configured
node is immediately alive and runs jobs on the virtual completion queue);
without it, nodes come alive as real craned daemons register.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time


def main(argv=None) -> int:
    # Bounded backend acquisition BEFORE anything imports jax (ISSUE
    # 17): with CPU pre-forced this only re-applies the config-level
    # forcing (a sitecustomize-registered accelerator plugin overrides
    # the env var at import time; only config.update after import
    # wins).  On any other platform it runs the hardened PJRT handshake
    # from parallel/acquire.py with a hard budget — a wedged plugin
    # (the r06-r09 failure mode) degrades the daemon to CPU within
    # CRANE_ACQUIRE_TIMEOUT instead of hanging the first scheduling
    # cycle while holding the RPC lock.  The structured diagnosis is
    # replayed into the scheduler's event log once it exists.
    from cranesched_tpu.parallel.acquire import ensure_backend
    acquisition = ensure_backend()
    if not acquisition.get("acquired", False):
        print(f"WARNING: backend acquisition failed — "
              f"{acquisition.get('diagnosis', '(no diagnosis)')}",
              file=sys.stderr, flush=True)
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax
        try:
            jax.config.update("jax_platforms", platforms)
        except Exception:
            pass  # backend already initialized — nothing to force

    ap = argparse.ArgumentParser(prog="cranectld")
    ap.add_argument("--config", "-c", required=True)
    ap.add_argument("--sim", action="store_true",
                    help="simulated node plane (no real craneds)")
    ap.add_argument("--listen", default="",
                    help="override the config listen address")
    ap.add_argument("--cycle-interval", type=float, default=1.0)
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="Prometheus /metrics port (overrides config "
                         "Observability.MetricsPort; 0 = ephemeral)")
    ap.add_argument("--log-file", default="",
                    help="rotating log file (32 MiB x 5 by default)")
    ap.add_argument("--log-level", default="info")
    ap.add_argument("--ha-standby", action="store_true",
                    help="start as a hot standby: replicate from "
                         "--ha-peer, serve queries only, and promote "
                         "when the leader's lease frees")
    ap.add_argument("--ha-peer", default="",
                    help="the other ctld's address (the leader to "
                         "replicate from when --ha-standby; advertised "
                         "to redirected clients otherwise)")
    ap.add_argument("--snapshot-interval", type=float, default=60.0,
                    help="seconds between WAL snapshots (leader only; "
                         "0 disables)")
    args = ap.parse_args(argv)
    if args.ha_standby and not args.ha_peer:
        ap.error("--ha-standby requires --ha-peer")

    from cranesched_tpu.utils.logging import setup_logging
    log = setup_logging("ctld", args.log_file, args.log_level)
    log.info("cranectld starting (config=%s)", args.config)

    from cranesched_tpu.craned.sim import SimCluster
    from cranesched_tpu.ctld.wal import WriteAheadLog
    from cranesched_tpu.rpc.dispatcher import GrpcDispatcher
    from cranesched_tpu.rpc.server import serve
    from cranesched_tpu.utils.config import load_config

    cfg = load_config(args.config)
    meta, scheduler = cfg.build()

    if not acquisition.get("acquired", False):
        # the boot-time fallback, now as a typed event operators can
        # query (cevents) and drills can assert on
        scheduler.events.emit(
            "backend_degraded", severity="error",
            detail=acquisition.get("diagnosis",
                                   "backend acquisition failed")[:800])

    if cfg.acct_store_path and scheduler.accounts is not None:
        print(f"accounting store: {cfg.acct_store_path} "
              f"({len(scheduler.accounts.accounts)} accounts, "
              f"{len(scheduler.accounts.users)} users, "
              f"{len(scheduler.accounts.qos)} qos)", flush=True)

    if cfg.archive_path:
        from cranesched_tpu.ctld.archive import JobArchive
        os.makedirs(os.path.dirname(cfg.archive_path) or ".",
                    exist_ok=True)
        scheduler.attach_archive(JobArchive(cfg.archive_path))
        print(f"history archive: {cfg.archive_path} "
              f"({scheduler.archive.count()} jobs)", flush=True)

    # federation plane BEFORE recovery: the replay must filter
    # committed migrations' jobs and rebuild imported node meta
    # (fed.prepare_recovery inside recover_from_snapshot), and the
    # UsageBook must exist before scheduler.recover backfills
    # note_submit/note_run for boot-restored jobs — a restarted leader
    # that published zero usage would let every peer's gate overshoot.
    shard_map = cfg.shard_map()
    shard_name = cfg.shard_name
    if shard_map is not None:
        # leases + live-migration WAL protocol ride on the scheduler
        # (fed/shard.py self-attaches as .fed), and Federation:
        # Limits: turns on the cluster-wide UsageBook
        from cranesched_tpu.fed.shard import FedShardPlane
        FedShardPlane(scheduler, shard_name)
        limits = cfg.global_limits()
        if limits is not None:
            from cranesched_tpu.fed.usage import (
                UsageBook,
                effective_publish_slack,
            )
            # PublishSlack = admissions a shard may run ahead of what
            # its slowest peer CONFIRMED pulling (the conservative
            # gate subtracts (shards-1)*slack from every global
            # limit); 8 absorbs a burst of submits inside one gossip
            # interval.  Clamped so a small global limit stays
            # satisfiable — unclamped, limit <= (shards-1)*slack
            # would deny every submit forever.
            asked = int((cfg.federation.get("Limits") or {})
                        .get("PublishSlack", 8))
            n_shards = len(shard_map.shards)
            slack, asked = effective_publish_slack(
                limits, n_shards, asked)
            if slack != asked:
                print(f"WARNING: PublishSlack={asked} leaves no "
                      f"admissible headroom under the configured "
                      f"global limits with {n_shards} shards — "
                      f"clamped to {slack}",
                      file=sys.stderr, flush=True)
            scheduler.global_usage = UsageBook(
                shard_name, limits,
                n_shards=n_shards,
                publish_slack=slack,
                seq_source=lambda: (scheduler.wal.durable_seq
                                    if scheduler.wal is not None
                                    else 0),
                peers=tuple(sorted(shard_map.shards)))
        print(f"federation shard {shard_name!r}: "
              f"{len(shard_map.shards)} shards, map epoch "
              f"{shard_map.epoch}"
              + (", global limits on" if limits is not None else ""),
              flush=True)

    # recovery before serving (reference JobScheduler::Init).  A leader
    # takes the WAL-dir lease FIRST: a second ctld pointed at the same
    # WAL (operator error, or a fenced-off old leader restarting) fails
    # fast instead of corrupting the log (VERDICT row 43).  A standby
    # skips all of this — its follower thread seeds from its own local
    # snapshot+WAL and only opens them for writing at promotion.
    lease = None
    if cfg.wal_path:
        # both roles write under the WAL dir (the standby keeps its
        # replicated WAL, snapshot, and observed epoch there)
        os.makedirs(os.path.dirname(cfg.wal_path) or ".", exist_ok=True)
    if cfg.wal_path and not args.ha_standby:
        from cranesched_tpu.ha import LeaderLease
        from cranesched_tpu.ha.snapshot import recover_from_snapshot
        from cranesched_tpu.utils.filelock import FileLockHeld
        lease = LeaderLease(cfg.wal_path)
        try:
            epoch = lease.acquire()
        except FileLockHeld:
            print(f"FATAL: another ctld holds the lease on "
                  f"{cfg.wal_path} (is a leader already running?); "
                  f"start this one with --ha-standby to follow it",
                  file=sys.stderr, flush=True)
            return 1
        scheduler.fencing_epoch = epoch
        if args.sim:
            for node in meta.nodes.values():
                node.alive = True
        count, snap_seq = recover_from_snapshot(
            scheduler, WriteAheadLog, cfg.wal_path, now=time.time())
        # stderr: the first STDOUT line stays the "listening on port"
        # banner (wrappers parse the bound port out of it)
        if count:
            print(f"recovered {count} jobs from {cfg.wal_path}"
                  + (f" (snapshot @seq={snap_seq} + tail)"
                     if snap_seq else ""),
                  file=sys.stderr, flush=True)
        scheduler.wal = WriteAheadLog(cfg.wal_path)
        fed = getattr(scheduler, "fed", None)
        if fed is not None:
            # lease tombstoning + migrated-away node re-death, and any
            # begin with no commit/abort surfaces unresolved (the RPC
            # server's resolve loop settles it against the dest)
            fed.recover(time.time())
            unresolved = fed.recover_migrations(time.time())
            if unresolved:
                mids = ", ".join(r["mid"] for r in unresolved)
                print(f"WARNING: {len(unresolved)} unresolved "
                      f"migration(s) [{mids}] — partitions stay "
                      f"sealed until the destination's has_import "
                      f"answer settles them",
                      file=sys.stderr, flush=True)
        print(f"leader lease acquired (fencing epoch {epoch})",
              file=sys.stderr, flush=True)

    sim = None
    dispatcher = None
    tls = cfg.tls_config()
    if args.sim:
        for node in meta.nodes.values():
            node.alive = True
        sim = SimCluster(scheduler)
        sim.wire(scheduler)
    else:
        dispatcher = GrpcDispatcher(
            scheduler, tls=tls.for_client() if tls else None)
        dispatcher.wire(scheduler)

    if cfg.node_event_hook_path:
        from cranesched_tpu.utils.config import (
            make_node_event_script_hook)
        scheduler.node_event_hook = make_node_event_script_hook(
            cfg.node_event_hook_path)

    auth = None
    if cfg.auth_token_file:
        from cranesched_tpu.ctld.auth import AuthManager
        os.makedirs(os.path.dirname(cfg.auth_token_file) or ".",
                    exist_ok=True)
        auth = AuthManager(cfg.auth_token_file,
                           admins=tuple(cfg.auth_admins),
                           accounts=scheduler.accounts)
        print(f"auth enabled (token table {cfg.auth_token_file}; "
              f"root + craned tokens inside)", flush=True)

    metrics_port = (args.metrics_port if args.metrics_port is not None
                    else cfg.metrics_port)
    address = args.listen or cfg.listen
    server, port = serve(scheduler, sim=sim, address=address,
                         cycle_interval=args.cycle_interval,
                         dispatcher=dispatcher, auth=auth, tls=tls,
                         metrics_port=metrics_port,
                         shard_name=shard_name, shard_map=shard_map,
                         standby=args.ha_standby,
                         peer_address=args.ha_peer)
    print(f"cranectld [{cfg.cluster_name}] listening on port {port} "
          f"({'simulated' if args.sim else 'real'} node plane, "
          f"{len(meta.nodes)} nodes configured"
          f"{', TLS' if tls else ''}"
          f"{', STANDBY of ' + args.ha_peer if args.ha_standby else ''}"
          ")", flush=True)
    if server.metrics_port is not None:
        print(f"metrics: http://0.0.0.0:{server.metrics_port}/metrics",
              flush=True)

    # HA plumbing needs the server lock, so it starts after serve()
    snapshotter = None
    follower = None
    if cfg.wal_path:
        from cranesched_tpu import ha as _ha

        def _start_snapshotter():
            nonlocal snapshotter
            if args.snapshot_interval <= 0:
                return
            snapshotter = _ha.Snapshotter(
                scheduler, scheduler.wal, server._lock, cfg.wal_path,
                interval=args.snapshot_interval)
            snapshotter.start()

        if args.ha_standby:
            follower = _ha.HaFollower(
                server, args.ha_peer, cfg.wal_path,
                token=(auth.craned_token if auth is not None else ""),
                tls=tls.for_client() if tls else None,
                on_promote=lambda epoch: _start_snapshotter())
            server.ha_follower = follower
            follower.start()
            print(f"hot standby: replicating from {args.ha_peer}",
                  flush=True)
        else:
            _ha.ROLE_GAUGE.set(1)
            _start_snapshotter()

    syncer = None
    if cfg.license_sync.get("Program"):
        from cranesched_tpu.ctld.licenses import LicenseSyncer
        syncer = LicenseSyncer(
            scheduler.licenses, str(cfg.license_sync["Program"]),
            interval=float(cfg.license_sync.get("Interval", 60)),
            lock=server._lock)
        syncer.sync_once()   # first observation before the first cycle
        syncer.start()
        print(f"license sync: {cfg.license_sync['Program']} "
              f"every {syncer.interval:g}s", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    if syncer is not None:
        syncer.stop()
    if follower is not None:
        follower.stop()
    if snapshotter is not None:
        snapshotter.stop()
    server.stop()
    if dispatcher is not None:
        dispatcher.close()
    if lease is not None:
        lease.release()
    return 0


if __name__ == "__main__":
    sys.exit(main())
