"""Interconnect-topology subsystem.

``topo.model`` describes the cluster's interconnect as a small tree of
node groups (TPU v4-style sub-tori or Slurm topology.conf-style switch
blocks); ``topo.place`` is the batched best-fit-block gang solve that
keeps multi-node jobs inside one ICI domain whenever possible.
"""

from cranesched_tpu.topo.model import Topology, topology_doc
from cranesched_tpu.topo.place import TopoInfo, solve_greedy_topo

__all__ = ["Topology", "topology_doc", "TopoInfo", "solve_greedy_topo"]
