"""Interconnect topology model.

A ``Topology`` is a small tree over the node registry: the leaf level
partitions nodes into *blocks* (a TPU sub-slice / an ICI domain / the
nodes under one leaf switch), and optional upper levels group blocks
under switches.  Two construction paths:

* ``Topology.from_torus(shape, slice_shape)`` — a TPU v4-style 3D torus
  carved into aligned sub-tori (Jouppi et al., ISCA 2023): node id i is
  the row-major coordinate of the torus, and its block is the aligned
  ``slice_shape`` sub-torus containing it.
* explicit blocks/switches from the YAML ``Topology:`` section
  (``Topology.from_config``), mirroring Slurm's topology.conf
  SwitchName/Nodes lines.

Everything the solver needs is precomputed as flat arrays so the device
solve stays shape-static:

* ``block_of_node``  int32 [N], -1 = not in any block (never grouped)
* per level ``(group_of_node [N], group_sizes [G])`` — leaf first, each
  upper level's group ids composed through the parent maps
* ``perm`` / ``inv_perm`` — the **block-major node permutation**: a
  stable sort of node ids by block id.  Feeding the permuted node axis
  to the existing first-fit backends makes their left-to-right walk
  locality-aware with zero kernel changes (nodes of a block are
  contiguous, so cheapest/first picks cluster inside blocks).

Host (numpy) arrays are authoritative; jnp twins are built lazily so
the module stays importable without initializing JAX.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class Topology:
    """Static interconnect description over node ids ``0..N-1``.

    ``upper_levels`` is a sequence of ``(level_name, group_names,
    parent_of_prev_group int32)`` triples ordered bottom-up: the first
    entry maps leaf blocks to its groups, the next maps those groups up,
    and so on.  ``-1`` parents propagate (an ungrouped block stays
    ungrouped at every upper level).
    """

    def __init__(self, num_nodes: int, block_of_node,
                 block_names: Sequence[str],
                 upper_levels: Sequence[tuple] = (),
                 coords: Optional[np.ndarray] = None,
                 leaf_name: str = "block"):
        block_of_node = np.asarray(block_of_node, np.int32)
        if block_of_node.shape != (num_nodes,):
            raise ValueError(
                f"block_of_node shape {block_of_node.shape} != ({num_nodes},)")
        self.num_nodes = int(num_nodes)
        self.block_of_node = block_of_node
        self.block_names = [str(n) for n in block_names]
        self.num_blocks = len(self.block_names)
        if int(block_of_node.max(initial=-1)) >= self.num_blocks:
            raise ValueError("block_of_node references an unnamed block")
        self.leaf_name = str(leaf_name)
        self.coords = None if coords is None else np.asarray(coords, np.int32)
        self.upper_levels = [
            (str(name), [str(g) for g in gnames],
             np.asarray(parent, np.int32))
            for name, gnames, parent in upper_levels]
        for _, gnames, parent in self.upper_levels:
            if int(parent.max(initial=-1)) >= len(gnames):
                raise ValueError("parent map references an unnamed group")
        self.block_sizes = np.bincount(
            block_of_node[block_of_node >= 0],
            minlength=self.num_blocks).astype(np.int32)
        # block-major permutation: stable by block id, ungrouped nodes
        # (bin B) last; within a block, node-id order is preserved
        bins = np.where(block_of_node >= 0, block_of_node, self.num_blocks)
        self.perm = np.argsort(bins, kind="stable").astype(np.int32)
        self.inv_perm = np.empty_like(self.perm)
        self.inv_perm[self.perm] = np.arange(num_nodes, dtype=np.int32)
        self._levels_np = None
        self._jnp = None

    # ---- constructors ----

    @classmethod
    def from_torus(cls, shape: Sequence[int], slice_shape: Sequence[int],
                   name_prefix: str = "slice") -> "Topology":
        """Torus of ``shape`` carved into aligned ``slice_shape`` blocks.

        Node id = row-major coordinate; every dimension of ``shape``
        must be divisible by the matching ``slice_shape`` dimension so
        the sub-tori tile the torus exactly.
        """
        shape = [int(d) for d in shape]
        slice_shape = [int(s) for s in slice_shape]
        if len(shape) != len(slice_shape) or not shape:
            raise ValueError(
                f"torus shape {shape} and slice {slice_shape} must have "
                "the same (nonzero) rank")
        for d, s in zip(shape, slice_shape):
            if d <= 0 or s <= 0 or d % s:
                raise ValueError(
                    f"slice shape {slice_shape} does not tile torus {shape}")
        n = int(np.prod(shape))
        coords = np.stack(
            np.unravel_index(np.arange(n), shape), axis=1).astype(np.int32)
        grid = [d // s for d, s in zip(shape, slice_shape)]
        bcoords = coords // np.asarray(slice_shape, np.int32)
        block = np.ravel_multi_index(
            tuple(bcoords.T), grid).astype(np.int32)
        names = [
            name_prefix + "-" + "x".join(
                str(int(c)) for c in np.unravel_index(b, grid))
            for b in range(int(np.prod(grid)))]
        return cls(n, block, names, coords=coords)

    @classmethod
    def uniform_blocks(cls, num_nodes: int, block_size: int,
                       name_prefix: str = "block") -> "Topology":
        """Contiguous-id blocks of equal size (bench/replay generator)."""
        if block_size <= 0 or num_nodes % block_size:
            raise ValueError(
                f"block size {block_size} does not divide {num_nodes}")
        block = (np.arange(num_nodes) // block_size).astype(np.int32)
        names = [f"{name_prefix}{b}"
                 for b in range(num_nodes // block_size)]
        return cls(num_nodes, block, names)

    @classmethod
    def from_config(cls, spec: dict, name_to_id=None,
                    num_nodes: Optional[int] = None) -> "Topology":
        """Build from the YAML ``Topology:`` section.

        Torus shorthand::

            Topology:
              Torus: [8, 8, 8]
              Slice: [4, 4, 4]

        Explicit tree (Slurm topology.conf style)::

            Topology:
              Blocks:
                - name: b0
                  nodes: tpu[00000-00003]
              Switches:
                - name: sw0
                  blocks: [b0, b1]
        """
        if "Torus" in spec:
            slice_shape = spec.get("Slice") or spec.get("SliceShape")
            if not slice_shape:
                raise ValueError("Topology.Torus requires Slice: [x, y, z]")
            topo = cls.from_torus(spec["Torus"], slice_shape)
            if num_nodes is not None and topo.num_nodes != num_nodes:
                raise ValueError(
                    f"Torus {spec['Torus']} covers {topo.num_nodes} nodes "
                    f"but the cluster registers {num_nodes}")
            return topo
        blocks = spec.get("Blocks")
        if not blocks:
            raise ValueError("Topology: needs either Torus: or Blocks:")
        if num_nodes is None:
            raise ValueError("explicit Blocks: need the registry size")
        from cranesched_tpu.utils.hostlist import parse_hostlist
        name_to_id = name_to_id or {}
        block_of_node = np.full(num_nodes, -1, np.int32)
        names: list[str] = []
        for entry in blocks:
            bid = len(names)
            names.append(str(entry["name"]))
            for host in parse_hostlist(str(entry["nodes"])):
                nid = name_to_id.get(host)
                if nid is None:
                    raise ValueError(
                        f"Topology block {entry['name']!r}: unknown node "
                        f"{host!r}")
                if block_of_node[nid] >= 0:
                    raise ValueError(
                        f"node {host!r} listed in two topology blocks")
                block_of_node[nid] = bid
        uppers = []
        if spec.get("Switches"):
            parent = np.full(len(names), -1, np.int32)
            gnames: list[str] = []
            bindex = {nm: i for i, nm in enumerate(names)}
            for entry in spec["Switches"]:
                gid = len(gnames)
                gnames.append(str(entry["name"]))
                for b in entry.get("blocks", ()):
                    if str(b) not in bindex:
                        raise ValueError(
                            f"switch {entry['name']!r}: unknown block "
                            f"{b!r}")
                    if parent[bindex[str(b)]] >= 0:
                        raise ValueError(
                            f"block {b!r} listed under two switches")
                    parent[bindex[str(b)]] = gid
            uppers.append(("switch", gnames, parent))
        return cls(num_nodes, block_of_node, names, upper_levels=uppers)

    # ---- derived level arrays ----

    @property
    def levels_np(self):
        """Leaf-first ``[(name, group_of_node [N], sizes [G], names)]``."""
        if self._levels_np is None:
            out = [(self.leaf_name, self.block_of_node, self.block_sizes,
                    self.block_names)]
            gon = self.block_of_node
            for name, gnames, parent in self.upper_levels:
                gon = np.where(gon >= 0, parent[np.maximum(gon, 0)],
                               np.int32(-1)).astype(np.int32)
                sizes = np.bincount(
                    gon[gon >= 0], minlength=len(gnames)).astype(np.int32)
                out.append((name, gon, sizes, list(gnames)))
            self._levels_np = out
        return self._levels_np

    def _jnp_cache(self):
        if self._jnp is None:
            import jax.numpy as jnp
            self._jnp = {
                "levels": tuple((jnp.asarray(gon), jnp.asarray(sizes))
                                for _, gon, sizes, _ in self.levels_np),
                "perm": jnp.asarray(self.perm),
                "inv_perm": jnp.asarray(self.inv_perm),
            }
        return self._jnp

    @property
    def jnp_levels(self):
        """Device twin of ``levels_np`` in ``solve_greedy_topo`` form."""
        return self._jnp_cache()["levels"]

    @property
    def jnp_perm(self):
        return self._jnp_cache()["perm"]

    @property
    def jnp_inv_perm(self):
        return self._jnp_cache()["inv_perm"]

    def block_masks(self) -> np.ndarray:
        """Boolean block-membership matrix ``[B, N]``."""
        return (self.block_of_node[None, :]
                == np.arange(self.num_blocks, dtype=np.int32)[:, None])

    def block_path(self, node_id: int) -> tuple:
        """Top-down group-name path for a node, e.g. (switch, block)."""
        b = int(self.block_of_node[node_id])
        if b < 0:
            return ()
        path = [self.block_names[b]]
        g = b
        for _, gnames, parent in self.upper_levels:
            g = int(parent[g])
            if g < 0:
                break
            path.append(gnames[g])
        return tuple(reversed(path))

    # ---- telemetry ----

    def fragmentation(self, free_mask) -> list[tuple[str, float]]:
        """Per-level free-capacity fragmentation, leaf first.

        ``1 - largest_free_group / total_free`` — 0.0 means all free
        nodes sit in one group (a gang up to that size fits locally),
        1.0-ish means the free pool is dust.  Free nodes outside any
        group count toward the total (they do fragment gang capacity)
        but never toward a group's share.  Defined as 0.0 when nothing
        is free (an empty pool is not fragmented, just full).
        """
        free_mask = np.asarray(free_mask, bool)
        total_free = int(free_mask.sum())
        out = []
        for name, gon, sizes, _ in self.levels_np:
            if total_free == 0:
                out.append((name, 0.0))
                continue
            per = np.bincount(gon[free_mask & (gon >= 0)],
                              minlength=max(len(sizes), 1))
            largest = int(per.max(initial=0))
            out.append((name, round(1.0 - largest / total_free, 6)))
        return out


def topology_doc(topo: Topology, free_mask=None) -> dict:
    """JSON section for QueryStats (feeds ``cinfo --topo``)."""
    parent_names = None
    if topo.upper_levels:
        _, gnames, parent = topo.upper_levels[0]
        parent_names = [gnames[p] if p >= 0 else None for p in parent]
    frags = (dict(topo.fragmentation(free_mask))
             if free_mask is not None else {})
    doc = {"num_nodes": topo.num_nodes, "num_blocks": topo.num_blocks,
           "levels": []}
    for li, (name, gon, sizes, names) in enumerate(topo.levels_np):
        groups = []
        for g in range(len(names)):
            entry = {"name": names[g], "size": int(sizes[g])}
            if free_mask is not None:
                entry["free"] = int(
                    np.asarray(free_mask, bool)[gon == g].sum())
            if li == 0 and parent_names is not None:
                entry["parent"] = parent_names[g]
            groups.append(entry)
        doc["levels"].append({"name": name,
                              "fragmentation": frags.get(name),
                              "groups": groups})
    return doc
