"""Batched best-fit-block gang placement.

``solve_greedy_topo`` is ``models.solver.solve_greedy`` with a
topology-restriction stage spliced between feasibility and node
selection.  Per scan step (one job), entirely in fixed-shape vector ops:

1. Segment-sum the job's feasible-node mask into per-group counts at
   every topology level (the [J,B] feasible-count matrix of the design,
   materialized one row per scan step so state mutations stay exact).
2. **Best fit**: at the leaf level pick the group with the smallest
   ``size`` among those whose feasible count covers the whole gang
   (ties → lowest group id, matching Slurm topology/tree's
   smallest-feasible-switch rule).
3. If no leaf block fits, restrict to the lowest *ancestor* level where
   some group fits (lowest-common-ancestor spanning), then span the
   fewest leaf blocks inside it: blocks ordered by descending feasible
   count (ties → lowest id), minimal prefix covering ``node_num``.
   Everything outside that spanning set gets the sentinel cost — an
   infinite cross-block penalty, so the cheapest-k walk cannot leak out.
4. ``cheapest_k`` over the restricted cost vector, allocation and cost
   update identical to the base solver.

Single-node jobs (``node_num == 1``) skip the restriction — locality for
them comes from the block-major permutation (see topo/model.py).

Semantics are deterministic in real node-id order (no hidden permutation)
so ``testing/topo_oracle.py`` can mirror them bit-for-bit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from cranesched_tpu.models.solver import (
    COST_INF,
    ClusterState,
    JobBatch,
    Placements,
    apply_placement,
    cheapest_k,
    decide_job,
    job_feasibility,
)


@struct.dataclass
class TopoInfo:
    """Per-job placement-locality verdicts, aligned with the job order.

    in_block: bool[J]   gang placed entirely inside one leaf block
    cross:    bool[J]   gang placed by the cross-block spanning fallback
    block:    int32[J]  leaf block id when in_block, else -1
    """

    in_block: jax.Array
    cross: jax.Array
    block: jax.Array


def _group_onehot(gon, num_groups):
    """Static int32 [G+1, N] membership matrix; row G = ungrouped.

    Per-step group counts are then ``onehot @ feasible`` — one small
    matmul instead of a scatter-add, which lowers to a SERIAL scatter
    on both CPU and TPU and dominated the solve before."""
    bins = jnp.where(gon >= 0, gon, num_groups)
    return (bins[None, :] == jnp.arange(num_groups + 1)[:, None]
            ).astype(jnp.int32)


def _level_fit(feasible, onehot, gon, sizes, node_num):
    """Smallest group at one level that fits the whole gang.

    Returns (have, group_id, member_mask): ``have`` iff some group's
    feasible count >= node_num; the winner is the smallest ``sizes[g]``,
    ties to the lowest group id (argmin first-occurrence).
    """
    num_groups = sizes.shape[0]
    counts = onehot @ feasible.astype(jnp.int32)
    fits = counts[:num_groups] >= node_num
    key = jnp.where(fits, sizes, jnp.int32(COST_INF))
    g = jnp.argmin(key).astype(jnp.int32)
    return fits[g], g, gon == g


def _span_mask(feasible, onehot, gon, sizes, node_num):
    """Minimal leaf-block spanning set: blocks ordered by descending
    feasible count (stable argsort → ties to the lowest id; the
    ungrouped pool rides along as one extra pseudo-block), minimal
    prefix whose cumulative count reaches ``node_num``."""
    num_groups = sizes.shape[0]
    counts = onehot @ feasible.astype(jnp.int32)
    order = jnp.argsort(-counts)
    sorted_counts = counts[order]
    cum = jnp.cumsum(sorted_counts)
    needed = ((cum - sorted_counts) < node_num) & (sorted_counts > 0)
    sel = jnp.zeros(num_groups + 1, bool).at[order].set(needed)
    bins = jnp.where(gon >= 0, gon, num_groups)
    return sel[bins]


@functools.partial(jax.jit, static_argnames=("max_nodes",))
def solve_greedy_topo(state: ClusterState, jobs: JobBatch, levels,
                      max_nodes: int = 1
                      ) -> tuple[Placements, ClusterState, TopoInfo]:
    """Topology-restricted greedy solve.

    ``levels`` is the leaf-first tuple of ``(group_of_node int32[N],
    group_sizes int32[G])`` pairs (``Topology.jnp_levels``); -1 marks a
    node outside every group at that level.  Admission (``decide_job``)
    uses the GLOBAL feasible count, so a gang the cluster can hold is
    never refused by the restriction — at worst it spans blocks and is
    flagged ``cross``.
    """
    max_nodes = min(max_nodes, state.num_nodes)
    leaf_gon, leaf_sizes = levels[0]
    prepped = tuple((gon, sizes, _group_onehot(gon, sizes.shape[0]))
                    for gon, sizes in levels)
    leaf_onehot = prepped[0][2]

    def step(carry, job):
        avail, cost = carry
        req, node_num, time_limit, part_mask, valid = job
        eligible, feasible = job_feasibility(avail, state.alive, part_mask,
                                             req)
        ok, reason = decide_job(valid, node_num, max_nodes,
                                jnp.sum(feasible, dtype=jnp.int32),
                                jnp.sum(eligible, dtype=jnp.int32))

        have_leaf, blk, leaf_mask = _level_fit(
            feasible, leaf_onehot, leaf_gon, leaf_sizes, node_num)
        gang = node_num > 1

        def _span_branch():
            # lowest fitting ancestor level bounds the spanning set; if
            # no level fits, the whole cluster is the "ancestor"
            anc_mask = jnp.ones_like(feasible)
            for gon, sizes, onehot in reversed(prepped[1:]):
                have, _, mask = _level_fit(feasible, onehot, gon, sizes,
                                           node_num)
                anc_mask = jnp.where(have, mask, anc_mask)
            return _span_mask(feasible & anc_mask, leaf_onehot,
                              leaf_gon, leaf_sizes, node_num)

        def _local_branch():
            return jnp.where(gang, leaf_mask, jnp.ones_like(feasible))

        # the spanning fallback is the rare path; cond keeps its extra
        # counts/argsort off the per-job critical path when a leaf fits
        restrict = jax.lax.cond(gang & ~have_leaf, _span_branch,
                                _local_branch)
        masked_cost = jnp.where(feasible & restrict, cost, COST_INF)
        sel_cost, idx = cheapest_k(masked_cost, max_nodes)
        k_mask = jnp.arange(max_nodes) < node_num
        sel = ok & k_mask & (sel_cost < COST_INF)
        avail, cost = apply_placement(avail, cost, state.total, req,
                                      time_limit, idx, sel)
        chosen = jnp.where(sel, idx, -1)
        in_block = ok & gang & have_leaf
        cross = ok & gang & ~have_leaf
        blk_out = jnp.where(in_block, blk, -1)
        return (avail, cost), (ok, chosen, reason, in_block, cross,
                               blk_out)

    (avail, cost), (placed, nodes, reason, in_block, cross, block) = (
        jax.lax.scan(
            step, (state.avail, state.cost),
            (jobs.req, jobs.node_num, jobs.time_limit, jobs.part_mask,
             jobs.valid)))

    new_state = state.replace(avail=avail, cost=cost)
    return (Placements(placed=placed, nodes=nodes, reason=reason),
            new_state,
            TopoInfo(in_block=in_block, cross=cross, block=block))


def solve_greedy_topo_permuted(state: ClusterState, jobs: JobBatch, topo,
                               max_nodes: int = 1
                               ) -> tuple[Placements, ClusterState,
                                          TopoInfo]:
    """Run the topo solve in block-major node order and map results back
    to real node ids — the same permutation plumbing the scheduler
    applies to the single-node backends, exercised against the direct
    solve for equivalence testing.

    Block ids are invariant under the node permutation and the stable
    block-major sort preserves within-block id order, so with a
    tie-free cost vector this returns exactly the direct solve's
    placements.
    """
    perm = topo.jnp_perm
    inv = topo.jnp_inv_perm
    pstate = state.replace(avail=state.avail[perm],
                           total=state.total[perm],
                           alive=state.alive[perm],
                           cost=state.cost[perm])
    pjobs = jobs.replace(part_mask=jobs.part_mask[:, perm])
    plevels = tuple((gon[perm], sizes) for gon, sizes in topo.jnp_levels)
    placements, pstate2, info = solve_greedy_topo(
        pstate, pjobs, plevels, max_nodes=max_nodes)
    real_nodes = jnp.where(placements.nodes >= 0,
                           perm[jnp.maximum(placements.nodes, 0)],
                           jnp.int32(-1))
    state2 = pstate2.replace(avail=pstate2.avail[inv],
                             total=pstate2.total[inv],
                             alive=pstate2.alive[inv],
                             cost=pstate2.cost[inv])
    return placements.replace(nodes=real_nodes), state2, info
