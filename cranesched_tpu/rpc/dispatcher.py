"""Per-node push dispatch: the reference's CranedKeeper + scheduler
fan-out (reference: src/CraneCtld/RpcService/CranedKeeper.h:74-107 — one
stub per craned on shared channels; AllocJobs/AllocSteps fan-out with a
thread pool + latch, JobScheduler.cpp:1732-1839).

Wire-up::

    dispatcher = GrpcDispatcher(scheduler)
    scheduler.dispatch = dispatcher.dispatch
    scheduler.dispatch_terminate = dispatcher.terminate
    scheduler.dispatch_suspend = dispatcher.suspend
    scheduler.dispatch_resume = dispatcher.resume
    server = CtldServer(scheduler, dispatcher=dispatcher)
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

import grpc

from cranesched_tpu.ctld.defs import Job, JobStatus
from cranesched_tpu.rpc import crane_pb2 as pb
from cranesched_tpu.rpc.consts import CRANED_SERVICE
from cranesched_tpu.rpc.convert import spec_to_pb
from cranesched_tpu.rpc.stub import GrpcStub


class _CranedStub(GrpcStub):
    """One channel per craned (reference CranedStub)."""

    def __init__(self, address: str, timeout: float = 10.0):
        super().__init__(address, CRANED_SERVICE, timeout)

    def call(self, name, request, reply_cls=pb.OkReply):
        return super().call(name, request, reply_cls)


class GrpcDispatcher:
    def __init__(self, scheduler, max_workers: int = 8):
        self.scheduler = scheduler
        self._stubs: dict[int, _CranedStub] = {}
        self._lock = threading.Lock()
        self._pool = futures.ThreadPoolExecutor(max_workers=max_workers)

    def node_registered(self, node_id: int, address: str) -> None:
        with self._lock:
            old = self._stubs.get(node_id)
            if old is not None and old.address != address:
                old.close()
                old = None
            if old is None:
                self._stubs[node_id] = _CranedStub(address)

    def _stub(self, node_id: int) -> _CranedStub | None:
        with self._lock:
            return self._stubs.get(node_id)

    # ---- the dispatch seam ----

    def dispatch(self, job: Job, node_ids: list[int]) -> None:
        """ExecuteStep fan-out, ASYNCHRONOUS: the caller holds the ctld
        lock, so pushes must not block on craned RPCs (an unreachable
        craned would stall pings from healthy nodes and cascade false
        CranedDown events).  A failed push fails the job via the normal
        status-change path (the reference frees resources and marks
        Failed on dispatch errors, JobScheduler.cpp:1908-1967)."""
        spec_pb = spec_to_pb(job.spec)
        tasks = job.task_layout or [1] * len(node_ids)
        # capture the incarnation NOW, synchronously under the ctld lock:
        # the async fan_out below can outlive a requeue (node death while
        # a push blocks on its RPC timeout), and a stale failure report
        # stamped with the job's *current* requeue_count would defeat the
        # staleness guard and kill the healthy new incarnation
        incarnation = job.requeue_count

        def push(node_id, ntasks):
            stub = self._stub(node_id)
            if stub is None:
                return f"node {node_id} has no stub"
            # transient refusals (e.g. GRES slots still held by a
            # previous incarnation mid-teardown) retry briefly
            for attempt in range(10):
                try:
                    reply = stub.call("ExecuteStep",
                                      pb.ExecuteStepRequest(
                                          job_id=job.job_id,
                                          spec=spec_pb,
                                          tasks_on_node=ntasks,
                                          now=time.time(),
                                          incarnation=incarnation))
                except grpc.RpcError as exc:
                    return f"push to node {node_id} failed: {exc.code()}"
                if reply.ok:
                    return ""
                if not reply.error.startswith("retryable:"):
                    return reply.error
                time.sleep(0.5)
            return reply.error

        def fan_out():
            errors = [e for e in map(push, node_ids,
                                     tasks[: len(node_ids)]) if e]
            if errors:
                # kill any step that did start — guarded by OUR
                # incarnation, so if the job was requeued and re-placed
                # while a push blocked on its RPC timeout, this late
                # cleanup cannot kill the healthy new incarnation
                for node_id in node_ids:
                    self._try_call(node_id, "TerminateStep",
                                   pb.JobIdRequest(job_id=job.job_id,
                                                   incarnation=incarnation))
                self.scheduler.step_status_change(
                    job.job_id, JobStatus.FAILED, 254, time.time(),
                    incarnation=incarnation)

        self._pool.submit(fan_out)

    def terminate(self, job_id: int, now: float,
                  incarnation: int | None = None,
                  skip_node: int | None = None) -> None:
        nodes = [n for n in self._job_nodes(job_id) if n != skip_node]
        req = (pb.JobIdRequest(job_id=job_id, incarnation=incarnation)
               if incarnation is not None
               else pb.JobIdRequest(job_id=job_id))
        self._pool.submit(lambda: [
            self._try_call(n, "TerminateStep", req) for n in nodes])

    def suspend(self, job_id: int, now: float) -> None:
        nodes = self._job_nodes(job_id)
        self._pool.submit(lambda: [
            self._try_call(n, "SuspendStep",
                           pb.JobIdRequest(job_id=job_id))
            for n in nodes])

    def resume(self, job_id: int, now: float) -> None:
        nodes = self._job_nodes(job_id)
        self._pool.submit(lambda: [
            self._try_call(n, "ResumeStep",
                           pb.JobIdRequest(job_id=job_id))
            for n in nodes])

    def _job_nodes(self, job_id: int) -> list[int]:
        job = self.scheduler.running.get(job_id)
        return list(job.node_ids) if job is not None else []

    def _try_call(self, node_id, name, request) -> None:
        stub = self._stub(node_id)
        if stub is None:
            return
        try:
            stub.call(name, request)
        except grpc.RpcError:
            pass  # the ping timeout will reap a dead node

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._lock:
            for stub in self._stubs.values():
                stub.close()
