"""Per-node push dispatch: the reference's CranedKeeper + scheduler
fan-out (reference: src/CraneCtld/RpcService/CranedKeeper.h:74-107 — one
stub per craned on shared channels; AllocJobs/AllocSteps fan-out with a
thread pool + latch, JobScheduler.cpp:1732-1839).

Wire-up::

    dispatcher = GrpcDispatcher(scheduler)
    scheduler.dispatch = dispatcher.dispatch
    scheduler.dispatch_step = dispatcher.dispatch_step
    scheduler.dispatch_terminate = dispatcher.terminate
    scheduler.dispatch_terminate_step = dispatcher.terminate_step
    scheduler.dispatch_free_alloc = dispatcher.free_alloc
    scheduler.dispatch_suspend = dispatcher.suspend
    scheduler.dispatch_resume = dispatcher.resume
    server = CtldServer(scheduler, dispatcher=dispatcher)
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

import grpc

from cranesched_tpu.ctld.defs import Job, JobStatus
from cranesched_tpu.rpc import crane_pb2 as pb
from cranesched_tpu.rpc.consts import CRANED_SERVICE
from cranesched_tpu.rpc.convert import spec_to_pb, step_spec_to_pb
from cranesched_tpu.rpc.stub import GrpcStub


class _CranedStub(GrpcStub):
    """One channel per craned (reference CranedStub)."""

    #: duck-typed capability flag — the dispatcher only passes the
    #: crane-trace metadata kwarg to stubs that advertise it (test
    #: fakes keep the plain (name, request) signature)
    trace_metadata = True

    def __init__(self, address: str, timeout: float = 10.0, tls=None):
        super().__init__(address, CRANED_SERVICE, timeout, tls=tls)

    def call(self, name, request, reply_cls=pb.OkReply, metadata=()):
        return super().call(name, request, reply_cls, metadata=metadata)


class _PushState:
    """Per-job completion latch for the coalesced fan-out: a job's
    pushes run on different per-node pool tasks, so the LAST node to
    finish (success or not) fires the rollback if any node errored —
    the coalesced analogue of the old per-job fan_out join."""

    __slots__ = ("_lock", "_remaining", "_errors", "_rollback")

    def __init__(self, remaining: int, rollback):
        self._lock = threading.Lock()
        self._remaining = remaining
        self._errors: list[str] = []
        self._rollback = rollback

    def done(self, error: str) -> None:
        with self._lock:
            if error:
                self._errors.append(error)
            self._remaining -= 1
            fire = self._remaining == 0 and bool(self._errors)
        if fire:
            self._rollback()


class GrpcDispatcher:
    def __init__(self, scheduler, max_workers: int | None = None,
                 tls=None):
        self.scheduler = scheduler
        # utils.pki.TlsConfig: push channels to craneds dial TLS,
        # verified against the cluster CA (craneds serve their node
        # certs) — the internal fabric's encrypted half
        self.tls = tls
        # fan-out width: explicit arg > SchedulerConfig.dispatch_workers
        # (YAML DispatchWorkers) > derived from cluster size.  The old
        # hardcoded 8 serialized a 10k-node cycle's pushes 8 at a time.
        if max_workers is None:
            max_workers = getattr(scheduler.config, "dispatch_workers",
                                  None)
        if max_workers is None:
            max_workers = self.default_workers(
                len(scheduler.meta.nodes))
        self.max_workers = int(max_workers)
        self._stubs: dict[int, _CranedStub] = {}
        self._lock = threading.Lock()
        self._pool = futures.ThreadPoolExecutor(
            max_workers=self.max_workers)

    @staticmethod
    def default_workers(num_nodes: int) -> int:
        """max(8, nodes // 64), capped at 128: wide enough that a
        10k-node commit wave drains in ~nodes/width push rounds, small
        enough not to oversubscribe the ctld host."""
        return min(max(8, num_nodes // 64), 128)

    def wire(self, scheduler) -> None:
        """Attach every dispatch seam in one place (wiring the seams
        individually has already produced a missed-seam bug once)."""
        scheduler.dispatch = self.dispatch
        scheduler.dispatch_batch = self.dispatch_batch
        scheduler.dispatch_step = self.dispatch_step
        scheduler.dispatch_terminate = self.terminate
        scheduler.dispatch_terminate_step = self.terminate_step
        scheduler.dispatch_free_alloc = self.free_alloc
        scheduler.dispatch_suspend = self.suspend
        scheduler.dispatch_resume = self.resume
        scheduler.dispatch_change_time_limit = self.change_time_limit

    def node_registered(self, node_id: int, address: str) -> None:
        tls = self.tls
        if tls is not None:
            # pin the channel to the node's own cert identity: a
            # compromised node redirecting its address at another
            # node's port cannot answer as it (certs are per-name)
            node = self.scheduler.meta.nodes.get(node_id)
            if node is not None:
                tls = tls.pinned(node.name)
        with self._lock:
            old = self._stubs.get(node_id)
            if old is not None and old.address != address:
                old.close()
                old = None
            if old is None:
                self._stubs[node_id] = _CranedStub(address, tls=tls)

    def _stub(self, node_id: int) -> _CranedStub | None:
        with self._lock:
            return self._stubs.get(node_id)

    # ---- the dispatch seam ----

    def dispatch(self, job: Job, node_ids: list[int]) -> None:
        """ExecuteStep/AllocJob fan-out, ASYNCHRONOUS: pushes must not
        block the caller on craned RPCs (an unreachable craned would
        stall pings from healthy nodes and cascade false CranedDown
        events).  A failed push fails the job via the normal
        status-change path (the reference frees resources and marks
        Failed on dispatch errors, JobScheduler.cpp:1908-1967).

        Batch jobs push ExecuteStep (implicit allocation + step 0 in
        one); alloc_only jobs push AllocJob (the allocation sits until
        steps arrive via dispatch_step)."""
        self.dispatch_batch([(job, node_ids, job.requeue_count,
                              self.scheduler.fencing_epoch)])

    def dispatch_batch(self, items) -> None:
        """Coalesced post-commit fan-out: the scheduler's dispatch ring
        arrives as ONE call; requests are grouped per craned so N jobs
        landing on one node become one pool task pushing back-to-back
        on that node's channel, instead of N independent fan-outs
        threading through the pool.  Per-job semantics are unchanged —
        if any of a job's nodes fails, whatever DID land is rolled back
        and the job fails via the status-change path.

        ``items`` entries are ``(job, node_ids, incarnation,
        fencing_epoch)`` (or 2-tuples, which re-read both from live
        state).  The 4-tuple values are captured synchronously under
        the ctld lock at commit time: the async pushes below can
        outlive a requeue (node death while a push blocks on its RPC
        timeout), and a stale failure report stamped with the job's
        *current* requeue_count would defeat the staleness guard and
        kill the healthy new incarnation; likewise a push built after
        this ctld lost its lease must carry the OLD fencing epoch so
        craneds that learned the new one reject it."""
        by_node: dict[int, list[tuple]] = {}
        for item in items:
            job, node_ids = item[0], list(item[1])
            if not node_ids:
                continue
            incarnation = (item[2] if len(item) > 2
                           else job.requeue_count)
            epoch = (item[3] if len(item) > 3
                     else self.scheduler.fencing_epoch)
            push, rollback, tasks = self._build_push(
                job, node_ids, incarnation, epoch)
            state = _PushState(len(node_ids), rollback)
            for rank, node_id in enumerate(node_ids):
                ntasks = tasks[rank] if rank < len(tasks) else 1
                by_node.setdefault(node_id, []).append(
                    (push, node_id, ntasks, state))
        for entries in by_node.values():
            self._pool.submit(self._push_node_batch, entries)

    @staticmethod
    def _push_node_batch(entries) -> None:
        """One pool task per craned: push every job bound for this node
        sequentially; a job's LAST completing node triggers its
        rollback if any node errored."""
        for push, node_id, ntasks, state in entries:
            err = push(node_id, ntasks)
            state.done(err)

    def _build_push(self, job: Job, node_ids: list[int],
                    incarnation: int, epoch: int):
        """One job's push closure + rollback, built once per dispatch
        (the pb encode + gang context are the per-job cost; per-node
        work is just the request stamp + the RPC)."""
        verb = "AllocJob" if job.spec.alloc_only else "ExecuteStep"
        step0 = job.steps.get(0)
        step_pb = (step_spec_to_pb(step0.spec)
                   if step0 is not None else None)
        spec_pb = spec_to_pb(job.spec)
        tasks = job.task_layout or [1] * len(node_ids)
        gang = self._gang_ctx(job.job_id, node_ids,
                              int(sum(tasks[: len(node_ids)])))
        # trace context (jobtrace): the base span seq lets the craned
        # number its local spans after the ctld-side ones, so the merged
        # timeline sorts monotonically by seq.  Captured here, at build
        # time, right after the ring drain stamped committed_durable +
        # dispatched for this incarnation.
        trace_md = ()
        if getattr(self.scheduler, "jobtrace", None) is not None:
            base_seq = self.scheduler.trace_seq(job.job_id, incarnation)
            trace_md = (("crane-trace",
                         f"{job.job_id}/{incarnation}/{epoch}/"
                         f"{base_seq}"),)

        def push(node_id, ntasks):
            stub = self._stub(node_id)
            if stub is None:
                return f"node {node_id} has no stub"
            # transient refusals (e.g. GRES slots still held by a
            # previous incarnation mid-teardown) retry briefly
            for attempt in range(10):
                req = pb.ExecuteStepRequest(
                    job_id=job.job_id, spec=spec_pb,
                    tasks_on_node=ntasks, now=time.time(),
                    incarnation=incarnation, step_id=0,
                    fencing_epoch=epoch,
                    nodelist=gang["nodelist"],
                    node_rank=gang["rank"][node_id],
                    nnodes=len(node_ids),
                    ntasks=gang["ntasks"],
                    rendezvous=gang["rendezvous"],
                    rendezvous_token=gang["token"])
                if step_pb is not None:
                    req.step.CopyFrom(step_pb)
                try:
                    if trace_md and getattr(stub, "trace_metadata",
                                            False):
                        reply = stub.call(verb, req,
                                          metadata=trace_md)
                    else:
                        reply = stub.call(verb, req)
                except grpc.RpcError as exc:
                    return f"push to node {node_id} failed: {exc.code()}"
                if reply.ok:
                    return ""
                if not reply.error.startswith("retryable:"):
                    self._note_fenced(node_id, reply.error)
                    return reply.error
                time.sleep(0.5)
            return reply.error

        def rollback():
            # roll back whatever DID land — guarded by OUR incarnation,
            # so if the job was requeued and re-placed while a push
            # blocked on its RPC timeout, this late cleanup cannot
            # touch the healthy new incarnation.  AllocJob pushes must
            # be undone with FreeJob (an explicit allocation with zero
            # steps ignores TerminateStep and would leak its cgroup +
            # GRES).
            undo = "FreeJob" if verb == "AllocJob" else "TerminateStep"
            for node_id in node_ids:
                self._try_call(node_id, undo,
                               pb.JobIdRequest(job_id=job.job_id,
                                               incarnation=incarnation,
                                               fencing_epoch=epoch))
            self.scheduler.step_status_change(
                job.job_id, JobStatus.FAILED, 254, time.time(),
                incarnation=incarnation)

        return push, rollback, tasks

    def _gang_ctx(self, job_id: int, node_ids: list[int],
                  ntasks: int, step_id: int = 0) -> dict:
        """Per-gang rendezvous context (the PMIx role per SURVEY §2.4):
        compressed nodelist, per-node rank, and a deterministic
        rank-0 rendezvous endpoint — enough for members to enumerate
        each other and bootstrap a jax.distributed-style init."""
        from cranesched_tpu.utils.hostlist import compress_hostlist
        nodes = self.scheduler.meta.nodes
        names = [nodes[n].name if n in nodes else f"?{n}"
                 for n in node_ids]
        # deterministic per-(job, step, incarnation) port in a high
        # range: two concurrent steps of one allocation must not share a
        # coordinator endpoint.  Hashing removes the old job_id*131
        # lattice correlation, but the port space is still 20000, so two
        # concurrently live gangs sharing a rank-0 host collide with
        # ~1/20000 probability per pair (birthday regime near ~170 such
        # gangs).  Residual risk accepted for the env-only bootstrap;
        # the fix-proper (rank-0 picks a free port and reports back)
        # needs a supervisor round-trip this path deliberately avoids
        incarnation = self.scheduler.running[job_id].requeue_count \
            if job_id in self.scheduler.running else 0
        import hashlib
        import secrets
        digest = hashlib.blake2b(
            f"{job_id}/{step_id}/{incarnation}".encode(),
            digest_size=8).digest()
        port = 28000 + (int.from_bytes(digest, "big") % 20000)
        return {
            "nodelist": compress_hostlist(names),
            "rank": {n: i for i, n in enumerate(node_ids)},
            "ntasks": ntasks,
            "rendezvous": f"{names[0]}:{port}" if names else "",
            # gates the rank-0 fence/modex service: unguessable,
            # one per dispatched gang
            "token": secrets.token_urlsafe(12),
        }

    def dispatch_step(self, job: Job, step) -> None:
        """Push one step into an existing allocation (the AllocSteps
        half).  Failure cancels just the step via step_report."""
        spec_pb = spec_to_pb(job.spec)
        step_pb = step_spec_to_pb(step.spec)
        incarnation = job.requeue_count
        epoch = self.scheduler.fencing_epoch
        node_ids = list(step.node_ids)
        step_id = step.step_id
        gang = self._gang_ctx(job.job_id, node_ids, len(node_ids),
                              step_id=step_id)

        def push():
            from cranesched_tpu.ctld.defs import StepStatus
            errors = []
            for node_id in node_ids:
                stub = self._stub(node_id)
                if stub is None:
                    errors.append(f"node {node_id} has no stub")
                    continue
                req = pb.ExecuteStepRequest(
                    job_id=job.job_id, spec=spec_pb, tasks_on_node=1,
                    now=time.time(), incarnation=incarnation,
                    step_id=step_id, fencing_epoch=epoch,
                    nodelist=gang["nodelist"],
                    node_rank=gang["rank"][node_id],
                    nnodes=len(node_ids),
                    ntasks=gang["ntasks"],
                    rendezvous=gang["rendezvous"],
                    rendezvous_token=gang["token"])
                req.step.CopyFrom(step_pb)
                try:
                    reply = stub.call("ExecuteStep", req)
                except grpc.RpcError as exc:
                    errors.append(f"push to node {node_id}: {exc.code()}")
                    continue
                if not reply.ok:
                    self._note_fenced(node_id, reply.error)
                    errors.append(reply.error)
            if errors:
                for node_id in node_ids:
                    self._try_call(node_id, "TerminateStep",
                                   pb.JobIdRequest(job_id=job.job_id,
                                                   step_id=step_id,
                                                   incarnation=incarnation,
                                                   fencing_epoch=epoch))
                # enqueue, never mutate: this runs on a pool thread
                # without the server lock (step_report would race the
                # cycle thread's _try_start_steps and WAL writes)
                self.scheduler.step_report_async(
                    job.job_id, step_id, StepStatus.FAILED, 254,
                    time.time(), incarnation=incarnation)

        self._pool.submit(push)

    def terminate_step(self, job_id: int, step_id: int,
                       now: float) -> None:
        job = self.scheduler.running.get(job_id)
        if job is None:
            return
        step = job.steps.get(step_id)
        nodes = list(step.node_ids) if step is not None else []
        incarnation = job.requeue_count
        epoch = self.scheduler.fencing_epoch
        self._pool.submit(lambda: [
            self._try_call(n, "TerminateStep",
                           pb.JobIdRequest(job_id=job_id, step_id=step_id,
                                           incarnation=incarnation,
                                           fencing_epoch=epoch))
            for n in nodes])

    def free_alloc(self, job_id: int, now: float,
                   incarnation: int | None = None,
                   skip_node: int | None = None) -> None:
        """Release the allocation on every node (FreeJob fan-out)."""
        nodes = [n for n in self._job_nodes(job_id) if n != skip_node]
        epoch = self.scheduler.fencing_epoch
        req = (pb.JobIdRequest(job_id=job_id, incarnation=incarnation,
                               fencing_epoch=epoch)
               if incarnation is not None
               else pb.JobIdRequest(job_id=job_id, fencing_epoch=epoch))
        self._pool.submit(lambda: [
            self._try_call(n, "FreeJob", req) for n in nodes])

    def terminate(self, job_id: int, now: float,
                  incarnation: int | None = None,
                  skip_node: int | None = None) -> None:
        nodes = [n for n in self._job_nodes(job_id) if n != skip_node]
        epoch = self.scheduler.fencing_epoch
        req = (pb.JobIdRequest(job_id=job_id, incarnation=incarnation,
                               fencing_epoch=epoch)
               if incarnation is not None
               else pb.JobIdRequest(job_id=job_id, fencing_epoch=epoch))
        self._pool.submit(lambda: [
            self._try_call(n, "TerminateStep", req) for n in nodes])

    def suspend(self, job_id: int, now: float) -> None:
        nodes = self._job_nodes(job_id)
        epoch = self.scheduler.fencing_epoch
        self._pool.submit(lambda: [
            self._try_call(n, "SuspendStep",
                           pb.JobIdRequest(job_id=job_id,
                                           fencing_epoch=epoch))
            for n in nodes])

    def resume(self, job_id: int, now: float) -> None:
        nodes = self._job_nodes(job_id)
        epoch = self.scheduler.fencing_epoch
        self._pool.submit(lambda: [
            self._try_call(n, "ResumeStep",
                           pb.JobIdRequest(job_id=job_id,
                                           fencing_epoch=epoch))
            for n in nodes])

    def change_time_limit(self, job_id: int, time_limit: float,
                          now: float) -> None:
        """Push a modified deadline to the job's batch supervisors
        (reference ChangeJobTimeConstraint, Crane.proto:1654).  The push
        can beat the supervisor spawn (the craned then answers
        ok=False), so the scheduler renews the intent each cycle; once
        EVERY node accepts, the intent is popped here."""
        job = self.scheduler.running.get(job_id)
        if job is None:
            return
        nodes = list(job.node_ids)
        incarnation = job.requeue_count
        epoch = self.scheduler.fencing_epoch
        request = pb.TimeLimitRequest(job_id=job_id,
                                      time_limit=time_limit,
                                      incarnation=incarnation,
                                      fencing_epoch=epoch)

        def push():
            all_ok = True
            for n in nodes:
                stub = self._stub(n)
                if stub is None:
                    all_ok = False
                    continue
                try:
                    reply = stub.call("ChangeTimeLimit", request)
                    all_ok &= bool(reply.ok)
                except grpc.RpcError:
                    all_ok = False
            if all_ok:
                # racy-but-benign pop: a concurrent renewal just sends
                # one extra idempotent update
                self.scheduler._limit_intents.pop(job_id, None)

        self._pool.submit(push)

    def _job_nodes(self, job_id: int) -> list[int]:
        job = self.scheduler.running.get(job_id)
        return list(job.node_ids) if job is not None else []

    def _note_fenced(self, node_id, error: str) -> None:
        """Surface a craned-side fencing rejection in the event ring:
        the craned is a separate process, so the ctld whose push was
        refused is the one that can record it (the deposed leader's
        ring — the test harness and post-mortems read it there)."""
        if not error or not error.startswith("fenced"):
            return
        try:
            self.scheduler.events.emit("fencing_rejection", "error",
                                       node=str(node_id), detail=error)
        except Exception:
            pass  # observability must never break a dispatch path

    def _try_call(self, node_id, name, request) -> None:
        stub = self._stub(node_id)
        if stub is None:
            return
        try:
            reply = stub.call(name, request)
        except grpc.RpcError:
            return  # the ping timeout will reap a dead node
        if not getattr(reply, "ok", True):
            self._note_fenced(node_id, reply.error)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._lock:
            for stub in self._stubs.values():
                stub.close()
