"""Wire constants shared by client and server (kept dependency-free so
the CLI can import the client without pulling the scheduler + JAX)."""

SERVICE = "cranesched.CraneCtld"
CRANED_SERVICE = "cranesched.Craned"
CFORED_SERVICE = "cranesched.CraneFored"
