"""Client-embedded interactive I/O hub: the cfored role.

The reference runs a standalone user-side ``cfored`` daemon holding a
bidi ``CforedStream`` to ctld plus per-step ``StepIOStream``s from
supervisors (reference: protos/Crane.proto:794-900,1679;
src/Craned/Supervisor/CforedClient.h:28-95).  Here the hub is embedded
in the submitting client (crun/calloc): it hosts the ``CraneFored``
gRPC service, the submitting spec carries its address, and each
supervisor connects back with one ``StepIO`` bidi stream.

Ordering contract (CforedClient.h:60-63 — output drained before exit
status): the supervisor sends the final ``exited`` chunk only after
both output pipes reached EOF, so by construction a client that reads
the stream in order has seen every output byte before the exit code.
"""

from __future__ import annotations

import queue
import threading
from concurrent import futures

import grpc

from cranesched_tpu.rpc import crane_pb2 as pb
from cranesched_tpu.rpc.consts import CFORED_SERVICE


class StepIOSession:
    """One supervisor's live stream, as seen by the client.

    ``read()`` yields (stream-name, bytes) chunks until the step exits;
    ``exit_code`` is set once the final chunk arrived.  ``send_stdin``/
    ``close_stdin`` feed the response direction.
    """

    def __init__(self, job_id: int, step_id: int):
        self.job_id = job_id
        self.step_id = step_id
        self.exit_code: int | None = None
        self._out: queue.Queue = queue.Queue()
        self._stdin: queue.Queue = queue.Queue()
        self.exited = threading.Event()
        # ordering observability: bytes received before the exited
        # chunk — equals the total output iff the drained-before-exit
        # contract held (chunks arrive in stream order)
        self.bytes_received = 0
        self.bytes_at_exit: int | None = None

    # -- client side --

    def read(self, timeout: float | None = None):
        """Yield (stream, bytes) until the exited chunk; sets exit_code."""
        while True:
            item = self._out.get(timeout=timeout)
            if item is None:
                return
            yield item

    def send_stdin(self, data: bytes) -> None:
        self._stdin.put(pb.StepIOChunk(data=data))

    def close_stdin(self) -> None:
        self._stdin.put(pb.StepIOChunk(stdin_eof=True))

    def abort(self, exit_code: int) -> None:
        """Client-side liveness fallback: end the session when no
        supervisor will ever stream (the job died before dispatch, a
        stale cancel landed, the node vanished pre-connect).  No-op if
        the stream already finished."""
        if self.exited.is_set():
            return
        self.exit_code = exit_code
        self.exited.set()
        self._out.put(None)
        self._stdin.put(None)

    # -- handler side --

    def _push_output(self, chunk) -> None:
        if chunk.exited:
            if self.exited.is_set():
                return  # already aborted client-side
            self.exit_code = chunk.exit_code
            self.bytes_at_exit = self.bytes_received
            self.exited.set()
            self._out.put(None)
            self._stdin.put(None)  # unblock the response generator
        elif chunk.data:
            self.bytes_received += len(chunk.data)
            self._out.put((chunk.stream or "out", chunk.data))

    def _stdin_iter(self):
        while True:
            item = self._stdin.get()
            if item is None:
                return
            yield item


class CforedServer:
    """Hosts CraneFored; hands incoming supervisor streams to waiters.

    ``expect(job_id, step_id)`` registers interest and returns the
    session (created on first use from either side, so the supervisor
    connecting before/after expect() both work).

    ``secret`` is the hub-wide stream credential: it exists before any
    submission (no job-id ordering problem), every spec this client
    submits carries it (``interactive_token``), and the first chunk of
    every incoming stream must present it — without it, any peer that
    can reach the port could claim a session (read the user's stdin,
    forge the exit status).  Empty = open hub (tests, trusted loopback).
    """

    def __init__(self, secret: str | None = None, tls=None,
                 x_display: str | None = None):
        import secrets as _secrets
        self.secret = (_secrets.token_urlsafe(16) if secret is None
                       else secret)
        # where X11 relay streams land (reference SetupX11forwarding_
        # counterpart): the USER'S display — $DISPLAY by default
        self.x_display = x_display
        # utils.pki.TlsConfig: the hub serves TLS and supervisors dial
        # back with the cluster CA (their side rides the craned's
        # config) — the stream secret stops being sniffable in flight
        self.tls = tls
        self._sessions: dict[tuple[int, int], StepIOSession] = {}
        self._lock = threading.Lock()
        self._server: grpc.Server | None = None
        self.address = ""

    def _session(self, job_id: int, step_id: int) -> StepIOSession:
        with self._lock:
            key = (job_id, step_id)
            sess = self._sessions.get(key)
            if sess is None:
                sess = self._sessions[key] = StepIOSession(job_id,
                                                           step_id)
            return sess

    expect = _session

    def StepIO(self, request_iterator, context):
        """Bidi handler: a thread drains the supervisor's output chunks
        into the session; this generator yields stdin chunks back."""
        import grpc as _grpc
        first = next(request_iterator, None)
        if first is None:
            return
        if self.secret and first.token != self.secret:
            context.abort(_grpc.StatusCode.PERMISSION_DENIED,
                          "bad stream token")
        if first.stream == "x11":
            # a whole-stream X11 relay channel (one per X connection
            # the job opened against the forwarded DISPLAY)
            yield from self._x11_stream(request_iterator, context)
            return
        sess = self._session(first.job_id, first.step_id)
        sess._push_output(first)

        def drain():
            try:
                for chunk in request_iterator:
                    sess._push_output(chunk)
            except grpc.RpcError:
                pass
            finally:
                if not sess.exited.is_set():
                    # supervisor died mid-stream: release both sides
                    sess.exit_code = sess.exit_code or 255
                    sess.exited.set()
                    sess._out.put(None)
                    sess._stdin.put(None)

        threading.Thread(target=drain, daemon=True).start()
        yield from sess._stdin_iter()

    def _connect_x_display(self):
        """Socket to the user's X server from $DISPLAY grammar:
        ':N[.s]' / 'unix:N' -> /tmp/.X11-unix/XN; 'host:N' ->
        TCP host:6000+N."""
        import os
        import socket as _socket
        display = self.x_display or os.environ.get("DISPLAY", "")
        if not display:
            raise OSError("no DISPLAY to relay X11 to")
        host, _, num = display.rpartition(":")
        number = int(num.split(".")[0] or 0)
        if host in ("", "unix"):
            s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            s.connect(f"/tmp/.X11-unix/X{number}")
            return s
        return _socket.create_connection((host, 6000 + number),
                                         timeout=10)

    def _x11_stream(self, request_iterator, context):
        """Relay one X connection: incoming chunks -> X server; X
        server bytes -> response chunks.  Ends when either side
        closes."""
        try:
            xsock = self._connect_x_display()
        except OSError as exc:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"X display unavailable: {exc}")
            return

        def pump_to_x():
            try:
                for chunk in request_iterator:
                    if chunk.data:
                        xsock.sendall(chunk.data)
            except (grpc.RpcError, OSError):
                pass
            finally:
                try:
                    xsock.shutdown(2)
                except OSError:
                    pass

        threading.Thread(target=pump_to_x, daemon=True).start()
        try:
            while data := xsock.recv(65536):
                yield pb.StepIOChunk(data=data)
        except OSError:
            pass
        finally:
            try:
                xsock.close()
            except OSError:
                pass
        yield pb.StepIOChunk(exited=True)

    def start(self, address: str | None = None,
              host_for_clients: str = "127.0.0.1") -> str:
        """Bind and advertise.  When ``host_for_clients`` is not
        loopback the listen socket must be reachable on that interface,
        so the bind follows it (0.0.0.0); plain loopback stays bound to
        loopback."""
        if address is None:
            address = ("127.0.0.1:0"
                       if host_for_clients in ("127.0.0.1", "localhost")
                       else "0.0.0.0:0")
        handler = grpc.stream_stream_rpc_method_handler(
            self.StepIO,
            request_deserializer=pb.StepIOChunk.FromString,
            response_serializer=pb.StepIOChunk.SerializeToString)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(
                CFORED_SERVICE, {"StepIO": handler}),))
        if self.tls is not None:
            from cranesched_tpu.utils.pki import server_credentials
            port = self._server.add_secure_port(
                address, server_credentials(self.tls))
        else:
            port = self._server.add_insecure_port(address)
        self._server.start()
        # tls://<identity>@ marks the advertised address so craneds
        # know the supervisor must dial back with the cluster CA AND
        # can pin the hub's issued cert name — without the pin, any
        # cluster-issued cert validates as the hub on loopback hosts
        # (every cert carries localhost SANs for single-host setups)
        scheme = ""
        if self.tls is not None:
            from cranesched_tpu.utils.pki import cert_identity
            ident = cert_identity(self.tls.cert) if self.tls.cert else ""
            scheme = f"tls://{ident}@" if ident else "tls://"
        self.address = f"{scheme}{host_for_clients}:{port}"
        return self.address

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
