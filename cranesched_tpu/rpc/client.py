"""Client stub for the CraneCtld service (hand-glued; used by the CLI
and by node daemons).

:class:`HaCtldClient` wraps one :class:`CtldClient` per configured ctld
address and retries leader-affine: a call that fails with UNAVAILABLE
(endpoint dead) or FAILED_PRECONDITION (a standby's not-leader refusal)
rotates to the next address, so cbatch/cqueue keep working across a
failover without the caller knowing which ctld currently leads.
"""

from __future__ import annotations

import grpc

from cranesched_tpu.rpc import crane_pb2 as pb
from cranesched_tpu.rpc.consts import SERVICE
from cranesched_tpu.rpc.stub import GrpcStub


class StreamResult:
    """Out-parameter for streaming queries: did the server truncate?"""

    truncated = False


class CtldClient:
    def __init__(self, address: str, timeout: float = 30.0,
                 token: str = "", tls=None):
        self.address = address
        self.timeout = timeout
        self._stub = GrpcStub(address, SERVICE, timeout, token=token,
                              tls=tls)
        # kept for tests that introspect the channel
        self._channel = self._stub._channel

    def close(self) -> None:
        self._stub.close()

    def _call(self, name, request, reply_cls):
        return self._stub.call(name, request, reply_cls)

    # ---- external ----

    def submit(self, spec: pb.JobSpec, forwarded: bool = False,
               forwarded_at: float = 0.0,
               forwarded_from: str = "") -> pb.SubmitJobReply:
        return self._call(
            "SubmitBatchJob",
            pb.SubmitJobRequest(spec=spec, forwarded=forwarded,
                                forwarded_at=forwarded_at,
                                forwarded_from=forwarded_from),
            pb.SubmitJobReply)

    def submit_many(self, specs) -> pb.SubmitJobsReply:
        return self._call("SubmitBatchJobs",
                          pb.SubmitJobsRequest(specs=list(specs)),
                          pb.SubmitJobsReply)

    def cancel(self, job_id: int) -> pb.OkReply:
        return self._call("CancelJob", pb.JobIdRequest(job_id=job_id),
                          pb.OkReply)

    def hold(self, job_id: int, held: bool = True) -> pb.OkReply:
        return self._call("HoldJob",
                          pb.HoldRequest(job_id=job_id, held=held),
                          pb.OkReply)

    def modify_job(self, job_id: int, time_limit: float | None = None,
                   priority: int | None = None,
                   partition: str | None = None) -> pb.OkReply:
        req = pb.ModifyJobRequest(job_id=job_id)
        if time_limit is not None:
            req.time_limit = time_limit
        if priority is not None:
            req.priority = priority
        if partition is not None:
            req.partition = partition
        return self._call("ModifyJob", req, pb.OkReply)

    def suspend(self, job_id: int) -> pb.OkReply:
        return self._call("SuspendJob", pb.JobIdRequest(job_id=job_id),
                          pb.OkReply)

    def resume(self, job_id: int) -> pb.OkReply:
        return self._call("ResumeJob", pb.JobIdRequest(job_id=job_id),
                          pb.OkReply)

    def query_jobs(self, job_ids=(), user: str = "", partition: str = "",
                   include_history: bool = False, limit: int = 0,
                   after_job_id: int = 0,
                   max_staleness: float = 0.0) -> pb.QueryJobsReply:
        return self._call(
            "QueryJobsInfo",
            pb.QueryJobsRequest(job_ids=list(job_ids), user=user,
                                partition=partition,
                                include_history=include_history,
                                limit=limit,
                                after_job_id=after_job_id,
                                max_staleness=max_staleness),
            pb.QueryJobsReply)

    def query_jobs_stream(self, job_ids=(), user: str = "",
                          partition: str = "",
                          include_history: bool = False,
                          limit: int = 0, after_job_id: int = 0,
                          result=None, max_staleness: float = 0.0):
        """Yield JobInfo messages from the server-streaming query
        (chunked on the wire; flattened here).  Pass a
        ``StreamResult`` as ``result`` to learn whether the server
        truncated (more rows exist past the last yielded id)."""
        request = pb.QueryJobsRequest(
            job_ids=list(job_ids), user=user, partition=partition,
            include_history=include_history, limit=limit,
            after_job_id=after_job_id, max_staleness=max_staleness)
        for reply in self._stub.call_stream("QueryJobsStream", request,
                                            pb.QueryJobsReply):
            if reply.truncated and result is not None:
                result.truncated = True
            yield from reply.jobs

    def query_cluster(self, max_staleness: float = 0.0
                      ) -> pb.QueryClusterReply:
        return self._call(
            "QueryClusterInfo",
            pb.QueryClusterRequest(max_staleness=max_staleness),
            pb.QueryClusterReply)

    def create_reservation(self, name, partition, node_names, start_time,
                           end_time, allowed_accounts=(),
                           denied_accounts=()) -> pb.OkReply:
        return self._call(
            "CreateReservation",
            pb.CreateReservationRequest(
                name=name, partition=partition,
                node_names=list(node_names), start_time=start_time,
                end_time=end_time,
                allowed_accounts=list(allowed_accounts),
                denied_accounts=list(denied_accounts)),
            pb.OkReply)

    def delete_reservation(self, name: str) -> pb.OkReply:
        return self._call("DeleteReservation", pb.NameRequest(name=name),
                          pb.OkReply)

    def modify_node(self, name: str, action: str) -> pb.OkReply:
        return self._call("ModifyNode",
                          pb.ModifyNodeRequest(name=name, action=action),
                          pb.OkReply)

    def query_stats(self, max_staleness: float = 0.0) -> pb.StatsReply:
        return self._call("QueryStats",
                          pb.StatsRequest(max_staleness=max_staleness),
                          pb.StatsReply)

    def acct_mgr(self, actor: str, action: str,
                 payload: dict | None = None) -> pb.AcctMgrReply:
        import json as _json
        return self._call(
            "AcctMgr",
            pb.AcctMgrRequest(actor=actor, action=action,
                              payload=_json.dumps(payload or {})),
            pb.AcctMgrReply)

    def craned_health(self, node_id: int, healthy: bool,
                      message: str = "") -> pb.OkReply:
        return self._call(
            "CranedHealth",
            pb.CranedHealthRequest(node_id=node_id, healthy=healthy,
                                   message=message),
            pb.OkReply)

    # ---- internal ----

    def craned_register(self, name, total: pb.ResourceSpec,
                        partitions=("default",)
                        ) -> pb.CranedRegisterReply:
        return self._call(
            "CranedRegister",
            pb.CranedRegisterRequest(name=name, total=total,
                                     partitions=list(partitions)),
            pb.CranedRegisterReply)

    def craned_ping(self, node_id: int) -> pb.OkReply:
        return self._call("CranedPing",
                          pb.CranedPingRequest(node_id=node_id),
                          pb.OkReply)

    def step_status_change(self, job_id, status, exit_code, time,
                           node_id: int = -1, incarnation: int = 0,
                           step_id: int | None = None,
                           cpu_seconds: float = 0.0,
                           max_rss_bytes: int = 0,
                           spans=()) -> pb.OkReply:
        req = pb.StepStatusChangeRequest(job_id=job_id, status=status,
                                         exit_code=exit_code, time=time,
                                         node_id=node_id,
                                         incarnation=incarnation,
                                         cpu_seconds=cpu_seconds,
                                         max_rss_bytes=max_rss_bytes)
        if step_id is not None:
            req.step_id = step_id
        # craned-side lifecycle spans (obs/jobtrace.py ship-back)
        for s in spans or ():
            req.spans.append(pb.JobSpan(
                edge=s["edge"], seq=int(s["seq"]), time=float(s["t"]),
                node_id=int(s.get("node_id", -1)),
                skew=float(s.get("skew", 0.0))))
        return self._call("StepStatusChange", req, pb.OkReply)

    # ---- steps within an allocation ----

    def submit_step(self, job_id: int,
                    spec: pb.StepSpec) -> pb.SubmitStepReply:
        return self._call("SubmitStep",
                          pb.SubmitStepRequest(job_id=job_id, spec=spec),
                          pb.SubmitStepReply)

    def query_steps(self, job_id: int) -> pb.QueryStepsReply:
        return self._call("QueryStepsInfo",
                          pb.QueryStepsRequest(job_id=job_id),
                          pb.QueryStepsReply)

    def cancel_step(self, job_id: int, step_id: int) -> pb.OkReply:
        return self._call("CancelStep",
                          pb.JobIdRequest(job_id=job_id, step_id=step_id),
                          pb.OkReply)

    def free_allocation(self, job_id: int) -> pb.OkReply:
        return self._call("FreeAllocation",
                          pb.JobIdRequest(job_id=job_id), pb.OkReply)

    def issue_token(self, user: str) -> pb.TokenReply:
        return self._call("IssueToken", pb.IssueTokenRequest(user=user),
                          pb.TokenReply)

    def revoke_token(self, user: str) -> pb.OkReply:
        return self._call("RevokeToken",
                          pb.IssueTokenRequest(user=user), pb.OkReply)

    def tick(self, now: float) -> pb.TickReply:
        return self._call("Tick", pb.TickRequest(now=now), pb.TickReply)

    # ---- HA + summary ----

    def requeue(self, job_id: int) -> pb.OkReply:
        return self._call("RequeueJob", pb.JobIdRequest(job_id=job_id),
                          pb.OkReply)

    def query_job_summary(self, user: str = "", partition: str = "",
                          job_id: int = 0, max_staleness: float = 0.0
                          ) -> pb.QueryJobSummaryReply:
        """job_id != 0 additionally returns that job's timeline as
        JSON (standby-servable, like the summary itself)."""
        return self._call(
            "QueryJobSummary",
            pb.QueryJobSummaryRequest(user=user, partition=partition,
                                      job_id=job_id,
                                      max_staleness=max_staleness),
            pb.QueryJobSummaryReply)

    def ha_status(self) -> pb.HaStatusReply:
        return self._call("HaStatus", pb.HaStatusRequest(),
                          pb.HaStatusReply)

    def ha_fetch_snapshot(self) -> pb.HaSnapshotReply:
        return self._call("HaFetchSnapshot", pb.HaSnapshotRequest(),
                          pb.HaSnapshotReply)

    def ha_fetch_wal(self, after_seq: int, limit: int = 0,
                     after_event_seq: int = 0) -> pb.HaFetchReply:
        return self._call(
            "HaFetchWal",
            pb.HaFetchRequest(after_seq=after_seq, limit=limit,
                              after_event_seq=after_event_seq),
            pb.HaFetchReply)

    def query_events(self, severity: str = "", since: float = 0.0,
                     after_seq: int = 0, limit: int = 0,
                     type: str = "",
                     max_staleness: float = 0.0) -> pb.QueryEventsReply:
        """Structured cluster-event ring (standby-servable)."""
        return self._call(
            "QueryEvents",
            pb.QueryEventsRequest(severity=severity, since=since,
                                  after_seq=after_seq, limit=limit,
                                  type=type,
                                  max_staleness=max_staleness),
            pb.QueryEventsReply)

    def capture_profile(self, cycles: int = 1,
                        dir: str = "") -> pb.CaptureProfileReply:
        """Arm a jax.profiler window over the next N cycles."""
        return self._call(
            "CaptureProfile",
            pb.CaptureProfileRequest(cycles=cycles, dir=dir),
            pb.CaptureProfileReply)

    # ---- federation (fed/) ----

    def query_shard_map(self) -> pb.QueryShardMapReply:
        return self._call("QueryShardMap", pb.QueryShardMapRequest(),
                          pb.QueryShardMapReply)

    def lease_nodes(self, lease_id: str, partition: str, node_num: int,
                    res: pb.ResourceSpec | None = None,
                    ttl: float = 0.0) -> pb.LeaseNodesReply:
        req = pb.LeaseNodesRequest(lease_id=lease_id, partition=partition,
                                   node_num=node_num, ttl=ttl)
        if res is not None:
            req.res.CopyFrom(res)
        return self._call("LeaseNodes", req, pb.LeaseNodesReply)

    def confirm_gang(self, lease_id: str, gang_id: str,
                     spec: pb.JobSpec, node_names=(),
                     fencing_epoch: int = 0) -> pb.ConfirmGangReply:
        return self._call(
            "ConfirmGang",
            pb.ConfirmGangRequest(lease_id=lease_id, gang_id=gang_id,
                                  spec=spec,
                                  node_names=list(node_names),
                                  fencing_epoch=fencing_epoch),
            pb.ConfirmGangReply)

    def release_lease(self, lease_id: str,
                      fencing_epoch: int = 0) -> pb.OkReply:
        return self._call(
            "ReleaseLease",
            pb.ReleaseLeaseRequest(lease_id=lease_id,
                                   fencing_epoch=fencing_epoch),
            pb.OkReply)

    def fetch_usage(self, shard: str = "") -> pb.FetchUsageReply:
        """This shard's usage-gossip summary (cluster-wide
        accounting).  ``shard`` names the PULLING shard — serving the
        fetch is confirmed delivery to it, which is what releases the
        server's publish-slack throttle; leave it empty for a CLI
        query that should ack nobody."""
        return self._call("FetchUsage",
                          pb.FetchUsageRequest(shard=shard),
                          pb.FetchUsageReply)

    def migrate_partition(self, partition: str, dest_shard: str,
                          phase: str = "", payload: str = "",
                          mid: str = "") -> pb.MigratePartitionReply:
        """Live partition migration: ``phase=""`` drives the whole
        handoff (dial the source shard), ``phase="import"`` ships an
        exported payload to the destination (shard-to-shard), and
        ``phase="query"`` asks the destination whether it durably
        adopted handoff ``mid`` (the source's resolution path)."""
        return self._call(
            "MigratePartition",
            pb.MigratePartitionRequest(partition=partition,
                                       dest_shard=dest_shard,
                                       phase=phase, payload=payload,
                                       mid=mid),
            pb.MigratePartitionReply)


# gRPC codes that mean "try the next ctld": the endpoint is down/
# unreachable, or it answered but refused as a standby
_ROTATE_CODES = (grpc.StatusCode.UNAVAILABLE,
                 grpc.StatusCode.FAILED_PRECONDITION,
                 grpc.StatusCode.DEADLINE_EXCEEDED)


class HaCtldClient(CtldClient):
    """Leader-finding client over a list of ctld addresses.

    Shares :class:`CtldClient`'s full method surface — only ``_call``
    (and the stream dial) differ: the sticky index remembers the last
    address that answered as leader, and every failure in
    ``_ROTATE_CODES`` advances it.  One full rotation without an answer
    re-raises the last error.
    """

    def __init__(self, addresses, timeout: float = 30.0,
                 token: str = "", tls=None):
        if isinstance(addresses, str):
            addresses = [a.strip() for a in addresses.split(",")
                         if a.strip()]
        if not addresses:
            raise ValueError("HaCtldClient needs at least one address")
        self.addresses = list(addresses)
        self.timeout = timeout
        self._token = token
        self._tls = tls
        self._idx = 0
        self._clients: dict[int, CtldClient] = {}
        # CtldClient API compat (tests introspect .address/._stub)
        self.address = self.addresses[0]
        # federation routing: partition -> shard leader address,
        # learned from SubmitJobReply redirect hints (or pre-seeded by
        # learn_shard_map); addresses here may lie OUTSIDE the HA
        # rotation list, so their clients live in their own cache
        self._shard_routes: dict[str, str] = {}
        self._route_clients: dict[str, CtldClient] = {}
        # the shard-map epoch the routes were learned at: a reply
        # stamped with a NEWER epoch means a live partition migration
        # flipped the map — re-learn instead of redirect-bouncing
        self._map_epoch = 0

    def _at(self, idx: int) -> CtldClient:
        cli = self._clients.get(idx)
        if cli is None:
            cli = CtldClient(self.addresses[idx], timeout=self.timeout,
                             token=self._token, tls=self._tls)
            self._clients[idx] = cli
        return cli

    @property
    def _stub(self):
        return self._at(self._idx)._stub

    def close(self) -> None:
        for cli in self._clients.values():
            cli.close()
        self._clients.clear()
        for cli in self._route_clients.values():
            cli.close()
        self._route_clients.clear()

    # -- federation: shard-aware submit routing --

    def learn_shard_map(self) -> int:
        """Pre-seed partition routes from any reachable ctld's
        QueryShardMap.  Returns the number of partitions learned (0 on
        a non-federated cluster)."""
        try:
            reply = self.query_shard_map()
        except grpc.RpcError:
            return 0
        n = 0
        self._shard_routes.clear()
        self._map_epoch = reply.map_epoch
        for shard in reply.shards:
            if not shard.address:
                continue
            for part in shard.partitions:
                self._shard_routes[part] = shard.address
                n += 1
        return n

    def _route(self, address: str) -> CtldClient:
        cli = self._route_clients.get(address)
        if cli is None:
            cli = CtldClient(address, timeout=self.timeout,
                             token=self._token, tls=self._tls)
            self._route_clients[address] = cli
        return cli

    def submit(self, spec: pb.JobSpec, forwarded: bool = False,
               forwarded_at: float = 0.0,
               forwarded_from: str = "") -> pb.SubmitJobReply:
        """Route the submit to the partition's owning shard when the
        route is known; otherwise fall back to the HA rotation (the
        server forwards misrouted submits and answers with a redirect
        hint, which teaches us the route for next time)."""
        addr = self._shard_routes.get(spec.partition)
        if addr:
            try:
                reply = self._route(addr).submit(
                    spec, forwarded=forwarded,
                    forwarded_at=forwarded_at,
                    forwarded_from=forwarded_from)
                if reply.map_epoch > self._map_epoch:
                    self.learn_shard_map()
                if reply.redirect_address:
                    self._shard_routes[spec.partition] = \
                        reply.redirect_address
                return reply
            except grpc.RpcError as e:
                if e.code() not in _ROTATE_CODES:
                    raise
                # the learned route went stale — drop it and fall back
                self._shard_routes.pop(spec.partition, None)
                cli = self._route_clients.pop(addr, None)
                if cli is not None:
                    try:
                        cli.close()
                    except Exception:
                        pass
        reply = super().submit(spec, forwarded=forwarded,
                               forwarded_at=forwarded_at,
                               forwarded_from=forwarded_from)
        if reply.redirect_address:
            self._shard_routes[spec.partition] = reply.redirect_address
        if reply.map_epoch > self._map_epoch:
            # a migration flipped the map since we learned it: refresh
            # every route in one query rather than paying a redirect
            # bounce per moved partition
            self.learn_shard_map()
        return reply

    def _call(self, name, request, reply_cls):
        last_err = None
        for attempt in range(len(self.addresses)):
            idx = (self._idx + attempt) % len(self.addresses)
            try:
                reply = self._at(idx)._call(name, request, reply_cls)
            except grpc.RpcError as e:
                if e.code() not in _ROTATE_CODES:
                    raise
                last_err = e
                # drop the dead channel so a later retry re-dials
                cli = self._clients.pop(idx, None)
                if cli is not None:
                    try:
                        cli.close()
                    except Exception:
                        pass
                continue
            self._idx = idx
            self.address = self.addresses[idx]
            return reply
        raise last_err

    def query_jobs_stream(self, *args, **kwargs):
        """The streaming query dials ``self._stub`` directly, so it
        needs its own rotation: a stream that dies BEFORE yielding a
        row advances to the next address (cqueue right after a
        failover); one that dies mid-stream re-raises — the caller
        must not see a silently restarted (duplicated) listing."""
        last_err = None
        for attempt in range(len(self.addresses)):
            idx = (self._idx + attempt) % len(self.addresses)
            yielded = False
            try:
                for item in self._at(idx).query_jobs_stream(*args,
                                                            **kwargs):
                    yielded = True
                    yield item
            except grpc.RpcError as e:
                if yielded or e.code() not in _ROTATE_CODES:
                    raise
                last_err = e
                cli = self._clients.pop(idx, None)
                if cli is not None:
                    try:
                        cli.close()
                    except Exception:
                        pass
                continue
            self._idx = idx
            self.address = self.addresses[idx]
            return
        raise last_err


def make_client(addresses, timeout: float = 30.0, token: str = "",
                tls=None) -> CtldClient:
    """One address -> plain client; a comma-separated list (or an
    actual list) -> failover-aware :class:`HaCtldClient`."""
    if isinstance(addresses, str):
        parts = [a.strip() for a in addresses.split(",") if a.strip()]
    else:
        parts = list(addresses)
    if len(parts) == 1:
        return CtldClient(parts[0], timeout=timeout, token=token,
                          tls=tls)
    return HaCtldClient(parts, timeout=timeout, token=token, tls=tls)
