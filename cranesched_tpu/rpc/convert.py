"""proto <-> domain conversions for the ctld service."""

from __future__ import annotations

from cranesched_tpu.ctld.defs import (
    ArraySpec,
    Dependency,
    DepType,
    Job,
    JobSpec,
    ResourceSpec,
    Step,
    StepSpec,
)
from cranesched_tpu.rpc import crane_pb2 as pb

_DEP_TYPES = {t.value: t for t in DepType}


def res_from_pb(msg) -> ResourceSpec:
    gres = None
    if msg.gres:
        gres = {}
        for key, count in msg.gres.items():
            name, _, typ = key.partition(":")
            gres[(name, typ)] = count
    return ResourceSpec(cpu=msg.cpu or 0.0, mem_bytes=msg.mem_bytes,
                        memsw_bytes=msg.memsw_bytes, gres=gres)


def res_to_pb(res: ResourceSpec) -> pb.ResourceSpec:
    msg = pb.ResourceSpec(cpu=res.cpu, mem_bytes=res.mem_bytes,
                          memsw_bytes=res.memsw_bytes)
    for (name, typ), count in (res.gres or {}).items():
        msg.gres[f"{name}:{typ}"] = count
    return msg


def spec_from_pb(msg) -> JobSpec:
    deps = []
    for d in msg.dependencies:
        dep_type = _DEP_TYPES.get(d.type)
        if dep_type is None:
            raise ValueError(
                f"unknown dependency type {d.type!r} "
                f"(expected one of {sorted(_DEP_TYPES)})")
        deps.append(Dependency(job_id=d.job_id, type=dep_type,
                               delay_seconds=d.delay_seconds))
    deps = tuple(deps)
    array = None
    if msg.HasField("array"):
        array = ArraySpec(start=msg.array.start, end=msg.array.end,
                          stride=msg.array.stride or 1,
                          max_concurrent=msg.array.max_concurrent)
    return JobSpec(
        name=msg.name or "job",
        user=msg.user or "user",
        account=msg.account or "default",
        partition=msg.partition or "default",
        res=res_from_pb(msg.res),
        node_num=msg.node_num or 1,
        task_res=(res_from_pb(msg.task_res)
                  if msg.HasField("task_res") else None),
        ntasks=msg.ntasks or None,
        ntasks_per_node_min=msg.ntasks_per_node_min or 1,
        ntasks_per_node_max=msg.ntasks_per_node_max or 1,
        exclusive=msg.exclusive,
        time_limit=msg.time_limit or 3600,
        qos=msg.qos,
        qos_priority=msg.qos_priority,
        held=msg.held,
        include_nodes=tuple(msg.include_nodes),
        exclude_nodes=tuple(msg.exclude_nodes),
        begin_time=msg.begin_time or None,
        requeue_if_failed=msg.requeue_if_failed,
        dependencies=deps,
        deps_is_or=msg.deps_is_or,
        array=array,
        reservation=msg.reservation,
        script=msg.script,
        output_path=msg.output_path,
        alloc_only=msg.alloc_only,
        interactive_address=msg.interactive_address,
        pty=msg.pty,
        interactive_token=msg.interactive_token,
        container_image=msg.container_image,
        container_mounts=tuple(msg.container_mounts),
        x11=msg.x11,
        x11_cookie=msg.x11_cookie,
        sim_runtime=msg.sim_runtime or None,
        sim_exit_code=msg.sim_exit_code,
    )


def spec_to_pb(spec: JobSpec) -> pb.JobSpec:
    msg = pb.JobSpec(
        name=spec.name, user=spec.user, account=spec.account,
        partition=spec.partition, res=res_to_pb(spec.res),
        node_num=spec.node_num,
        ntasks=spec.ntasks or 0,
        ntasks_per_node_min=spec.ntasks_per_node_min,
        ntasks_per_node_max=spec.ntasks_per_node_max,
        # host-side limits are float seconds; the wire field is uint32
        # (a float here raises TypeError inside a dispatch thread)
        exclusive=spec.exclusive, time_limit=int(spec.time_limit),
        qos=spec.qos, qos_priority=spec.qos_priority, held=spec.held,
        include_nodes=list(spec.include_nodes),
        exclude_nodes=list(spec.exclude_nodes),
        begin_time=spec.begin_time or 0.0,
        requeue_if_failed=spec.requeue_if_failed,
        deps_is_or=spec.deps_is_or,
        reservation=spec.reservation,
        script=spec.script, output_path=spec.output_path,
        alloc_only=spec.alloc_only,
        interactive_address=spec.interactive_address,
        pty=spec.pty,
        interactive_token=spec.interactive_token,
        container_image=spec.container_image,
        container_mounts=list(spec.container_mounts),
        x11=spec.x11,
        x11_cookie=spec.x11_cookie,
        sim_runtime=spec.sim_runtime or 0.0,
        sim_exit_code=spec.sim_exit_code)
    if spec.task_res is not None:
        msg.task_res.CopyFrom(res_to_pb(spec.task_res))
    for dep in spec.dependencies:
        msg.dependencies.add(job_id=dep.job_id, type=dep.type.value,
                             delay_seconds=dep.delay_seconds)
    if spec.array is not None:
        msg.array.CopyFrom(pb.ArraySpec(
            start=spec.array.start, end=spec.array.end,
            stride=spec.array.stride,
            max_concurrent=spec.array.max_concurrent))
    return msg


def step_spec_from_pb(msg) -> StepSpec:
    return StepSpec(
        name=msg.name or "step",
        script=msg.script,
        res=res_from_pb(msg.res) if msg.HasField("res") else None,
        node_num=msg.node_num,
        time_limit=msg.time_limit,
        output_path=msg.output_path,
        interactive_address=msg.interactive_address,
        pty=msg.pty,
        interactive_token=msg.interactive_token,
        container_image=msg.container_image,
        container_mounts=tuple(msg.container_mounts),
        overlap=msg.overlap,
        follow_step=(msg.follow_step
                     if msg.HasField("follow_step") else None),
        x11=msg.x11,
        x11_cookie=msg.x11_cookie,
        sim_runtime=msg.sim_runtime or None,
        sim_exit_code=msg.sim_exit_code,
    )


def step_spec_to_pb(spec: StepSpec) -> pb.StepSpec:
    msg = pb.StepSpec(name=spec.name, script=spec.script,
                      node_num=spec.node_num,
                      time_limit=int(spec.time_limit),
                      output_path=spec.output_path,
                      interactive_address=spec.interactive_address,
                      pty=spec.pty,
                      interactive_token=spec.interactive_token,
                      container_image=spec.container_image,
                      container_mounts=list(spec.container_mounts),
                      overlap=spec.overlap,
                      x11=spec.x11,
                      x11_cookie=spec.x11_cookie,
                      sim_runtime=spec.sim_runtime or 0.0,
                      sim_exit_code=spec.sim_exit_code)
    if spec.follow_step is not None:
        msg.follow_step = spec.follow_step
    if spec.res is not None:
        msg.res.CopyFrom(res_to_pb(spec.res))
    return msg


def _node_name(node_names, n: int) -> str:
    """Archived history can reference nodes that left the topology (or
    a rebuilt cluster whose ids shifted) — render a placeholder, never
    crash the query surface."""
    return node_names.get(n, f"node#{n}")


def step_to_pb(job_id: int, step: Step, node_names) -> pb.StepInfo:
    return pb.StepInfo(
        job_id=job_id,
        step_id=step.step_id,
        name=step.spec.name,
        status=step.status.value,
        exit_code=step.exit_code or 0,
        submit_time=step.submit_time,
        start_time=step.start_time or 0.0,
        end_time=step.end_time or 0.0,
        node_names=[_node_name(node_names, n) for n in step.node_ids],
        cpu_seconds=step.cpu_seconds,
        max_rss_bytes=step.max_rss_bytes,
    )


def job_to_pb(job: Job, node_names) -> pb.JobInfo:
    return pb.JobInfo(
        job_id=job.job_id,
        name=job.spec.name,
        user=job.spec.user,
        account=job.spec.account,
        partition=job.spec.partition,
        status=job.status.value,
        pending_reason=job.pending_reason.value,
        node_names=[_node_name(node_names, n) for n in job.node_ids],
        task_layout=job.task_layout,
        submit_time=job.submit_time,
        start_time=job.start_time or 0.0,
        end_time=job.end_time or 0.0,
        exit_code=job.exit_code or 0,
        requeue_count=job.requeue_count,
        qos=job.qos_name,
        priority=job.priority,
        array_parent_id=job.array_parent_id or 0,
        array_task_id=(job.array_task_id
                       if job.array_task_id is not None else -1),
        cpu_seconds=job.cpu_seconds,
        max_rss_bytes=job.max_rss_bytes,
    )
