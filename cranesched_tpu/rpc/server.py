"""The ctld gRPC service: the reference's CtldGrpcServer, hand-glued.

(reference: src/CraneCtld/RpcService/CtldGrpcServer.cpp — SubmitBatchJob
:691, SubmitBatchJobs :790, the ~60-RPC external surface of
protos/Crane.proto:1401-1683, and the CraneCtldForInternal craned-facing
service :1620.)

The scheduler is single-threaded by design; a coarse lock serializes all
RPC handlers onto it (the reference serializes through per-purpose
lock-free queues drained by its scheduler threads — same effect, more
machinery than a Python control plane needs).

Two clock modes:
* real time: a daemon thread runs schedule_cycle every cycle_interval;
* virtual time (``tick_mode=True``): nothing runs until a ``Tick`` RPC
  supplies ``now`` — deterministic for tests, replays, and simulations.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

import grpc

from cranesched_tpu.craned.sim import SimCluster, SimCraned
from cranesched_tpu.ctld.defs import JobStatus, StepStatus
from cranesched_tpu.ctld.scheduler import JobScheduler
from cranesched_tpu.obs import REGISTRY as _OBS
from cranesched_tpu.rpc import crane_pb2 as pb
from cranesched_tpu.rpc.consts import SERVICE
from cranesched_tpu.rpc.convert import (
    job_to_pb,
    res_from_pb,
    spec_from_pb,
    step_spec_from_pb,
    step_to_pb,
)

_MET_FWD = _OBS.counter(
    "crane_fed_forwards_total",
    "misrouted submits forwarded to the partition's owning shard")
_MET_STALE = _OBS.counter(
    "crane_fed_stale_reads_refused_total",
    "follower reads refused for exceeding the caller's max_staleness")


def _node_state(node) -> str:
    if node.power_state == "POWEREDOFF":
        return "POWEREDOFF"
    if not node.alive:
        return "DOWN"
    if node.drained or node.health_drained:
        return "DRAIN"
    if (node.avail == node.total).all():
        return "IDLE"
    if (node.avail == 0).all():
        return "ALLOC"
    return "MIXED"


class CtldServer:
    """Wraps a JobScheduler (and optionally a simulated node plane)
    behind the CraneCtld service."""

    def __init__(self, scheduler: JobScheduler,
                 sim: SimCluster | None = None,
                 cycle_interval: float = 1.0, tick_mode: bool = False,
                 dispatcher=None, auth=None, tls=None,
                 metrics_port: int | None = None,
                 standby: bool = False, peer_address: str = "",
                 shard_name: str = "", shard_map=None):
        self.scheduler = scheduler
        self.sim = sim
        # real node plane: per-node push stubs (wired into the
        # scheduler's dispatch seam by the caller)
        self.dispatcher = dispatcher
        # AuthManager (ctld/auth.py) or None = open system (the
        # reference's equivalent seam is CheckCertAndUIDAllowed_ on
        # every external RPC, CtldGrpcServer.h:568)
        self.auth = auth
        # utils.pki.TlsConfig or None = plaintext (sims/tests); with
        # require_client_cert set, callers must present a cluster-CA
        # cert — the reference's internal mTLS domain
        # (CtldPublicDefs.h:133-143)
        self.tls = tls
        self.cycle_interval = cycle_interval
        self.tick_mode = tick_mode
        # Prometheus /metrics endpoint: None = off, 0 = ephemeral port
        # (tests); the bound port lands in self.metrics_port after
        # start()
        self.metrics_port = metrics_port
        self._metrics_server = None
        self._lock = threading.Lock()
        self._server: grpc.Server | None = None
        self._cycle_thread: threading.Thread | None = None
        self._usage_thread: threading.Thread | None = None
        self._stop = threading.Event()
        # event-driven cycle wakeup (the reference's
        # m_task_scheduler_thread_ condition variable): submits, status
        # changes, and node/reservation events set this so the loop
        # never sleeps through work, and an idle cluster can sleep past
        # the base tick (SchedulerConfig.cycle_idle_sleep)
        self._cycle_kick = threading.Event()
        scheduler.cycle_kick = self._cycle_kick.set
        # HA: a standby serves the read surface from its shadow state
        # and aborts mutations with FAILED_PRECONDITION so failover-
        # aware clients (HaCtldClient, craned's address rotation) move
        # on; promote_to_leader() flips the role and the cycle-loop gate
        self.ha_role = "standby" if standby else "leader"
        self.ha_peer = peer_address  # the other ctld (redirect hint)
        self.ha_follower = None      # set by ctld_main on a standby
        self.failovers = 0
        # federation (fed/): this ctld's shard identity plus the static
        # partition -> shard routing table.  A populated map turns on
        # misrouted-submit forwarding and reply shard stamping; None
        # keeps the single-controller behavior bit-for-bit.
        self.shard_name = shard_name or getattr(scheduler,
                                                "shard_name", "")
        self.shard_map = shard_map
        scheduler.shard_name = self.shard_name
        self._fwd_clients: dict = {}  # address -> CtldClient (forwards)

    # ---- authentication helpers ----

    def _ident(self, context) -> str | None:
        """Authenticated identity of the caller, or None.  With auth
        disabled returns the sentinel "" meaning 'trust the claim'."""
        if self.auth is None:
            return ""
        return self.auth.identity(context.invocation_metadata())

    def _deny_job_mutation(self, ident, job_id) -> str:
        """Owner-or-admin check for job mutations; returns the denial
        message or ''."""
        if self.auth is None:
            return ""
        if ident is None:
            return "authentication required"
        job = self.scheduler.job_info(job_id)
        if job is None:
            return ""  # fall through: handler reports no-such-job
        if not self.auth.may_act_on_job(ident, job):
            return f"permission denied (job belongs to {job.spec.user})"
        return ""

    def _require_authenticated(self, ident, context) -> None:
        """Read surface: any authenticated identity suffices, but an
        anonymous caller must not enumerate jobs/steps/topology
        (the information-disclosure half of the cert check).  Aborts
        the RPC — queries have no error field to carry a denial."""
        if self.auth is not None and ident is None:
            context.abort(grpc.StatusCode.UNAUTHENTICATED,
                          "authentication required")

    def _deny_admin(self, ident) -> str:
        if self.auth is None:
            return ""
        if ident is None:
            return "authentication required"
        if not self.auth.is_admin(ident):
            return "permission denied (admin required)"
        return ""

    def _deny_internal(self, ident, node_id: int | None = None,
                       node_name: str | None = None) -> str:
        """Craned-internal surface: a craned identity or an admin.

        A per-node token (identity ``@craned/<name>``, ADVICE r3) is
        additionally bound to the node it names: an RPC that claims a
        ``node_id``/name is denied when the token belongs to a different
        node, so one compromised craned cannot forge reports for the
        rest of the plane.  The shared ``@craned`` cluster secret (and
        admins) keep plane-wide access for sim/small deployments."""
        if self.auth is None:
            return ""
        from cranesched_tpu.ctld.auth import craned_node_of
        bound = craned_node_of(ident)
        if bound is None:
            if self.auth.is_admin(ident):
                return ""
            return "craned authentication required"
        if bound == "*":
            return ""
        if node_name is None and node_id is not None:
            node = self.scheduler.meta.nodes.get(node_id)
            node_name = node.name if node is not None else None
        if node_name is None:
            # fail CLOSED: an unresolvable node claim (unknown id, or
            # the node_id=-1 whole-job report form only the sim plane
            # uses) would otherwise let a node-bound token act outside
            # its binding — exactly the impersonation it exists to stop
            return (f"token is bound to node {bound!r} but the request "
                    "names no resolvable node")
        if node_name != bound:
            return (f"token is bound to node {bound!r}, not "
                    f"{node_name!r}")
        return ""

    # ---- handlers (each is unary-unary; the lock serializes) ----

    def _check_submit_identity(self, ident, spec):
        """The submit-side uid check (reference: the cert identity must
        match the claimed uid): the spec's user must be the caller
        unless the caller is an admin."""
        if self.auth is None:
            return ""
        if ident is None:
            return "authentication required"
        if spec.user != ident and not self.auth.is_admin(ident):
            return (f"permission denied (authenticated as {ident}, "
                    f"spec claims {spec.user})")
        return ""

    def _trusted_forward(self, request) -> bool:
        """True for a forwarded submit arriving from a known peer shard
        of this federation.  The identity check already ran at the
        ingress shard — the shard that forwarded it — and the
        shard-to-shard hop carries no user credential, so re-running it
        here would deny every forwarded submit under auth (and
        double-count the denial metrics without it).  Trust is scoped:
        a request claiming ``forwarded`` outside a federation, or
        naming an unknown shard, still gets the full check."""
        if self.shard_map is None or not request.forwarded:
            return False
        peer = request.forwarded_from
        return bool(peer) and peer != self.shard_name \
            and self.shard_map.spec(peer) is not None

    def _fed_owner(self, partition: str):
        """(owner shard, leader address) when ``partition`` belongs to
        a DIFFERENT shard of the federation, else None — local
        partitions and unknown ones (the scheduler's own diagnostics
        handle those) take the normal path."""
        if self.shard_map is None:
            return None
        owner = self.shard_map.shard_for_partition(partition)
        if not owner or owner == self.shard_name:
            return None
        spec = self.shard_map.spec(owner)
        return owner, (spec.address if spec is not None else "")

    def _map_epoch(self) -> int:
        """The shard-map epoch this server currently routes by; stamped
        on submit/shard-map replies so clients detect a live partition
        migration and re-learn routes instead of redirect-bouncing on a
        stale map."""
        return self.shard_map.epoch if self.shard_map is not None else 0

    def _fed_client(self, address: str):
        cli = self._fwd_clients.get(address)
        if cli is None:
            from cranesched_tpu.rpc.client import CtldClient
            cli = CtldClient(address, tls=self.tls)
            self._fwd_clients[address] = cli
        return cli

    def _query_dest_import(self, address: str, mid: str,
                           attempts: int = 3
                           ) -> tuple[bool, int] | None:
        """Ask the dest whether it durably adopted handoff ``mid``
        (``phase="query"`` -> has_import).  Returns (adopted, jobs) on
        an answer, None when the dest stays unreachable — the ONLY
        outcome that may leave the begin unresolved; never guess."""
        for i in range(max(attempts, 1)):
            try:
                r = self._fed_client(address).migrate_partition(
                    "", "", phase="query", mid=mid)
                if r.ok:
                    return bool(r.adopted), int(r.jobs_moved)
            except Exception:
                pass
            if i + 1 < attempts:
                time.sleep(0.2)
        return None

    def _forward_submit(self, spec_pb, partition: str, owner: str,
                        address: str, already_forwarded: bool):
        """One-hop forward of a misrouted submit to the owning shard.
        The reply always carries the owner's address as a redirect hint
        so shard-aware clients (HaCtldClient) learn the route and stop
        paying the extra hop.  An ``already_forwarded`` request is never
        re-forwarded: two shards with skewed maps redirect-bounce the
        client instead of building a forwarding loop."""
        if already_forwarded or not address:
            return pb.SubmitJobReply(
                job_id=0, shard=self.shard_name,
                redirect_address=address,
                map_epoch=self._map_epoch(),
                error=f"partition {partition!r} belongs to shard "
                      f"{owner!r}")
        try:
            # trace context rides the forward: the owner stamps a
            # fed_forwarded span at (when the hop left, from which
            # shard) so the job's waterfall shows the boundary crossing
            reply = self._fed_client(address).submit(
                spec_pb, forwarded=True, forwarded_at=self._now(),
                forwarded_from=self.shard_name)
        except grpc.RpcError as exc:
            # drop the cached channel: the next misroute redials
            cli = self._fwd_clients.pop(address, None)
            if cli is not None:
                try:
                    cli.close()
                except Exception:
                    pass
            return pb.SubmitJobReply(
                job_id=0, shard=self.shard_name,
                redirect_address=address,
                map_epoch=self._map_epoch(),
                error=f"forward to shard {owner!r} failed: "
                      f"{exc.code().name}")
        self.scheduler.events.emit(
            "fed_forward", "info", time=self._now(),
            job_id=reply.job_id,
            detail=f"partition={partition} -> shard={owner}")
        _MET_FWD.inc()
        return pb.SubmitJobReply(job_id=reply.job_id, error=reply.error,
                                 shard=owner, redirect_address=address,
                                 map_epoch=self._map_epoch())

    def SubmitBatchJob(self, request, context):
        try:
            spec = spec_from_pb(request.spec)
        except ValueError as exc:
            return pb.SubmitJobReply(job_id=0, error=str(exc))
        # the identity check runs exactly once, at the INGRESS shard: a
        # trusted forward was already checked where the client connected
        if not self._trusted_forward(request):
            deny = self._check_submit_identity(self._ident(context),
                                               spec)
            if deny:
                return pb.SubmitJobReply(job_id=0, error=deny)
        owner = self._fed_owner(spec.partition)
        if owner is not None:
            return self._forward_submit(request.spec, spec.partition,
                                        *owner, request.forwarded)
        now = self._now()
        with self._lock:
            job_id = self.scheduler.submit(spec, now=now)
            if (request.forwarded and job_id
                    and self.scheduler.jobtrace is not None):
                # span the shard hop on the fresh (job_id, 0) timeline:
                # t = when the forward LEFT the misrouted shard, so the
                # submit->fed_forwarded segment shows the hop latency
                # (clocks are the federation's, skew rides as detail)
                t_fwd = request.forwarded_at or now
                self.scheduler.jobtrace.stamp(
                    job_id, 0, "fed_forwarded", t_fwd,
                    skew=round(now - t_fwd, 6))
        return pb.SubmitJobReply(
            job_id=job_id, error="" if job_id else "rejected",
            shard=self.shard_name, map_epoch=self._map_epoch())

    def SubmitBatchJobs(self, request, context):
        now = self._now()
        ident = self._ident(context)
        replies: list = [None] * len(request.specs)
        local = []
        # parse + route OUTSIDE the lock: forwarding a misrouted spec
        # is an RPC and must not stall the local scheduler
        for i, spec_pb in enumerate(request.specs):
            try:
                spec = spec_from_pb(spec_pb)
            except ValueError as exc:
                replies[i] = pb.SubmitJobReply(job_id=0, error=str(exc))
                continue
            deny = self._check_submit_identity(ident, spec)
            if deny:
                replies[i] = pb.SubmitJobReply(job_id=0, error=deny)
                continue
            owner = self._fed_owner(spec.partition)
            if owner is not None:
                replies[i] = self._forward_submit(
                    spec_pb, spec.partition, *owner, False)
                continue
            local.append((i, spec))
        # chunked insert: batch submit is not atomic (every spec gets
        # its own reply), so release the lock between chunks — a
        # whole-batch hold kept readers waiting for the full insert
        # (~75ms for 250 specs) and set the query-plane p99
        chunk = 32
        for start in range(0, len(local), chunk):
            with self._lock:
                for i, spec in local[start:start + chunk]:
                    job_id = self.scheduler.submit(spec, now=now)
                    replies[i] = pb.SubmitJobReply(
                        job_id=job_id,
                        error="" if job_id else "rejected",
                        shard=self.shard_name)
        return pb.SubmitJobsReply(replies=replies)

    def CancelJob(self, request, context):
        with self._lock:
            deny = self._deny_job_mutation(self._ident(context),
                                           request.job_id)
            if deny:
                return pb.OkReply(ok=False, error=deny)
            ok = self.scheduler.cancel(request.job_id, now=self._now())
        return pb.OkReply(ok=ok, error="" if ok else "no such job")

    def HoldJob(self, request, context):
        with self._lock:
            deny = self._deny_job_mutation(self._ident(context),
                                           request.job_id)
            if deny:
                return pb.OkReply(ok=False, error=deny)
            ok = self.scheduler.hold(request.job_id, request.held,
                                     now=self._now())
        return pb.OkReply(ok=ok, error="" if ok else "not pending")

    def ModifyJob(self, request, context):
        """Job modification (reference ModifyJob, Crane.proto:1447).
        Owner-or-admin; two refinements mirroring the reference's
        operator gating: only an admin may RAISE a time limit (owners
        may lower their own), and priority changes are admin-only."""
        with self._lock:
            ident = self._ident(context)
            deny = self._deny_job_mutation(ident, request.job_id)
            if deny:
                return pb.OkReply(ok=False, error=deny)
            time_limit = (request.time_limit
                          if request.HasField("time_limit") else None)
            priority = (request.priority
                        if request.HasField("priority") else None)
            partition = (request.partition
                         if request.HasField("partition") else None)
            if self.auth is not None and not self.auth.is_admin(ident):
                if priority is not None:
                    return pb.OkReply(
                        ok=False,
                        error="permission denied (priority changes "
                              "require admin)")
                job = self.scheduler.job_info(request.job_id)
                if (time_limit is not None and job is not None
                        and time_limit > job.spec.time_limit):
                    return pb.OkReply(
                        ok=False,
                        error="permission denied (raising a time "
                              "limit requires admin)")
            err = self.scheduler.modify_job(
                request.job_id, now=self._now(), time_limit=time_limit,
                priority=priority, partition=partition)
        return pb.OkReply(ok=not err, error=err)

    def SuspendJob(self, request, context):
        with self._lock:
            deny = self._deny_job_mutation(self._ident(context),
                                           request.job_id)
            if deny:
                return pb.OkReply(ok=False, error=deny)
            ok = self.scheduler.suspend(request.job_id, now=self._now())
        return pb.OkReply(ok=ok, error="" if ok else "not running")

    def ResumeJob(self, request, context):
        with self._lock:
            deny = self._deny_job_mutation(self._ident(context),
                                           request.job_id)
            if deny:
                return pb.OkReply(ok=False, error=deny)
            ok = self.scheduler.resume(request.job_id, now=self._now())
        return pb.OkReply(ok=ok, error="" if ok else "not suspended")

    def SubmitStep(self, request, context):
        try:
            spec = step_spec_from_pb(request.spec)
        except ValueError as exc:
            return pb.SubmitStepReply(step_id=-1, error=str(exc))
        with self._lock:
            deny = self._deny_job_mutation(self._ident(context),
                                           request.job_id)
            if deny:
                return pb.SubmitStepReply(step_id=-1, error=deny)
            step_id = self.scheduler.submit_step(request.job_id, spec,
                                                 now=self._now())
        return pb.SubmitStepReply(
            step_id=step_id,
            error="" if step_id >= 0 else "rejected (no such running "
                                          "allocation or bad share)")

    def QueryStepsInfo(self, request, context):
        self._require_authenticated(self._ident(context), context)
        with self._lock:
            names = {i: n.name
                     for i, n in self.scheduler.meta.nodes.items()}
            job = self.scheduler.job_info(request.job_id)
            steps = (sorted(job.steps.values(), key=lambda s: s.step_id)
                     if job is not None else [])
            return pb.QueryStepsReply(
                steps=[step_to_pb(request.job_id, s, names)
                       for s in steps])

    def CancelStep(self, request, context):
        with self._lock:
            deny = self._deny_job_mutation(self._ident(context),
                                           request.job_id)
            if deny:
                return pb.OkReply(ok=False, error=deny)
            ok = self.scheduler.cancel_step(
                request.job_id, request.step_id, now=self._now())
        return pb.OkReply(ok=ok, error="" if ok else "no such live step")

    def FreeAllocation(self, request, context):
        with self._lock:
            deny = self._deny_job_mutation(self._ident(context),
                                           request.job_id)
            if deny:
                return pb.OkReply(ok=False, error=deny)
            ok = self.scheduler.free_allocation(request.job_id,
                                                now=self._now())
        return pb.OkReply(ok=ok,
                          error="" if ok else "not a running allocation")

    # default page size for cursor reads that don't set a limit — also
    # the bare-read archive cap
    DEFAULT_PAGE = 10_000

    def _job_snapshot(self, request) -> tuple[list, dict]:
        """Filtered job list + node-name map, under the lock.  Returns
        refs (cheap); pb conversion happens in bounded chunks so large
        queues never pin the scheduler for the whole result set."""
        if request.after_job_id and not request.limit:
            # a cursor without a limit gets the default page size — so
            # the handlers' truncation math (limit-based) marks the
            # reply truncated instead of silently dropping the tail
            request.limit = self.DEFAULT_PAGE
        names = {i: n.name
                 for i, n in self.scheduler.meta.nodes.items()}
        jobs = list(self.scheduler.queue())
        if request.include_history:
            jobs += list(self.scheduler.history.values())
            if self.scheduler.archive is not None:
                # durable rows not in RAM (pre-restart /
                # post-compaction history); RAM wins on overlap.
                # Capped: a bare cacct on a long-lived cluster must
                # not deserialize the whole archive under the
                # server lock (newest rows are returned first)
                seen = {j.job_id for j in jobs}
                # a paginated read (after_job_id set) pages the archive
                # by keyset so every archived row is reachable; the
                # bare read keeps the newest-10k cap
                # paginated reads (limit set) page the archive by
                # keyset from the cursor (0 = start) so every row is
                # reachable; +1 row lets the truncated flag tell a
                # full final page from a continued one.  Bare reads
                # keep the newest-10k cap.
                paged = bool(request.limit or request.after_job_id)
                # cursor reads always carry a limit here (normalized
                # above): limit+1 rows let the truncated flag tell a
                # full final page from a continued one
                jobs += [j for j in self.scheduler.archive.query(
                             job_ids=list(request.job_ids),
                             user=request.user,
                             partition=request.partition,
                             limit=(request.limit + 1 if paged
                                    else self.DEFAULT_PAGE),
                             after_job_id=request.after_job_id,
                             keyset=paged)
                         if j.job_id not in seen]
        if request.job_ids:
            wanted = set(request.job_ids)
            jobs = [j for j in jobs if j.job_id in wanted]
        if request.user:
            jobs = [j for j in jobs if j.spec.user == request.user]
        if request.partition:
            jobs = [j for j in jobs
                    if j.spec.partition == request.partition]
        if request.after_job_id:
            # keyset pagination: results ascend by job id, so resume
            # strictly after the cursor
            jobs = [j for j in jobs if j.job_id > request.after_job_id]
        jobs.sort(key=lambda j: j.job_id)
        return jobs, names

    # conversion batch: bounds both the message size of one streamed
    # chunk and the lock hold per chunk
    QUERY_CHUNK = 1000

    def _staleness_guard(self, max_staleness: float, context) -> None:
        """Bounded-staleness read contract (federation query plane): a
        follower may serve this read only if it was fully caught up with
        its leader within the last ``max_staleness`` seconds; otherwise
        it refuses with FAILED_PRECONDITION so the client rotates to the
        leader.  ``max_staleness == 0`` keeps the old contract — any
        replica answers with whatever it has.  Leaders always pass."""
        if max_staleness <= 0 or self.ha_follower is None:
            return
        stale = self.ha_follower.staleness()
        if stale > max_staleness:
            _MET_STALE.inc()
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "staleness %.3fs exceeds max_staleness %.3fs%s" % (
                    stale, max_staleness,
                    "; try " + self.ha_peer if self.ha_peer else ""))

    def _durable_seq(self) -> int:
        """The durability watermark this replica's answers reflect:
        applied_seq on a follower, the WAL's fsync'd seq on a leader."""
        if self.ha_follower is not None:
            return self.ha_follower.applied_seq
        wal = self.scheduler.wal
        return wal.durable_seq if wal is not None else 0

    def QueryJobsInfo(self, request, context):
        self._require_authenticated(self._ident(context), context)
        self._staleness_guard(request.max_staleness, context)
        limit = request.limit or 0
        with self._lock:
            jobs, names = self._job_snapshot(request)
            truncated = bool(limit) and len(jobs) > limit
            if truncated:
                jobs = jobs[:limit]
            return pb.QueryJobsReply(
                jobs=[job_to_pb(j, names) for j in jobs],
                truncated=truncated,
                durable_seq=self._durable_seq(), shard=self.shard_name)

    def QueryJobsStream(self, request, context):
        """Server-streaming query (reference Crane.proto:1576-1590):
        chunks of QUERY_CHUNK jobs, converted under short lock holds —
        a 100k-job cqueue neither builds one giant message nor stalls
        the scheduling cycle for its whole duration."""
        self._require_authenticated(self._ident(context), context)
        self._staleness_guard(request.max_staleness, context)
        with self._lock:
            jobs, names = self._job_snapshot(request)
        remaining = request.limit or len(jobs)
        end = min(len(jobs), remaining)
        truncated = len(jobs) > remaining
        for lo in range(0, end, self.QUERY_CHUNK):
            hi = min(lo + self.QUERY_CHUNK, end)
            batch = jobs[lo:hi]
            # re-take the lock per chunk: Job objects are mutable and
            # the cycle runs between chunks
            with self._lock:
                chunk = [job_to_pb(j, names) for j in batch]
            yield pb.QueryJobsReply(jobs=chunk,
                                    truncated=truncated and hi == end)

    def QueryClusterInfo(self, request, context):
        self._require_authenticated(self._ident(context), context)
        self._staleness_guard(request.max_staleness, context)
        from cranesched_tpu.ops.resources import (
            CPU_SCALE, DIM_CPU, DIM_MEM, MEM_UNIT_BYTES)
        with self._lock:
            out = []
            for node in self.scheduler.meta.nodes.values():
                out.append(pb.NodeInfo(
                    name=node.name,
                    state=_node_state(node),
                    cpu_total=float(node.total[DIM_CPU]) / CPU_SCALE,
                    cpu_avail=float(node.avail[DIM_CPU]) / CPU_SCALE,
                    mem_total=int(node.total[DIM_MEM]) * MEM_UNIT_BYTES,
                    mem_avail=int(node.avail[DIM_MEM]) * MEM_UNIT_BYTES,
                    partitions=sorted(node.partitions),
                    running_jobs=len(node.running_jobs)))
            return pb.QueryClusterReply(
                nodes=out, durable_seq=self._durable_seq(),
                shard=self.shard_name)

    def CreateReservation(self, request, context):
        deny = self._deny_admin(self._ident(context))
        if deny:
            return pb.OkReply(ok=False, error=deny)
        with self._lock:
            resv = self.scheduler.meta.create_reservation(
                request.name, request.partition,
                list(request.node_names), request.start_time,
                request.end_time,
                allowed_accounts=(list(request.allowed_accounts)
                                  if request.allowed_accounts else None),
                denied_accounts=list(request.denied_accounts))
        if resv is not None:
            self._cycle_kick.set()
        return pb.OkReply(ok=resv is not None,
                          error="" if resv else "conflict")

    def DeleteReservation(self, request, context):
        deny = self._deny_admin(self._ident(context))
        if deny:
            return pb.OkReply(ok=False, error=deny)
        with self._lock:
            ok = self.scheduler.meta.delete_reservation(request.name)
        if ok:
            self._cycle_kick.set()
        return pb.OkReply(ok=ok, error="" if ok else "no such reservation")

    def ModifyNode(self, request, context):
        """Node control ops (reference control states
        PublicDefs.proto:98-106 + PowerStateChange,
        CtldGrpcServer.cpp:2583-2649)."""
        deny = self._deny_admin(self._ident(context))
        if deny:
            return pb.OkReply(ok=False, error=deny)
        with self._lock:
            meta = self.scheduler.meta
            if request.name not in meta._name_to_id:
                return pb.OkReply(ok=False, error="unknown node")
            node = meta.node_by_name(request.name)
            action = request.action.lower()
            if action == "drain":
                meta.drain(node.node_id, True)
                self.scheduler.emit_node_event("drain", node.name,
                                               "operator",
                                               now=self._now())
            elif action == "resume":
                meta.drain(node.node_id, False)
                # the operator's resume is the recovery path for hook-
                # failure drains too (they ride the health flag and
                # nothing else would ever clear them without a
                # configured health program)
                node.health_drained = False
                node.health_message = ""
                self.scheduler.emit_node_event("undrain", node.name,
                                               "operator",
                                               now=self._now())
            elif action == "poweroff":
                node.power_state = "POWEREDOFF"
                self.scheduler.emit_node_event("poweroff", node.name,
                                               now=self._now())
                self.scheduler.on_craned_down(node.node_id, self._now())
            elif action == "wake":
                node.power_state = "ACTIVE"
                self.scheduler.emit_node_event("wake", node.name,
                                               now=self._now())
                if not node.expect_pings:
                    node.alive = True  # sim nodes wake immediately;
                                       # real ones wake at re-register
            else:
                return pb.OkReply(ok=False,
                                  error=f"unknown action {action!r}")
            self._cycle_kick.set()
            return pb.OkReply(ok=True)

    def QueryStats(self, request, context):
        self._require_authenticated(self._ident(context), context)
        self._staleness_guard(request.max_staleness, context)
        import json as _json

        from cranesched_tpu.obs import REGISTRY
        with self._lock:
            doc = dict(self.scheduler.stats)
            doc["licenses"] = {
                name: {"total": lic.total, "in_use": lic.in_use,
                       "external_used": lic.external_used,
                       "free": lic.free, "remote": lic.remote}
                for name, lic in
                self.scheduler.licenses.licenses.items()}
            # obs layer: full metric snapshot + the cycle-trace ring +
            # liveness, so `cstats --metrics/--cycles` needs no extra
            # RPC and can flag "scheduler stalled" client-side
            doc["metrics"] = REGISTRY.snapshot()
            doc["cycle_trace"] = self.scheduler.cycle_trace.snapshot()
            # per-job tracing + SLO plane (cstats --slo): evaluating on
            # query refreshes the burn-rate gauges, so /metrics scraped
            # right after a cstats --slo shows the same numbers
            if self.scheduler.jobtrace is not None:
                doc["jobtrace"] = self.scheduler.jobtrace.stats()
            if self.scheduler.slo_engine is not None:
                doc["slo"] = self.scheduler.slo_engine.evaluate(
                    time.time())
            topo = getattr(self.scheduler.meta, "topology", None)
            if topo is not None:
                from cranesched_tpu.topo.model import topology_doc
                avail_np, total_np, alive_np = \
                    self.scheduler.meta.snapshot()
                free = alive_np & (avail_np == total_np).all(axis=1)
                doc["topology"] = topology_doc(topo, free)
            # stall forensics (cflight): recent phase ring + the last
            # sentry-captured stall with its all-thread stacks
            doc["flight"] = self.scheduler.flight.report()
            doc["watchdog"] = {
                "now": time.time(),
                "cycle_interval": self.cycle_interval,
                "idle_sleep": float(getattr(
                    self.scheduler.config, "cycle_idle_sleep", 0.0)),
                "tick_mode": self.tick_mode,
                "last_cycle_walltime":
                    self.scheduler.stats.get("last_cycle_walltime", 0.0),
                "cycle_crashes_total":
                    self.scheduler.stats.get("cycle_crashes_total", 0),
                "last_crash": self.scheduler.stats.get("last_crash"),
            }
            wal = self.scheduler.wal
            lag = 0
            if self.ha_follower is not None:
                lag = max(0, self.ha_follower.leader_seq
                          - self.ha_follower.applied_seq)
            doc["ha"] = {
                "role": self.ha_role,
                "fencing_epoch": self.scheduler.fencing_epoch,
                "wal_seq": (self.ha_follower.applied_seq
                            if self.ha_follower is not None
                            else (wal.durable_seq
                                  if wal is not None else 0)),
                "replication_lag": lag,
                "failovers_total": self.failovers,
                "peer": self.ha_peer,
            }
            if self.shard_name or self.shard_map is not None:
                doc["fed"] = {
                    "shard": self.shard_name,
                    "map_epoch": self._map_epoch(),
                    "shards": (self.shard_map.doc()
                               if self.shard_map is not None else []),
                }
                if self.scheduler.fed is not None:
                    doc["fed"].update(self.scheduler.fed.stats())
                if self.scheduler.global_usage is not None:
                    doc["fed"]["usage"] = \
                        self.scheduler.global_usage.stats()
            return pb.StatsReply(json=_json.dumps(doc),
                                 durable_seq=self._durable_seq(),
                                 shard=self.shard_name)

    def AcctMgr(self, request, context):
        """Accounting CRUD (reference cacctmgr -> AccountManager RPC
        surface, AccountManager.h:33-445): one multiplexed action with a
        JSON payload; RBAC enforced by the manager via ``actor``."""
        import json as _json
        from cranesched_tpu.ctld.accounting import (
            Account, AccountingError, AdminLevel, Qos, User)
        mgr = self.scheduler.accounts
        if mgr is None:
            return pb.AcctMgrReply(ok=False,
                                   error="accounting is not enabled")
        try:
            args = _json.loads(request.payload) if request.payload \
                else {}
        except _json.JSONDecodeError as exc:
            return pb.AcctMgrReply(ok=False, error=f"bad payload: {exc}")
        if self.auth is not None:
            # the actor is the AUTHENTICATED identity — never a request
            # field (round-2 advisor: any client could claim
            # actor="root" over the insecure port)
            ident = self._ident(context)
            if ident is None:
                return pb.AcctMgrReply(ok=False,
                                       error="authentication required")
            actor = ident
        else:
            actor = request.actor
        try:
            with self._lock:
                action = request.action
                if action == "add_qos":
                    preempt = set(args.pop("preempt", []))
                    mgr.add_qos(actor, Qos(preempt=preempt, **args))
                elif action == "add_account":
                    allowed_qos = set(args.pop("allowed_qos", []))
                    mgr.add_account(actor, Account(
                        allowed_qos=allowed_qos, **args))
                elif action == "add_user":
                    account = args.pop("account")
                    mgr.add_user(actor, User(**args), account)
                elif action == "block_user":
                    mgr.block_user(actor, args["name"], args["account"],
                                   args.get("blocked", True))
                elif action == "block_account":
                    mgr.block_account(actor, args["name"],
                                      args.get("blocked", True))
                elif action == "set_admin_level":
                    mgr.set_admin_level(actor, args["name"],
                                        AdminLevel[args["level"].upper()])
                elif action == "show":
                    doc = {
                        "accounts": {
                            name: {"parent": a.parent,
                                   "users": sorted(a.users),
                                   "allowed_qos": sorted(a.allowed_qos),
                                   "default_qos": a.default_qos,
                                   "blocked": a.blocked}
                            for name, a in mgr.accounts.items()},
                        "users": {
                            name: {"accounts": sorted(u.accounts),
                                   "admin_level": u.admin_level.name}
                            for name, u in mgr.users.items()},
                        "qos": {
                            name: {"priority": q.priority,
                                   "preempt": sorted(q.preempt)}
                            for name, q in mgr.qos.items()},
                    }
                    return pb.AcctMgrReply(ok=True,
                                           json=_json.dumps(doc))
                else:
                    return pb.AcctMgrReply(
                        ok=False, error=f"unknown action {action!r}")
            return pb.AcctMgrReply(ok=True)
        except AccountingError as exc:
            return pb.AcctMgrReply(ok=False, error=str(exc))
        except Exception as exc:  # malformed payloads of any shape come
            # back as a legible reply, never a raw gRPC error
            return pb.AcctMgrReply(
                ok=False, error=f"bad payload for {request.action}: "
                                f"{type(exc).__name__}: {exc}")

    def CranedHealth(self, request, context):
        """Health-check report (reference HealthCheck config,
        Craned.cpp:731-751): unhealthy nodes drain until they report
        healthy again."""
        deny = self._deny_internal(self._ident(context),
                                   node_id=request.node_id)
        if deny:
            return pb.OkReply(ok=False, error=deny)
        with self._lock:
            node = self.scheduler.meta.nodes.get(request.node_id)
            if node is None:
                return pb.OkReply(ok=False, error="unknown node")
            was_drained = node.health_drained
            node.health_message = request.message
            node.health_drained = not request.healthy
            if not request.healthy:
                from cranesched_tpu.ctld.meta import ResReduceEvent
                self.scheduler.meta._log_event(
                    ResReduceEvent(node.node_id))
            if was_drained != node.health_drained:
                self.scheduler.emit_node_event(
                    "drain" if node.health_drained else "undrain",
                    node.name, f"health: {request.message}",
                    now=self._now())
                self._cycle_kick.set()
            return pb.OkReply(ok=True)

    def IssueToken(self, request, context):
        """Admin-only token issuance (the SignUserCertificate analog)."""
        if self.auth is None:
            return pb.TokenReply(ok=False,
                                 error="authentication is not enabled")
        token = self.auth.issue(self._ident(context), request.user)
        if token is None:
            return pb.TokenReply(ok=False,
                                 error="permission denied "
                                       "(admin required)")
        return pb.TokenReply(ok=True, token=token)

    def RevokeToken(self, request, context):
        if self.auth is None:
            return pb.OkReply(ok=False,
                              error="authentication is not enabled")
        n = self.auth.revoke(self._ident(context), request.user)
        if n < 0:
            return pb.OkReply(ok=False, error="permission denied "
                                              "(admin required)")
        return pb.OkReply(ok=True)

    # ---- internal (node plane + virtual time) ----

    def CranedRegister(self, request, context):
        deny = self._deny_internal(self._ident(context),
                                   node_name=request.name)
        if deny:
            return pb.CranedRegisterReply(ok=False, error=deny)
        with self._lock:
            meta = self.scheduler.meta
            if request.name in meta._name_to_id:
                node = meta.node_by_name(request.name)
                if node.power_state == "POWEREDOFF":
                    # refused until the operator wakes it (cnode wake)
                    return pb.CranedRegisterReply(
                        ok=False, error="node is powered off "
                                        "(wake it with cnode wake)")
                # a re-registration may report CHANGED capacity
                # (hardware swap, cgroup limits): re-encode and apply it
                # through update_node_total, which also invalidates the
                # partition max-total cache — skipping this left the
                # cache stale and submit-time feasibility wrong
                if request.total.cpu or request.total.mem_bytes:
                    known = set(meta.layout.gres_dims)
                    gres = {}
                    for key, count in request.total.gres.items():
                        name, _, typ = key.partition(":")
                        if (name, typ) in known:
                            gres[(name, typ)] = count
                    meta.update_node_total(
                        node.node_id,
                        meta.layout.encode(
                            cpu=request.total.cpu,
                            mem_bytes=request.total.mem_bytes,
                            memsw_bytes=request.total.memsw_bytes,
                            gres=gres,
                            is_capacity=True))
            else:
                # only GRES pairs in the cluster's configured layout can
                # be represented; unknown pairs are ignored (the craned
                # still tracks its local slots)
                known = set(meta.layout.gres_dims)
                gres = {}
                for key, count in request.total.gres.items():
                    name, _, typ = key.partition(":")
                    if (name, typ) in known:
                        gres[(name, typ)] = count
                node = meta.add_node(
                    request.name,
                    meta.layout.encode(
                        cpu=request.total.cpu,
                        mem_bytes=request.total.mem_bytes,
                        memsw_bytes=request.total.memsw_bytes,
                        gres=gres,
                        is_capacity=True),
                    partitions=tuple(request.partitions) or ("default",))
            was_alive = node.alive
            meta.craned_up(node.node_id)
            if not was_alive:
                self.scheduler.emit_node_event("node_up", node.name,
                                               now=self._now())
            if request.address:
                # a REAL craned: remember its push address and expect
                # pings (missed pings -> CranedDown in the cycle)
                node.address = request.address
                node.expect_pings = True
                node.last_ping = self._now()
                if self.dispatcher is not None:
                    self.dispatcher.node_registered(node.node_id,
                                                    request.address)
            # keep the simulated plane in sync so dispatch to the new
            # node has a craned to land on
            elif self.sim is not None and node.node_id not in \
                    self.sim.craneds:
                self.sim.craneds[node.node_id] = SimCraned(node.node_id)
            # tell the craned which steps ctld still expects on it;
            # anything else running locally is stale (Configure flow)
            expected = [jid for jid, job in
                        self.scheduler.running.items()
                        if node.node_id in job.node_ids]
            # the craned latches this epoch and fences lower-epoch
            # pushes — the deposed leader's in-flight RPCs die here
            self._cycle_kick.set()
            return pb.CranedRegisterReply(
                ok=True, node_id=node.node_id, expected_jobs=expected,
                fencing_epoch=self.scheduler.fencing_epoch)

    def CranedPing(self, request, context):
        deny = self._deny_internal(self._ident(context),
                                   node_id=request.node_id)
        if deny:
            return pb.OkReply(ok=False, error=deny)
        with self._lock:
            node = self.scheduler.meta.nodes.get(request.node_id)
            if node is None:
                return pb.OkReply(ok=False, error="unknown node")
            if not node.alive and node.expect_pings:
                # ctld declared this node down (its jobs were requeued):
                # a bare ping cannot resurrect it — force the craned back
                # through registration so stale steps get reconciled
                return pb.OkReply(ok=False, error="re-register")
            node.last_ping = self._now()
            return pb.OkReply(ok=True)

    def StepStatusChange(self, request, context):
        deny = self._deny_internal(self._ident(context),
                                   node_id=request.node_id)
        if deny:
            return pb.OkReply(ok=False, error=deny)
        with self._lock:
            if request.spans:
                # craned-side lifecycle spans land BEFORE the status
                # change is queued, so the timeline holds them when the
                # next cycle stamps the terminal ``end`` edge
                self.scheduler.record_remote_spans(
                    request.job_id, request.incarnation, request.spans)
            if request.HasField("step_id"):
                # step-level report (real craneds): routes through the
                # per-step machine; batch step 0 closes the job
                self.scheduler.step_report(
                    request.job_id, request.step_id,
                    StepStatus(request.status), request.exit_code,
                    request.time, node_id=request.node_id,
                    incarnation=request.incarnation,
                    cpu_seconds=request.cpu_seconds,
                    max_rss_bytes=request.max_rss_bytes)
            else:
                self.scheduler.step_status_change(
                    request.job_id, JobStatus(request.status),
                    request.exit_code, request.time,
                    node_id=request.node_id,
                    incarnation=request.incarnation)
        return pb.OkReply(ok=True)

    def Tick(self, request, context):
        """Run one virtual-time cycle (advance the sim plane first).
        Admin-gated under auth: it drives the cluster clock."""
        deny = self._deny_admin(self._ident(context))
        if deny:
            return pb.TickReply(now=request.now, error=deny)
        with self._lock:
            if self.sim is not None:
                self.sim.advance_to(request.now)
            started = self.scheduler.schedule_cycle(request.now)
        return pb.TickReply(started=started, now=request.now)

    # ---- HA + summary ----

    def RequeueJob(self, request, context):
        """Kill-and-repend a running job (reference RequeueJob,
        Crane.proto:1407)."""
        with self._lock:
            deny = self._deny_job_mutation(self._ident(context),
                                           request.job_id)
            if deny:
                return pb.OkReply(ok=False, error=deny)
            err = self.scheduler.requeue(request.job_id,
                                         now=self._now())
        return pb.OkReply(ok=not err, error=err)

    def QueryJobSummary(self, request, context):
        """Per-status counts (reference QueryJobSummary,
        Crane.proto:1588) — works on a standby too (shadow state).
        job_id != 0 additionally returns that job's recorded timeline
        (followers serve the traces they replicated, read-only)."""
        self._require_authenticated(self._ident(context), context)
        self._staleness_guard(request.max_staleness, context)
        import json as _json
        timeline = explain = ""
        with self._lock:
            counts = self.scheduler.job_summary(request.user,
                                                request.partition)
            if request.job_id:
                if self.scheduler.jobtrace is not None:
                    doc = self.scheduler.jobtrace.timeline(request.job_id)
                    if doc is not None:
                        timeline = _json.dumps(doc)
                explain = _json.dumps(self.scheduler.explain_pending(
                    request.job_id, self._now()))
        reply = pb.QueryJobSummaryReply(total=sum(counts.values()),
                                        timeline_json=timeline,
                                        explain_json=explain,
                                        durable_seq=self._durable_seq(),
                                        shard=self.shard_name)
        for status in sorted(counts):
            reply.states.add(status=status, count=counts[status])
        return reply

    def QueryEvents(self, request, context):
        """Structured cluster-event ring with min-severity / time /
        cursor / type filters (``cevents``).  Standby-servable: a
        follower answers from the events it replicated plus its own
        local emissions (its seq numbering is local)."""
        self._require_authenticated(self._ident(context), context)
        self._staleness_guard(request.max_staleness, context)
        with self._lock:
            recs = self.scheduler.events.since(
                after_seq=request.after_seq,
                severity=request.severity,
                since_time=request.since,
                type=request.type,
                limit=request.limit)
        reply = pb.QueryEventsReply(durable_seq=self._durable_seq(),
                                    shard=self.shard_name)
        for r in recs:
            reply.events.add(seq=r["seq"], time=r["time"],
                             type=r["type"], severity=r["severity"],
                             node=r["node"], job_id=r["job_id"],
                             detail=r["detail"])
        return reply

    # ---- federation: shard map + the arbiter's lease plane ----

    def QueryShardMap(self, request, context):
        """The static partition -> shard routing table, served by every
        shard (and every follower — the map is config, not state) so
        clients can learn routes from whichever replica answered."""
        self._require_authenticated(self._ident(context), context)
        if self.shard_map is None:
            return pb.QueryShardMapReply(shard=self.shard_name,
                                         error="not federated")
        reply = pb.QueryShardMapReply(shard=self.shard_name,
                                      map_epoch=self.shard_map.epoch)
        for doc in self.shard_map.doc():
            reply.shards.add(name=doc["name"],
                             partitions=doc["partitions"],
                             address=doc["address"],
                             followers=doc["followers"])
        return reply

    def LeaseNodes(self, request, context):
        """Phase one of the arbiter's cross-partition gang commit:
        durably reserve nodes under this shard's fencing epoch."""
        deny = self._deny_admin(self._ident(context))
        if deny:
            return pb.LeaseNodesReply(ok=False, error=deny)
        fed = self.scheduler.fed
        if fed is None:
            return pb.LeaseNodesReply(ok=False,
                                      error="not a federation shard")
        req = res_from_pb(request.res).encode(self.scheduler.meta.layout)
        with self._lock:
            try:
                names, epoch, seq = fed.lease_nodes(
                    request.lease_id, request.partition,
                    int(request.node_num), req, request.ttl,
                    self._now())
            except ValueError as exc:
                return pb.LeaseNodesReply(ok=False, error=str(exc))
        return pb.LeaseNodesReply(ok=True, node_names=names,
                                  fencing_epoch=epoch, durable_seq=seq)

    def ConfirmGang(self, request, context):
        """Phase two: turn a lease into a RUNNING local gang member in
        one WAL group (the only record that creates the job)."""
        deny = self._deny_admin(self._ident(context))
        if deny:
            return pb.ConfirmGangReply(ok=False, error=deny)
        fed = self.scheduler.fed
        if fed is None:
            return pb.ConfirmGangReply(ok=False,
                                       error="not a federation shard")
        try:
            spec = spec_from_pb(request.spec)
        except ValueError as exc:
            return pb.ConfirmGangReply(ok=False, error=str(exc))
        with self._lock:
            try:
                job_id = fed.confirm_gang(
                    request.lease_id, request.gang_id, spec,
                    list(request.node_names), self._now(),
                    epoch=request.fencing_epoch)
            except ValueError as exc:
                return pb.ConfirmGangReply(ok=False, error=str(exc))
        return pb.ConfirmGangReply(ok=True, job_id=job_id,
                                   durable_seq=self._durable_seq())

    def ReleaseLease(self, request, context):
        """Drop an unconfirmed reservation (arbiter abort)."""
        deny = self._deny_admin(self._ident(context))
        if deny:
            return pb.OkReply(ok=False, error=deny)
        fed = self.scheduler.fed
        if fed is None:
            return pb.OkReply(ok=False, error="not a federation shard")
        with self._lock:
            ok = fed.release_lease(request.lease_id, self._now())
        return pb.OkReply(ok=ok, error="" if ok else "no such lease")

    # ---- elastic federation: usage gossip + live migration ----

    def FetchUsage(self, request, context):
        """This shard's per-user/per-account usage summary, stamped
        with its WAL watermark (``durable_seq``).  Peers poll this and
        feed the payload to their own UsageBook.ingest — the gossip
        transport for cluster-wide MaxJobs / fair-share.  The request
        names the PULLING shard: serving it is confirmed delivery to
        that peer, and only the slowest peer's confirmation releases
        the publish-slack throttle (an anonymous pull — the CLI —
        acks nobody)."""
        import json as _json
        self._require_authenticated(self._ident(context), context)
        book = self.scheduler.global_usage
        if book is None:
            return pb.FetchUsageReply(ok=False, shard=self.shard_name,
                                      error="no global accounting")
        with self._lock:
            doc = book.publish(self._now(), peer=request.shard or "")
            seq = self._durable_seq()
        return pb.FetchUsageReply(ok=True, shard=self.shard_name,
                                  payload=_json.dumps(doc),
                                  durable_seq=seq)

    def MigratePartition(self, request, context):
        """Live partition migration (admin-only).  Two phases share the
        verb:

        * ``phase=""`` — drive the whole handoff.  Must land on the
          partition's source shard (``cfed migrate`` dials it from the
          map); runs seal -> export locally, ships the payload to the
          dest with ``phase="import"``, flips this shard's map, then
          commits.  An import failure aborts durably and re-opens the
          partition in place.
        * ``phase="import"`` — adopt an exported payload: one WAL group
          creates every job under fresh local ids, then this shard's
          map flips so it starts routing the partition to itself.
        * ``phase="query"`` — answer :meth:`has_import` for ``mid``:
          the source's resolution path keys commit-vs-abort on this
          after an ambiguous import RPC (timeout/drop) or a crash.
        """
        import json as _json
        deny = self._deny_admin(self._ident(context))
        if deny:
            return pb.MigratePartitionReply(ok=False, error=deny)
        fed = self.scheduler.fed
        if fed is None or self.shard_map is None:
            return pb.MigratePartitionReply(
                ok=False, error="not a federation shard")
        now = self._now()
        if request.phase == "query":
            with self._lock:
                adopted = fed.has_import(request.mid)
                jobs = len(fed.imports.get(str(request.mid)) or [])
            return pb.MigratePartitionReply(
                ok=True, mid=request.mid, adopted=adopted,
                jobs_moved=jobs, map_epoch=self._map_epoch())
        if request.phase == "import":
            try:
                payload = _json.loads(request.payload)
            except _json.JSONDecodeError as exc:
                return pb.MigratePartitionReply(
                    ok=False, error=f"bad payload: {exc}")
            with self._lock:
                try:
                    imported, _nodes = fed.import_partition(payload, now)
                except ValueError as exc:
                    return pb.MigratePartitionReply(ok=False,
                                                    error=str(exc))
                try:
                    self.shard_map = self.shard_map.with_partition_moved(
                        payload["partition"], self.shard_name)
                except ValueError:
                    pass  # already ours (idempotent re-import)
            self._cycle_kick.set()
            return pb.MigratePartitionReply(
                ok=True, mid=payload.get("mid", ""),
                jobs_moved=len(imported), map_epoch=self._map_epoch())
        if request.phase:
            return pb.MigratePartitionReply(
                ok=False, error=f"unknown phase {request.phase!r}")
        partition, dest = request.partition, request.dest_shard
        owner = self.shard_map.shard_for_partition(partition)
        if owner != self.shard_name:
            spec = self.shard_map.spec(owner) if owner else None
            return pb.MigratePartitionReply(
                ok=False,
                error=f"partition {partition!r} belongs to shard "
                      f"{owner!r}"
                      + (f" at {spec.address}" if spec is not None
                         and spec.address else ""))
        dspec = self.shard_map.spec(dest)
        if dspec is None or dest == self.shard_name:
            return pb.MigratePartitionReply(
                ok=False, error=f"bad destination shard {dest!r}")
        mid = (f"mig:{partition}:{self.shard_map.epoch}"
               f":{self.shard_name}->{dest}")
        with self._lock:
            try:
                fed.seal_partition(mid, partition, dest, now)
                payload = fed.export_partition(mid, partition)
            except ValueError as exc:
                return pb.MigratePartitionReply(ok=False, error=str(exc))
        adopted = None
        jobs_moved = 0
        err = ""
        try:
            dreply = self._fed_client(dspec.address).migrate_partition(
                partition, dest, phase="import",
                payload=_json.dumps(payload), mid=mid)
            if dreply.ok:
                adopted = True
                jobs_moved = int(dreply.jobs_moved)
            else:
                # a structured refusal: the dest's two-phase import
                # validates+mallocs everything BEFORE its first WAL
                # write, so "not ok" genuinely means nothing adopted
                adopted = False
                err = dreply.error
        except Exception as exc:
            # the RPC died in flight — AMBIGUOUS.  The dest may have
            # durably imported (and flipped its map) before the
            # channel dropped; a blind abort here would leave BOTH
            # shards owning the jobs.  Ask the dest what it holds.
            err = str(exc)
            verdict = self._query_dest_import(dspec.address, mid)
            if verdict is not None:
                adopted, jobs_moved = verdict
        if adopted is False:
            with self._lock:
                fed.abort_migration(mid, partition, now)
            return pb.MigratePartitionReply(
                ok=False, mid=mid,
                error=f"dest import failed (aborted): {err}")
        if adopted is None:
            # dest unreachable AND adoption unknown: the ONLY safe
            # move is none.  The partition stays sealed (no local
            # admits, no duplicate execution either way) and the
            # resolver loop settles the begin once the dest answers.
            with self._lock:
                if not any(r.get("mid") == mid
                           for r in fed.unresolved_migrations):
                    fed.unresolved_migrations.append({
                        "mid": mid, "partition": partition,
                        "dest": dest,
                        "job_ids": [e["job"]["job_id"]
                                    for e in payload.get("jobs", [])]})
                self.scheduler.events.emit(
                    "fed_migrate_unresolved", "warning", time=now,
                    detail=f"mid={mid} part={partition} dest={dest} "
                           "(import RPC died; partition sealed "
                           "pending resolution)")
            return pb.MigratePartitionReply(
                ok=False, mid=mid,
                error=f"dest unreachable after import RPC ({err}); "
                      "partition stays sealed pending resolution")
        # the dest holds the jobs durably: flip BEFORE commit, so a
        # crash here still routes the partition to the shard that has
        # the jobs; recovery resolves the bare begin against the dest
        with self._lock:
            self.shard_map = self.shard_map.with_partition_moved(
                partition, dest)
            fed.commit_migration(mid, partition, now)
        self.scheduler.events.emit(
            "fed_migrate", "info", time=now,
            detail=f"partition={partition} -> shard={dest} "
                   f"jobs={jobs_moved} "
                   f"epoch={self.shard_map.epoch}")
        return pb.MigratePartitionReply(
            ok=True, mid=mid, jobs_moved=jobs_moved,
            map_epoch=self.shard_map.epoch)

    def CaptureProfile(self, request, context):
        """Arm an on-demand jax.profiler window spanning the next N
        scheduling cycles (leader-only: the trace is of the cycle loop
        this ctld runs)."""
        self._require_authenticated(self._ident(context), context)
        with self._lock:
            ok, detail = self.scheduler.profiler_window.request(
                request.cycles or 1, out_dir=request.dir)
        if ok:
            return pb.CaptureProfileReply(ok=True, dir=detail)
        return pb.CaptureProfileReply(ok=False, error=detail)

    def HaStatus(self, request, context):
        self._require_authenticated(self._ident(context), context)
        with self._lock:
            wal = self.scheduler.wal
            seq = wal.durable_seq if wal is not None else 0
            lag = 0
            leader = "" if self.ha_role == "leader" else self.ha_peer
            if self.ha_follower is not None:
                seq = self.ha_follower.applied_seq
                lag = max(0, self.ha_follower.leader_seq - seq)
            return pb.HaStatusReply(
                role=self.ha_role,
                fencing_epoch=self.scheduler.fencing_epoch,
                wal_seq=seq, leader_address=leader,
                replication_lag=lag)

    def HaFetchSnapshot(self, request, context):
        """Serve a point-in-time snapshot to a syncing standby."""
        self._require_authenticated(self._ident(context), context)
        import json as _json

        from cranesched_tpu.ha.snapshot import capture_snapshot
        with self._lock:
            doc = capture_snapshot(self.scheduler)
            epoch = self.scheduler.fencing_epoch
        return pb.HaSnapshotReply(ok=True, seq=doc["seq"],
                                  payload=_json.dumps(
                                      doc, separators=(",", ":")),
                                  fencing_epoch=epoch)

    def HaFetchWal(self, request, context):
        """Cursor-based WAL tail for the polling standby."""
        self._require_authenticated(self._ident(context), context)
        with self._lock:
            wal = self.scheduler.wal
            if wal is None:
                return pb.HaFetchReply(ok=False,
                                       error="no WAL on this ctld")
            out = wal.tail_since(request.after_seq,
                                 limit=request.limit or 512)
            # the follower's replication cursor must never run ahead of
            # the durability barrier — inside an open group `seq` does
            seq = wal.durable_seq
            epoch = self.scheduler.fencing_epoch
            # event-ring piggyback: the ring is bounded and events are
            # advisory, so no resync protocol — a follower that missed
            # evicted entries just starts from what is still in the ring
            events = self.scheduler.events.since(
                after_seq=request.after_event_seq)
            event_seq = self.scheduler.events.last_seq
        reply = pb.HaFetchReply(ok=True, wal_seq=seq,
                                fencing_epoch=epoch, event_seq=event_seq)
        for r in events:
            reply.events.add(seq=r["seq"], time=r["time"], type=r["type"],
                             severity=r["severity"], node=r["node"],
                             job_id=r["job_id"], detail=r["detail"])
        if out is None:
            reply.resync = True
        else:
            for s, line in out:
                reply.records.add(seq=s, payload=line)
        return reply

    def promote_to_leader(self, epoch: int) -> None:
        """Flip a standby to leader: the cycle-loop gate opens on the
        next tick and the mutation surface starts answering.  The
        scheduler-side rebuild (recover + device state + epoch) is the
        follower's job BEFORE calling this."""
        self.ha_role = "leader"
        self.ha_follower = None
        self.failovers += 1
        self.scheduler.events.emit(
            "failover", "critical",
            detail="standby promoted to leader (epoch %d)" % epoch,
            time=self._now())
        # seed push channels from the replicated node addresses so a
        # re-sent kill (recover's cancel-intent redelivery) can land
        # BEFORE the craneds get around to re-registering
        if self.dispatcher is not None:
            for node in self.scheduler.meta.nodes.values():
                if node.alive and node.address:
                    self.dispatcher.node_registered(node.node_id,
                                                    node.address)

    # ---- lifecycle ----

    _RPCS = {
        "SubmitBatchJob": (pb.SubmitJobRequest, pb.SubmitJobReply),
        "SubmitBatchJobs": (pb.SubmitJobsRequest, pb.SubmitJobsReply),
        "CancelJob": (pb.JobIdRequest, pb.OkReply),
        "HoldJob": (pb.HoldRequest, pb.OkReply),
        "ModifyJob": (pb.ModifyJobRequest, pb.OkReply),
        "SuspendJob": (pb.JobIdRequest, pb.OkReply),
        "ResumeJob": (pb.JobIdRequest, pb.OkReply),
        "QueryJobsInfo": (pb.QueryJobsRequest, pb.QueryJobsReply),
        "SubmitStep": (pb.SubmitStepRequest, pb.SubmitStepReply),
        "QueryStepsInfo": (pb.QueryStepsRequest, pb.QueryStepsReply),
        "CancelStep": (pb.JobIdRequest, pb.OkReply),
        "FreeAllocation": (pb.JobIdRequest, pb.OkReply),
        "QueryClusterInfo": (pb.QueryClusterRequest, pb.QueryClusterReply),
        "CreateReservation": (pb.CreateReservationRequest, pb.OkReply),
        "DeleteReservation": (pb.NameRequest, pb.OkReply),
        "ModifyNode": (pb.ModifyNodeRequest, pb.OkReply),
        "QueryStats": (pb.StatsRequest, pb.StatsReply),
        "AcctMgr": (pb.AcctMgrRequest, pb.AcctMgrReply),
        "IssueToken": (pb.IssueTokenRequest, pb.TokenReply),
        "RevokeToken": (pb.IssueTokenRequest, pb.OkReply),
        "CranedHealth": (pb.CranedHealthRequest, pb.OkReply),
        "CranedRegister": (pb.CranedRegisterRequest,
                           pb.CranedRegisterReply),
        "CranedPing": (pb.CranedPingRequest, pb.OkReply),
        "StepStatusChange": (pb.StepStatusChangeRequest, pb.OkReply),
        "Tick": (pb.TickRequest, pb.TickReply),
        "RequeueJob": (pb.JobIdRequest, pb.OkReply),
        "QueryJobSummary": (pb.QueryJobSummaryRequest,
                            pb.QueryJobSummaryReply),
        "HaStatus": (pb.HaStatusRequest, pb.HaStatusReply),
        "HaFetchSnapshot": (pb.HaSnapshotRequest, pb.HaSnapshotReply),
        "HaFetchWal": (pb.HaFetchRequest, pb.HaFetchReply),
        "QueryEvents": (pb.QueryEventsRequest, pb.QueryEventsReply),
        "CaptureProfile": (pb.CaptureProfileRequest,
                           pb.CaptureProfileReply),
        "QueryShardMap": (pb.QueryShardMapRequest,
                          pb.QueryShardMapReply),
        "LeaseNodes": (pb.LeaseNodesRequest, pb.LeaseNodesReply),
        "ConfirmGang": (pb.ConfirmGangRequest, pb.ConfirmGangReply),
        "ReleaseLease": (pb.ReleaseLeaseRequest, pb.OkReply),
        "FetchUsage": (pb.FetchUsageRequest, pb.FetchUsageReply),
        "MigratePartition": (pb.MigratePartitionRequest,
                             pb.MigratePartitionReply),
    }

    # the surface a standby may serve from its shadow state; everything
    # else aborts FAILED_PRECONDITION ("not leader") so failover-aware
    # callers rotate to the leader.  Craned-internal RPCs are
    # deliberately NOT here: craneds must register/report to the leader
    # only, or the standby's shadow state would fork from the WAL.
    _STANDBY_OK = frozenset({
        "QueryJobsInfo", "QueryJobsStream", "QueryStepsInfo",
        "QueryClusterInfo", "QueryStats", "QueryJobSummary", "HaStatus",
        "QueryEvents", "QueryShardMap",
    })

    def _now(self) -> float:
        return self.sim.now if (self.tick_mode and self.sim is not None) \
            else time.time()

    def _leader_only(self, name, fn):
        """Gate one handler on leadership.  The abort code is part of
        the failover contract: HaCtldClient and the craned's ctld
        address rotation both treat FAILED_PRECONDITION as 'ask the
        other ctld'."""
        def handler(request, context):
            if self.ha_role != "leader":
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"not leader (standby"
                    f"{'; try ' + self.ha_peer if self.ha_peer else ''})")
            return fn(request, context)
        return handler

    def start(self, address: str = "127.0.0.1:0") -> int:
        """Start serving; returns the bound port."""
        handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                (getattr(self, name) if name in self._STANDBY_OK
                 else self._leader_only(name, getattr(self, name))),
                request_deserializer=req.FromString,
                response_serializer=reply.SerializeToString)
            for name, (req, reply) in self._RPCS.items()
        }
        handlers["QueryJobsStream"] = \
            grpc.unary_stream_rpc_method_handler(
                self.QueryJobsStream,
                request_deserializer=pb.QueryJobsRequest.FromString,
                response_serializer=(
                    pb.QueryJobsReply.SerializeToString))
        from cranesched_tpu.rpc.interceptors import MetricsInterceptor
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8),
            interceptors=(MetricsInterceptor(plane="ctld"),))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        if self.tls is not None:
            from cranesched_tpu.utils.pki import server_credentials
            port = self._server.add_secure_port(
                address, server_credentials(self.tls))
        else:
            port = self._server.add_insecure_port(address)
        self._server.start()
        if self.metrics_port is not None:
            from cranesched_tpu.obs import serve_metrics
            self._metrics_server = serve_metrics(self.metrics_port)
            self.metrics_port = self._metrics_server.server_address[1]
        if not self.tick_mode:
            self._cycle_thread = threading.Thread(
                target=self._cycle_loop, daemon=True)
            self._cycle_thread.start()
        if (self.shard_map is not None
                and self.scheduler.global_usage is not None):
            self._usage_thread = threading.Thread(
                target=self._usage_gossip_loop, daemon=True)
            self._usage_thread.start()
        if (self.shard_map is not None
                and self.scheduler.fed is not None):
            self._resolve_thread = threading.Thread(
                target=self._fed_resolve_loop, daemon=True)
            self._resolve_thread.start()
        return port

    def _usage_gossip_loop(self) -> None:
        """Cluster-wide accounting pump (fed/usage.py): pull every
        peer's latest summary via FetchUsage and ingest it under the
        lock.  The request carries OUR shard name — serving it is that
        peer's confirmed delivery to us, and symmetrically our
        FetchUsage handler marks our counters delivered per pulling
        peer.  Only the SLOWEST peer's confirmation releases the
        publish-slack throttle (UsageBook.unconfirmed), so a peer that
        cannot fetch for several intervals tightens our own admissions
        instead of letting global limits overshoot.  A peer outage
        only ages that peer's summary and withholds its acks; it never
        blocks this loop or the cycle thread."""
        import json as _json
        interval = max(self.cycle_interval, 0.5)
        while not self._stop.wait(interval):
            if self.ha_role != "leader":
                continue
            book = self.scheduler.global_usage
            for name, spec in self.shard_map.shards.items():
                if name == self.shard_name or not spec.address:
                    continue
                try:
                    reply = self._fed_client(
                        spec.address).fetch_usage(
                            shard=self.shard_name)
                    doc = _json.loads(reply.payload) if reply.ok \
                        else None
                except Exception:
                    continue
                if doc:
                    with self._lock:
                        book.ingest(doc, self._now())

    def _fed_resolve_loop(self) -> None:
        """Background settlement of unresolved migration begins (a
        crash or a dropped import RPC left a durable begin with no
        commit/abort).  Each pass asks every begin's dest for its
        has_import answer: adopted -> flip the map and commit; not
        adopted -> abort and re-open.  Unreachable dests just stay
        queued — the partition remains sealed, which is safe on both
        sides."""
        interval = max(self.cycle_interval * 5.0, 2.0)
        while not self._stop.wait(interval):
            if self.ha_role != "leader":
                continue
            try:
                self._resolve_migrations_once()
            except Exception:
                pass  # never kill the loop; next tick retries

    def _resolve_migrations_once(self) -> int:
        """One resolution pass; returns how many begins settled."""
        fed = self.scheduler.fed
        if fed is None or self.shard_map is None:
            return 0
        with self._lock:
            pending = [dict(r) for r in fed.unresolved_migrations]
        settled = 0
        for rec in pending:
            mid = str(rec.get("mid", ""))
            partition = str(rec.get("partition", ""))
            dest = str(rec.get("dest", ""))
            spec = self.shard_map.spec(dest) if dest else None
            if spec is None or not spec.address:
                continue
            verdict = self._query_dest_import(spec.address, mid,
                                              attempts=1)
            if verdict is None:
                continue  # still unreachable; stay sealed
            adopted, _jobs = verdict
            now = self._now()
            with self._lock:
                if not any(r.get("mid") == mid
                           for r in fed.unresolved_migrations):
                    continue  # settled concurrently
                if adopted:
                    try:
                        self.shard_map = \
                            self.shard_map.with_partition_moved(
                                partition, dest)
                    except ValueError:
                        pass  # map already routes it to the dest
                    fed.commit_migration(mid, partition, now)
                else:
                    fed.abort_migration(mid, partition, now)
                self.scheduler.events.emit(
                    "fed_migrate_resolved", "info", time=now,
                    detail=f"mid={mid} part={partition} -> "
                           + ("commit" if adopted else "abort"))
            settled += 1
        return settled

    def _cycle_loop(self) -> None:
        """The 1 Hz ScheduleThread_ analog (JobScheduler.cpp:1321,1981).

        Snapshot-in / commit-out: the lock is held only for the
        scheduler's state phases (prelude, snapshot, commit); each
        solve closure yielded by ``cycle_phases`` — the expensive 99%
        of a big cycle — runs with the lock RELEASED, so submits and
        queries landing mid-cycle wait microseconds, not a full solve
        (reference: 9 scheduler threads + per-entry-locked maps,
        JobScheduler.h:1290-1335; here one cycle thread + a lock whose
        hold time excludes the solve).

        WATCHDOG: any exception escaping a cycle — prelude, solve
        closure, or commit — used to kill this thread and silently stop
        scheduling forever.  Now each iteration is fenced: the
        traceback is logged and kept in stats["last_crash"],
        crane_cycle_crashes_total is bumped, the half-run generator is
        closed, and the NEXT tick schedules normally (fault-injection
        test: tests/test_obs.py)."""
        while not self._stop.is_set():
            # condition-variable tick: any event ends the sleep early;
            # with no events the timeout is the base cadence, or the
            # idle bound when the scheduler proves the next cycle would
            # be a no-op anyway (_sleep_interval)
            self._cycle_kick.wait(self._sleep_interval())
            self._cycle_kick.clear()
            if self._stop.is_set():
                break
            if self.ha_role != "leader":
                continue  # standby: shadow state only, never schedule
            now = time.time()
            # arm the stall sentry around the cycle: a cycle that
            # neither finishes nor raises (a wedged solve, a stuck
            # fsync) fires the flight recorder — all-thread stacks into
            # flight.last_stall — instead of hanging silently.  The
            # deadline mirrors the cstats staleness heuristic.
            stall_after = max(3.0 * self.cycle_interval,
                              2.0 * float(getattr(
                                  self.scheduler.config,
                                  "cycle_idle_sleep", 0.0)),
                              5.0)
            self.scheduler.flight.arm(stall_after, label="cycle")
            try:
                self._cycle_once(now)
            except Exception:
                self._record_cycle_crash(now)
            finally:
                self.scheduler.flight.disarm()

    def _sleep_interval(self) -> float:
        """Upper bound for the loop's event wait.  The base cadence
        unless the scheduler can prove the next tick would short-circuit
        (armed no-op fingerprint, nothing in flight) — then sleep up to
        ``cycle_idle_sleep``, clipped to the nearest time-dependent edge
        (begin_time/dep deadline, reservation boundary, alloc-only
        expiry, ping-timeout check).  Events still wake us instantly."""
        base = self.cycle_interval
        sched = self.scheduler
        idle = float(getattr(sched.config, "cycle_idle_sleep", 0.0))
        if self.ha_role != "leader" or idle <= base:
            return base
        with self._lock:
            if not sched.can_idle():
                return base
            wake = sched.next_wake_time(time.time())
        if wake == float("inf"):
            return idle
        return min(idle, max(wake - time.time(), base))

    def _cycle_once(self, now: float) -> None:
        """One lock-break cycle: state phases under the lock, solve
        closures outside it."""
        gen = None
        try:
            with self._lock:
                if self.sim is not None:
                    self.sim.advance_to(now)
                if self.scheduler.fed is not None:
                    # a dead arbiter's leases self-expire here, so
                    # reserved-but-never-confirmed nodes rejoin the
                    # local pool without operator action
                    self.scheduler.fed.expire(now)
                gen = self.scheduler.cycle_phases(now)
                try:
                    fn = next(gen)
                except StopIteration:
                    return
            while True:
                result = fn()          # lock released: the solve
                with self._lock:
                    try:
                        fn = gen.send(result)
                    except StopIteration:
                        return
        except Exception:
            if gen is not None:
                with self._lock:
                    try:
                        gen.close()    # unwind the half-run cycle
                    except Exception:
                        pass
            raise

    def _record_cycle_crash(self, now: float) -> None:
        import logging
        import traceback

        from cranesched_tpu.obs import REGISTRY
        tb = traceback.format_exc()
        logging.getLogger("cranesched.ctld").error(
            "scheduling cycle crashed (next tick continues):\n%s", tb)
        REGISTRY.counter(
            "crane_cycle_crashes_total",
            "scheduling cycles that died with an exception").inc()
        with self._lock:
            st = self.scheduler.stats
            st["cycle_crashes_total"] = (
                st.get("cycle_crashes_total", 0) + 1)
            st["last_crash"] = {"time": now, "traceback": tb,
                                "flight": self.scheduler.flight.report(
                                    tail=16)}
            self.scheduler.events.emit(
                "watchdog_crash", "error", time=now,
                detail=tb.strip().rsplit("\n", 1)[-1][:200])

    def stop(self) -> None:
        self._stop.set()
        self._cycle_kick.set()  # wake a possibly long idle sleep
        self.scheduler.flight.close()
        for cli in self._fwd_clients.values():
            try:
                cli.close()
            except Exception:
                pass
        self._fwd_clients.clear()
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server = None
        if self._server is not None:
            self._server.stop(grace=0.5)


def serve(scheduler: JobScheduler, sim: SimCluster | None = None,
          address: str = "127.0.0.1:0", **kw) -> tuple[CtldServer, int]:
    server = CtldServer(scheduler, sim=sim, **kw)
    port = server.start(address)
    return server, port
