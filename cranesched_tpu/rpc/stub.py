"""Generic unary-unary gRPC stub with a lazy per-method cache — the
single transport plumbing shared by the CLI/ctld client and the
ctld->craned dispatcher.  Plaintext by default; pass a
``utils.pki.TlsConfig`` to dial TLS (with a client cert when the peer
requires mTLS)."""

from __future__ import annotations

import grpc


class GrpcStub:
    def __init__(self, address: str, service: str, timeout: float = 30.0,
                 token: str = "", tls=None,
                 token_key: str = "crane-token"):
        self.address = address
        self.service = service
        self.timeout = timeout
        # bearer token attached as metadata on every call (verified by
        # the ctld's AuthManager; empty = unauthenticated).  token_key
        # lets other services on this plumbing use their own header
        # (e.g. the rendezvous service's per-gang secret)
        self.token = token
        self.token_key = token_key
        if tls is not None:
            from cranesched_tpu.utils.pki import secure_channel
            self._channel = secure_channel(address, tls)
        else:
            self._channel = grpc.insecure_channel(address)
        self._stubs = {}

    def call(self, name, request, reply_cls, timeout: float | None = None,
             metadata=()):
        """``metadata``: extra (key, value) pairs appended after the
        auth token — e.g. the dispatcher's crane-trace context."""
        stub = self._stubs.get(name)
        if stub is None:
            stub = self._channel.unary_unary(
                f"/{self.service}/{name}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=reply_cls.FromString)
            self._stubs[name] = stub
        md = (((self.token_key, self.token),) if self.token else ())
        md = md + tuple(metadata)
        return stub(request, timeout=timeout or self.timeout,
                    metadata=md or None)

    # server streams drain large result sets across many scheduler
    # cycles — the unary timeout (30 s) would abort them mid-stream
    STREAM_TIMEOUT = 600.0

    def call_stream(self, name, request, reply_cls):
        """Server-streaming call: yields reply messages."""
        stub = self._stubs.get(("stream", name))
        if stub is None:
            stub = self._channel.unary_stream(
                f"/{self.service}/{name}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=reply_cls.FromString)
            self._stubs[("stream", name)] = stub
        metadata = (((self.token_key, self.token),) if self.token
                    else None)
        return stub(request, timeout=self.STREAM_TIMEOUT,
                    metadata=metadata)

    def close(self) -> None:
        self._channel.close()
