"""Gang rendezvous service: fences + key-value modex.

The PMIx-server role (reference: src/Utilities/Pmix/Pmix.h:44 —
embedded PMIx server per supervisor; ring/tree fence collectives
PmixCollRing.h:53 / ReverseTree.cpp; direct modex PmixDModex.{h,cpp}),
redesigned as a single per-gang coordinator: the rank-0 supervisor of
a multi-node step hosts this service, every member (rank 0 included)
reaches it at ``CRANE_RENDEZVOUS``.  One coordinator instead of a
ring/tree server mesh is the jax.distributed / torchrun bootstrap
model — on TPU pods the heavy collectives ride ICI under XLA; the
host side only needs wire-up, barriers, and small KV exchange.

Capabilities:

* ``Fence`` — a named barrier over ``nranks`` participants with
  optional data contribution; releases everyone with the rank-ordered
  contributions (PMIx fence with data collection).  Re-usable: each
  completion opens a new epoch of the same name.
* ``Put``/``Get`` — the modex: publish once, read from any rank,
  blocking reads with timeout (direct-modex semantics).

A per-gang bearer token (``CRANE_RENDEZVOUS_TOKEN``) gates every call:
anyone who can reach the port could otherwise skew a barrier or
poison the modex.

Epochs (ISSUE 17): the coordinator carries an incarnation number.  A
member still retrying against a restarted coordinator — or lagging a
step behind the rest of the gang after a partial failure — gets a
typed ``stale epoch`` rejection instead of silently contributing to
the wrong barrier round (the rank-skew corruption mode: rank A's step
N+1 contribution satisfying rank B's step N fence).  Fence state is
keyed per ``(fence_id, epoch)``; epoch 0 means "no check" for
pre-epoch clients.
"""

from __future__ import annotations

import threading
from concurrent import futures

import grpc

from cranesched_tpu.rpc import crane_pb2 as pb

RDZV_SERVICE = "cranesched.CraneRendezvous"


class _FenceState:
    def __init__(self, nranks: int):
        self.nranks = nranks
        self.data: dict[int, bytes] = {}
        self.done = threading.Event()
        self.error = ""


class RendezvousServer:
    """Hosts CraneRendezvous (in the rank-0 supervisor).

    ``nranks`` sizes the worker pool: every waiting Fence handler
    parks one worker, so a pool smaller than the gang would deadlock
    the barrier (the final ranks' RPCs queue behind the parked ones
    and the fence times out at N_pool/N arrived)."""

    def __init__(self, token: str = "", nranks: int = 0, tls=None,
                 epoch: int = 0):
        self.token = token
        self.nranks = nranks
        # coordinator incarnation: a restarted coordinator comes back
        # with a higher epoch so members of the previous incarnation
        # fail fast (stale epoch) instead of skewing fresh barriers
        self.epoch = epoch
        # utils.pki.TlsConfig (the hosting node's cluster cert): when
        # set, the service serves TLS so the per-gang bearer token and
        # modex/fence payloads never ride plaintext node-to-node in
        # TLS-enabled clusters (members dial with the cluster CA via
        # CRANE_RENDEZVOUS_CA)
        self.tls = tls
        self._kv: dict[str, bytes] = {}
        self._kv_cond = threading.Condition()
        self._fences: dict[tuple[str, int], _FenceState] = {}
        self._lock = threading.Lock()
        self._server: grpc.Server | None = None
        self.port = 0

    # ---- handlers ----

    def _check(self, context) -> None:
        if not self.token:
            return
        meta = dict(context.invocation_metadata() or ())
        if meta.get("crane-rdzv-token") != self.token:
            context.abort(grpc.StatusCode.PERMISSION_DENIED,
                          "bad rendezvous token")

    def _stale(self, req_epoch: int) -> str:
        """Non-empty error when ``req_epoch`` belongs to a previous
        coordinator incarnation (0 on either side disables the check
        for pre-epoch clients/servers)."""
        if self.epoch and req_epoch and req_epoch != self.epoch:
            return (f"stale epoch {req_epoch} (coordinator at "
                    f"incarnation {self.epoch})")
        return ""

    def Put(self, request, context):
        self._check(context)
        stale = self._stale(request.epoch)
        if stale:
            return pb.OkReply(ok=False, error=stale)
        with self._kv_cond:
            self._kv[request.key] = request.value
            self._kv_cond.notify_all()
        return pb.OkReply(ok=True)

    def Get(self, request, context):
        self._check(context)
        deadline = request.timeout or 0.0
        with self._kv_cond:
            if request.key not in self._kv and deadline > 0:
                self._kv_cond.wait_for(
                    lambda: request.key in self._kv, timeout=deadline)
            if request.key in self._kv:
                return pb.RdzvGetReply(ok=True,
                                       value=self._kv[request.key])
        return pb.RdzvGetReply(ok=False)

    def Fence(self, request, context):
        self._check(context)
        stale = self._stale(request.epoch)
        if stale:
            return pb.RdzvFenceReply(ok=False, error=stale,
                                     epoch=self.epoch)
        if request.nranks < 1 or request.rank >= request.nranks:
            return pb.RdzvFenceReply(
                ok=False, error=f"bad rank {request.rank}/"
                                f"{request.nranks}",
                epoch=self.epoch)
        fkey = (request.fence_id, request.epoch)
        with self._lock:
            st = self._fences.get(fkey)
            if st is None or st.done.is_set():
                # fresh round of this fence name (within this epoch)
                st = self._fences[fkey] = _FenceState(
                    request.nranks)
            if st.nranks != request.nranks:
                st.error = (f"nranks mismatch: {st.nranks} vs "
                            f"{request.nranks}")
                st.done.set()
            elif request.rank in st.data:
                return pb.RdzvFenceReply(
                    ok=False, error=f"duplicate rank {request.rank} "
                                    "in fence",
                    epoch=self.epoch)
            else:
                st.data[request.rank] = request.data
                if len(st.data) == st.nranks:
                    st.done.set()
        if not st.done.wait(timeout=request.timeout or 300.0):
            with self._lock:
                if not st.done.is_set():
                    # withdraw the contribution so THIS rank can retry
                    # the same fence (leaving it would wedge the epoch
                    # on 'duplicate rank' forever)
                    arrived = len(st.data)
                    st.data.pop(request.rank, None)
                    return pb.RdzvFenceReply(
                        ok=False,
                        error=f"fence timeout ({arrived}/"
                              f"{st.nranks} arrived)",
                        epoch=self.epoch)
            # completed at the buzzer: fall through to the result
        if st.error:
            return pb.RdzvFenceReply(ok=False, error=st.error,
                                     epoch=self.epoch)
        return pb.RdzvFenceReply(
            ok=True, data=[st.data[r] for r in range(st.nranks)],
            epoch=self.epoch)

    # ---- lifecycle ----

    _RPCS = {
        "Put": (pb.RdzvPutRequest, pb.OkReply),
        "Get": (pb.RdzvGetRequest, pb.RdzvGetReply),
        "Fence": (pb.RdzvFenceRequest, pb.RdzvFenceReply),
    }

    def start(self, address: str = "0.0.0.0:0") -> int:
        handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                getattr(self, name),
                request_deserializer=req.FromString,
                response_serializer=reply.SerializeToString)
            for name, (req, reply) in self._RPCS.items()
        }
        # enough workers that the FULL gang can park in Fence while
        # Put/Get still make progress
        workers = max(16, 2 * self.nranks + 8)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=workers))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(RDZV_SERVICE,
                                                  handlers),))
        if self.tls is not None:
            from cranesched_tpu.utils.pki import server_credentials
            self.port = self._server.add_secure_port(
                address, server_credentials(self.tls))
        else:
            self.port = self._server.add_insecure_port(address)
        if not self.port:
            # grpc returns 0 on bind failure instead of raising; a
            # silent no-listener server would strand the gang with
            # bare UNAVAILABLEs
            self._server.stop(grace=0)
            self._server = None
            raise OSError(f"rendezvous bind failed on {address}")
        self._server.start()
        return self.port

    def stop(self) -> None:
        # release every parked fence first: a handler blocked in
        # done.wait() sits on a NON-daemon gRPC worker thread and
        # would pin process exit until its timeout
        with self._lock:
            for st in self._fences.values():
                if not st.done.is_set():
                    st.error = "rendezvous server shutting down"
                    st.done.set()
        if self._server is not None:
            self._server.stop(grace=0.2)


class RendezvousClient:
    """Member-side stub (used by cranesched_tpu.coord) — the shared
    GrpcStub plumbing with the gang-token header."""

    def __init__(self, address: str, token: str = "", tls=None,
                 epoch: int = 0):
        from cranesched_tpu.rpc.stub import GrpcStub
        self._stub = GrpcStub(address, RDZV_SERVICE, token=token,
                              token_key="crane-rdzv-token", tls=tls)
        # default incarnation stamped on every call (0 = no-check);
        # per-call override via the epoch= kwarg
        self.epoch = epoch

    def put(self, key: str, value: bytes,
            epoch: int | None = None) -> None:
        reply = self._stub.call(
            "Put", pb.RdzvPutRequest(
                key=key, value=value,
                epoch=self.epoch if epoch is None else epoch),
            pb.OkReply)
        if not reply.ok:
            raise RuntimeError(f"put {key!r} rejected: {reply.error}")

    def get(self, key: str, timeout: float = 0.0) -> bytes | None:
        reply = self._stub.call(
            "Get", pb.RdzvGetRequest(key=key, timeout=timeout),
            pb.RdzvGetReply, timeout=timeout + 30.0)
        return reply.value if reply.ok else None

    def fence(self, fence_id: str, rank: int, nranks: int,
              data: bytes = b"", timeout: float = 300.0,
              epoch: int | None = None) -> list[bytes]:
        reply = self._stub.call(
            "Fence",
            pb.RdzvFenceRequest(
                fence_id=fence_id, rank=rank, nranks=nranks, data=data,
                timeout=timeout,
                epoch=self.epoch if epoch is None else epoch),
            pb.RdzvFenceReply, timeout=timeout + 30.0)
        if not reply.ok:
            raise RuntimeError(f"fence {fence_id!r} failed: "
                               f"{reply.error}")
        return list(reply.data)

    def close(self) -> None:
        self._stub.close()
