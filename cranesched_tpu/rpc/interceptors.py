"""gRPC server interceptor feeding the obs metrics registry.

One interceptor instance per daemon process (ctld's CtldServer and the
craned daemon's supervisor-facing server both install it): per-method
request count, latency histogram, and error count under the
``crane_rpc_*`` names.  Errors are exceptions escaping the handler —
application-level ``ok=False`` replies are successes at this layer, the
same line Prometheus draws between transport and application errors.
"""

from __future__ import annotations

import time

import grpc

from cranesched_tpu.obs import REGISTRY


class MetricsInterceptor(grpc.ServerInterceptor):
    def __init__(self, registry=None, plane: str = "ctld"):
        reg = registry or REGISTRY
        self.plane = plane
        self._requests = reg.counter(
            "crane_rpc_requests_total", "RPCs served (label method)")
        self._errors = reg.counter(
            "crane_rpc_errors_total",
            "RPCs whose handler raised (label method)")
        self._latency = reg.histogram(
            "crane_rpc_latency_seconds",
            "RPC handler wall time (label method)")

    def _observe(self, method: str, fn, request, context):
        t0 = time.perf_counter()
        try:
            return fn(request, context)
        except Exception:
            self._errors.inc(method=method, plane=self.plane)
            raise
        finally:
            self._requests.inc(method=method, plane=self.plane)
            self._latency.observe(time.perf_counter() - t0,
                                  method=method, plane=self.plane)

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        method = handler_call_details.method.rsplit("/", 1)[-1]
        if handler.unary_unary is not None:
            inner = handler.unary_unary

            def unary(request, context, _inner=inner, _m=method):
                return self._observe(_m, _inner, request, context)

            return grpc.unary_unary_rpc_method_handler(
                unary,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)
        if handler.unary_stream is not None:
            inner = handler.unary_stream

            def stream(request, context, _inner=inner, _m=method):
                # time to full drain: the latency a streaming client
                # actually experiences, not just first-byte
                t0 = time.perf_counter()
                try:
                    yield from _inner(request, context)
                except Exception:
                    self._errors.inc(method=_m, plane=self.plane)
                    raise
                finally:
                    self._requests.inc(method=_m, plane=self.plane)
                    self._latency.observe(time.perf_counter() - t0,
                                          method=_m, plane=self.plane)

            return grpc.unary_stream_rpc_method_handler(
                stream,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)
        return handler
