"""NumPy transcription of MultiFactorPriority — the parity reference.

Direct, loop-for-loop transcription of the reference's sorter
(src/CraneCtld/JobScheduler.cpp: CalculateFactorBound_ :7633-7754 and
CalculatePriority_ :7757-7819) in plain Python so it is obviously-correct
and diffable against the vectorized models/priority.py.

Jobs are dicts; accounts are plain strings like the C++ map keys, so the
transcription carries none of the dense-account-axis encoding the device
code uses.  Computed in float32 to match the device (the reference uses
double; only the ordering is contractual, but our two implementations must
agree bit-for-bit).
"""

from __future__ import annotations

import numpy as np

f32 = np.float32


def multifactor_priority_oracle(pending, running, weights):
    """pending/running: list[dict]; weights: dict with keys
    age/partition/job_size/fair_share/qos/favor_small/max_age.
    Returns np.float32[len(pending)] priorities.

    All job attributes are unsigned in the reference (uint32/uint64);
    negative inputs are clamped to 0, matching the device implementation.
    """
    clamp = lambda j: {k: (max(v, 0) if isinstance(v, (int, float)) else v)
                       for k, v in j.items()}
    pending = [clamp(j) for j in pending]
    running = [clamp(j) for j in running]
    # --- CalculateFactorBound_ ---
    age_max, age_min = 0.0, np.inf
    qos_max, qos_min = 0.0, np.inf
    part_max, part_min = 0.0, np.inf
    nodes_max, nodes_min = 0.0, np.inf
    mem_max, mem_min = 0.0, np.inf
    cpus_max, cpus_min = 0.0, np.inf
    acc_service = {}

    for job in pending:
        age = min(job["age"], weights["max_age"])
        acc_service[job["account"]] = f32(0.0)
        age_min, age_max = min(age, age_min), max(age, age_max)
        nodes_min = min(job["node_num"], nodes_min)
        nodes_max = max(job["node_num"], nodes_max)
        mem_min, mem_max = min(job["mem"], mem_min), max(job["mem"], mem_max)
        cpus_min = min(job["cpus"], cpus_min)
        cpus_max = max(job["cpus"], cpus_max)
        qos_min, qos_max = min(job["qos"], qos_min), max(job["qos"], qos_max)
        part_min = min(job["part"], part_min)
        part_max = max(job["part"], part_max)

    for job in running:
        nodes_min = min(job["node_num"], nodes_min)
        nodes_max = max(job["node_num"], nodes_max)
        mem_min, mem_max = min(job["mem"], mem_min), max(job["mem"], mem_max)
        cpus_min = min(job["cpus"], cpus_min)
        cpus_max = max(job["cpus"], cpus_max)
        qos_min, qos_max = min(job["qos"], qos_min), max(job["qos"], qos_max)
        part_min = min(job["part"], part_min)
        part_max = max(job["part"], part_max)

    for job in running:
        service_val = f32(0.0)
        if cpus_max > cpus_min:
            service_val += f32(job["cpus"] - cpus_min) / f32(cpus_max
                                                             - cpus_min)
        else:
            service_val += f32(1.0)
        if nodes_max > nodes_min:
            service_val += f32(job["node_num"] - nodes_min) / f32(nodes_max
                                                                  - nodes_min)
        else:
            service_val += f32(1.0)
        if mem_max > mem_min:
            service_val += f32(job["mem"] - mem_min) / f32(mem_max - mem_min)
        else:
            service_val += f32(1.0)
        prev = acc_service.get(job["account"], f32(0.0))
        acc_service[job["account"]] = f32(prev
                                          + service_val * f32(job["run_time"]))

    sv_min, sv_max = np.inf, 0.0
    for val in acc_service.values():
        sv_min, sv_max = min(val, sv_min), max(val, sv_max)

    # --- CalculatePriority_ per pending job ---
    out = np.zeros(len(pending), f32)
    for i, job in enumerate(pending):
        age = min(job["age"], weights["max_age"])
        age_f = f32(0.0)
        if age_max > age_min:
            age_f = f32(age - age_min) / f32(age_max - age_min)
        qos_f = f32(0.0)
        if qos_max > qos_min:
            qos_f = f32(job["qos"] - qos_min) / f32(qos_max - qos_min)
        part_f = f32(0.0)
        if part_max > part_min:
            part_f = f32(job["part"] - part_min) / f32(part_max - part_min)
        size_f = f32(0.0)
        if cpus_max > cpus_min:
            size_f += f32(job["cpus"] - cpus_min) / f32(cpus_max - cpus_min)
        if nodes_max > nodes_min:
            size_f += f32(job["node_num"] - nodes_min) / f32(nodes_max
                                                             - nodes_min)
        if mem_max > mem_min:
            size_f += f32(job["mem"] - mem_min) / f32(mem_max - mem_min)
        if weights["favor_small"]:
            size_f = f32(1.0) - f32(size_f) / f32(3.0)
        else:
            size_f = f32(size_f) / f32(3.0)
        fshare_f = f32(0.0)
        if sv_max > sv_min:
            fshare_f = f32(1.0) - (f32(acc_service[job["account"]] - sv_min)
                                   / f32(sv_max - sv_min))
        out[i] = (f32(weights["age"]) * age_f
                  + f32(weights["partition"]) * part_f
                  + f32(weights["job_size"]) * size_f
                  + f32(weights["fair_share"]) * fshare_f
                  + f32(weights["qos"]) * qos_f)
    return out
