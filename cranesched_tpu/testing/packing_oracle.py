"""NumPy oracle for the packed solve (task packing + exclusive nodes).

Loop transcription of the semantics pinned in models/packing.py (which
mirrors reference get_max_tasks cpp:6171-6186, exclusive cpp:6248-6262,
and the smallest-capacity-first task distribution cpp:6305-6344, with the
documented cheapest-gang divergence)."""

from __future__ import annotations

import numpy as np

from cranesched_tpu.models.solver import (
    COST_SCALE,
    REASON_CONSTRAINT,
    REASON_NONE,
    REASON_RESOURCE,
)
from cranesched_tpu.ops.resources import DIM_CPU

BIG = 2 ** 30


def _capacity(base, node_req, task_req, nt_min, nt_max):
    min_req = node_req + task_req * nt_min
    if not np.all(min_req <= base):
        return 0
    headroom = base - min_req
    cap = nt_min
    while cap < nt_max:
        if np.all(task_req <= headroom):
            headroom = headroom - task_req
            cap += 1
        else:
            break
    return int(cap)


def solve_packed_oracle(avail, total, alive, cost, jobs, max_nodes):
    """jobs: list of dicts with node_req/task_req/ntasks/ntasks_min/
    ntasks_max/node_num/time_limit/part_mask/exclusive/valid.
    Returns (placed, nodes, tasks, reason, avail', cost')."""
    avail = np.array(avail, np.int64)
    total = np.asarray(total)
    cost = np.round(np.asarray(cost)).astype(np.int64)
    alive = np.asarray(alive, bool)
    N = avail.shape[0]
    J = len(jobs)
    placed = np.zeros(J, bool)
    nodes_out = np.full((J, max_nodes), -1, np.int32)
    tasks_out = np.zeros((J, max_nodes), np.int32)
    reason = np.zeros(J, np.int32)

    for j, job in enumerate(jobs):
        eligible = alive & np.asarray(job["part_mask"], bool)
        nn = int(job["node_num"])
        if not job["valid"] or nn <= 0 or nn > max_nodes:
            bad = (not job["valid"]) or nn <= 0
            reason[j] = (REASON_CONSTRAINT
                         if bad or eligible.sum() < nn
                         else REASON_RESOURCE)
            continue
        cap = np.zeros(N, np.int64)
        feasible = np.zeros(N, bool)
        for n in range(N):
            if not eligible[n]:
                continue
            base = total[n] if job["exclusive"] else avail[n]
            c = _capacity(base, job["node_req"], job["task_req"],
                          int(job["ntasks_min"]), int(job["ntasks_max"]))
            cap[n] = c
            feasible[n] = c > 0 and (
                np.all(avail[n] == total[n]) if job["exclusive"] else True)
        if feasible.sum() < nn:
            reason[j] = (REASON_RESOURCE if eligible.sum() >= nn
                         else REASON_CONSTRAINT)
            continue
        order = np.argsort(np.where(feasible, cost, BIG), kind="stable")
        chosen = order[:nn]
        if cap[chosen].sum() < job["ntasks"] or job["ntasks"] < nn:
            reason[j] = REASON_RESOURCE
            continue

        # distribute smallest-capacity-first, ties -> lowest node index
        dist = sorted(chosen, key=lambda n: (cap[n], n))
        rest = int(job["ntasks"]) - nn
        tasks = {}
        for n in dist:
            t = min(rest, int(cap[n]) - 1) + 1
            tasks[n] = t
            rest -= t - 1
        for k, n in enumerate(chosen):
            alloc = (total[n] if job["exclusive"]
                     else job["node_req"] + job["task_req"] * tasks[n])
            avail[n] -= alloc
            cpu_total = max(int(total[n, DIM_CPU]), 1)
            cost[n] += int(np.round(
                np.float32(job["time_limit"])
                * np.float32(alloc[DIM_CPU]) * np.float32(COST_SCALE)
                / np.float32(cpu_total)))
            nodes_out[j, k] = n
            tasks_out[j, k] = tasks[n]
        placed[j] = True
        reason[j] = REASON_NONE

    return placed, nodes_out, tasks_out, reason, avail, cost
