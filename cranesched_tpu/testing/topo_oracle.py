"""NumPy oracle for the topology-restricted solve — parity reference.

Independent transcription of ``topo.place.solve_greedy_topo``'s
semantics in plain Python loops (same relationship to it as
``testing/oracle.py`` has to ``models.solver.solve_greedy``):

* admission from GLOBAL feasibility counts, exactly solve_greedy's rule;
* best fit at the leaf level: smallest group size whose feasible count
  covers the gang, ties → lowest group id;
* otherwise the lowest upper level with a fitting group bounds the
  spanning set, and the gang spans the minimal prefix of leaf blocks
  ordered by (feasible count desc, block id asc);
* the restriction applies only to gangs (node_num > 1);
* selection inside the restriction and the int32 fixed-point cost
  update match testing/oracle.py bit-for-bit.
"""

from __future__ import annotations

import numpy as np

from cranesched_tpu.models.solver import (
    COST_SCALE,
    REASON_CONSTRAINT,
    REASON_NONE,
    REASON_RESOURCE,
)
from cranesched_tpu.ops.resources import DIM_CPU

_INF = 2**31 - 1


def _fit(feasible, gon, sizes, k):
    """(have, group, member_mask): smallest group fitting k feasible."""
    num_groups = len(sizes)
    counts = np.zeros(num_groups + 1, np.int64)
    np.add.at(counts, np.where(gon >= 0, gon, num_groups),
              feasible.astype(np.int64))
    fits = counts[:num_groups] >= k
    key = np.where(fits, sizes.astype(np.int64), _INF)
    g = int(np.argmin(key)) if num_groups else 0
    if num_groups == 0 or not fits[g]:
        return False, -1, np.zeros_like(feasible)
    return True, g, gon == g


def _span(feasible, gon, sizes, k):
    """Minimal leaf-block prefix (count desc, id asc) covering k."""
    num_groups = len(sizes)
    counts = np.zeros(num_groups + 1, np.int64)
    np.add.at(counts, np.where(gon >= 0, gon, num_groups),
              feasible.astype(np.int64))
    order = np.argsort(-counts, kind="stable")
    sorted_counts = counts[order]
    cum = np.cumsum(sorted_counts)
    needed = ((cum - sorted_counts) < k) & (sorted_counts > 0)
    sel = np.zeros(num_groups + 1, bool)
    sel[order] = needed
    return sel[np.where(gon >= 0, gon, num_groups)]


def solve_greedy_topo_oracle(avail, total, alive, cost, req, node_num,
                             time_limit, part_mask, valid, max_nodes,
                             levels):
    """Same contract as topo.place.solve_greedy_topo, in NumPy.

    ``levels``: leaf-first ``[(group_of_node [N], sizes [G]), ...]``.
    Returns (placed[J], nodes[J, max_nodes], reason[J], avail', cost',
    in_block[J], cross[J], block[J]).
    """
    avail = np.array(avail, dtype=np.int64)
    cost = np.round(np.asarray(cost)).astype(np.int64)
    total = np.asarray(total)
    alive = np.asarray(alive, bool)
    levels = [(np.asarray(gon, np.int64), np.asarray(sizes, np.int64))
              for gon, sizes in levels]

    J = len(req)
    N = avail.shape[0]
    placed = np.zeros(J, bool)
    nodes_out = np.full((J, max_nodes), -1, np.int32)
    reason = np.zeros(J, np.int32)
    in_block = np.zeros(J, bool)
    cross = np.zeros(J, bool)
    block = np.full(J, -1, np.int32)

    for j in range(J):
        if not valid[j] or node_num[j] <= 0:
            reason[j] = REASON_CONSTRAINT
            continue
        k = int(node_num[j])
        eligible = alive & part_mask[j]
        if k > min(max_nodes, N):
            reason[j] = (REASON_RESOURCE if eligible.sum() >= k
                         else REASON_CONSTRAINT)
            continue
        feasible = eligible & np.all(req[j][None, :] <= avail, axis=-1)
        if feasible.sum() < k:
            reason[j] = (REASON_RESOURCE if eligible.sum() >= k
                         else REASON_CONSTRAINT)
            continue

        restrict = np.ones(N, bool)
        if k > 1:
            leaf_gon, leaf_sizes = levels[0]
            have_leaf, g, mask = _fit(feasible, leaf_gon, leaf_sizes, k)
            if have_leaf:
                restrict = mask
                in_block[j] = True
                block[j] = g
            else:
                anc = np.ones(N, bool)
                for gon, sizes in reversed(levels[1:]):
                    have, _, mask_l = _fit(feasible, gon, sizes, k)
                    if have:
                        anc = mask_l  # lowest fitting ancestor wins
                restrict = _span(feasible & anc, leaf_gon, leaf_sizes, k)
                cross[j] = True

        # ascending cost inside the restriction, ties -> lowest index
        order = np.argsort(np.where(feasible & restrict, cost, _INF),
                           kind="stable")
        chosen = order[:k]
        for n in chosen:
            avail[n] -= req[j]
            cpu_total = max(int(total[n, DIM_CPU]), 1)
            cost[n] += int(np.round(
                np.float32(time_limit[j])
                * np.float32(req[j, DIM_CPU]) * np.float32(COST_SCALE)
                / np.float32(cpu_total)))
        placed[j] = True
        nodes_out[j, :k] = chosen
        reason[j] = REASON_NONE

    return (placed, nodes_out, reason, avail.astype(np.int32), cost,
            in_block, cross, block)
