"""NumPy oracle for the time-axis solve — the parity reference.

Loop transcription of the grid scheduling semantics pinned in
models/solver_time.py (which itself mirrors the reference's
min-over-window fit, cpp:6278-6291, and earliest-start subset selection,
JobScheduler.h:792-865, on a uniform bucket grid).  Obviously-correct
nested loops, no vectorization.
"""

from __future__ import annotations

import numpy as np

from cranesched_tpu.models.solver import (
    REASON_CONSTRAINT,
    REASON_NONE,
    REASON_RESOURCE,
)
from cranesched_tpu.models.solver import COST_SCALE
from cranesched_tpu.models.solver_time import NO_START
from cranesched_tpu.ops.resources import DIM_CPU


def build_time_avail_oracle(avail, run_nodes, run_req, run_end_bucket,
                            num_buckets):
    """time_avail[n, t] = ledger avail + releases of running jobs whose
    end bucket <= t."""
    n, r = np.asarray(avail).shape
    ta = np.tile(np.asarray(avail, np.int64)[:, None, :],
                 (1, num_buckets, 1))
    for job_nodes, req, eb in zip(run_nodes, run_req, run_end_bucket):
        if eb >= num_buckets:
            continue
        for node in job_nodes:
            if node < 0:
                continue
            ta[node, max(eb, 0):, :] += np.asarray(req, np.int64)
    return ta


def solve_backfill_oracle(time_avail, total, alive, cost, req, node_num,
                          time_limit, part_mask, valid, max_nodes):
    """Same contract as models.solver_time.solve_backfill, in loops.

    Returns (placed[J], start[J], nodes[J, max_nodes], reason[J],
    time_avail', cost')."""
    ta = np.array(time_avail, np.int64)
    cost = np.round(np.asarray(cost)).astype(np.int64)
    total = np.asarray(total)
    alive = np.asarray(alive, bool)
    N, T, R = ta.shape
    J = len(req)

    placed = np.zeros(J, bool)
    start = np.full(J, int(NO_START), np.int64)
    nodes_out = np.full((J, max_nodes), -1, np.int32)
    reason = np.zeros(J, np.int32)

    for j in range(J):
        if not valid[j] or node_num[j] <= 0 or node_num[j] > max_nodes:
            eligible = alive & part_mask[j]
            bad = (not valid[j]) or node_num[j] <= 0
            reason[j] = (REASON_CONSTRAINT
                         if bad or eligible.sum() < node_num[j]
                         else REASON_RESOURCE)
            continue
        eligible = alive & part_mask[j]
        # unit grid (1 bucket == 1 second): duration in buckets is the
        # time_limit itself, floored to one bucket like the solver
        d = max(int(time_limit[j]), 1)

        # ok[n, s]: node n fits req for every bucket in [s, min(s+d, T))
        ok = np.zeros((N, T), bool)
        for n in range(N):
            if not eligible[n]:
                continue
            for s in range(T):
                e = min(s + d, T)
                ok[n, s] = bool(
                    np.all(req[j][None, :] <= ta[n, s:e]))
        s_found = -1
        for s in range(T):
            if ok[:, s].sum() >= node_num[j]:
                s_found = s
                break
        if s_found < 0:
            reason[j] = (REASON_RESOURCE
                         if eligible.sum() >= node_num[j]
                         else REASON_CONSTRAINT)
            continue

        order = np.argsort(np.where(ok[:, s_found], cost, 2 ** 31 - 1),
                           kind="stable")
        chosen = order[: node_num[j]]
        e = min(s_found + d, T)
        for n in chosen:
            ta[n, s_found:e] -= req[j]
            cpu_total = max(int(total[n, DIM_CPU]), 1)
            # int32 fixed-point dcost, same float32 op order as
            # quantized_dcost in models/solver.py
            cost[n] += int(np.round(
                np.float32(time_limit[j])
                * np.float32(req[j, DIM_CPU]) * np.float32(COST_SCALE)
                / np.float32(cpu_total)))
        placed[j] = True
        start[j] = s_found
        nodes_out[j, : node_num[j]] = chosen
        reason[j] = REASON_NONE

    return placed, start, nodes_out, reason, ta, cost
