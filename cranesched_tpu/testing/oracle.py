"""NumPy oracle for the placement solve — the parity reference.

Independent transcription of the scheduling semantics (reference:
src/CraneCtld/JobScheduler.cpp GetNodesAndTrySchedule_ :6147-6369, cost
policy MinCpuTimeRatioFirst JobScheduler.h:40-54), written in plain Python
loops so it is obviously-correct and diffable against the TPU solver.

The reference's only unspecified behavior — cost-tie ordering inside the
std::set<pair<double, NodeState*>> — is pinned to "lowest node index first",
and the TPU solver pins the same.

Uses the int32 fixed-point cost ledger (1/COST_SCALE cpu-second units,
see models/solver.py) to match the device solver exactly (the reference
uses double; cost magnitude ordering is what matters for parity, and both
of OUR implementations must agree bit-for-bit).
"""

from __future__ import annotations

import numpy as np

from cranesched_tpu.models.solver import (
    REASON_CONSTRAINT,
    REASON_NONE,
    REASON_RESOURCE,
)
from cranesched_tpu.models.solver import COST_SCALE
from cranesched_tpu.ops.resources import DIM_CPU


def solve_greedy_oracle(avail, total, alive, cost, req, node_num,
                        time_limit, part_mask, valid, max_nodes):
    """Same contract as models.solver.solve_greedy, in NumPy.

    Returns (placed[J], nodes[J, max_nodes], reason[J], avail', cost').
    """
    avail = np.array(avail, dtype=np.int64)  # headroom; values fit int32
    cost = np.round(np.asarray(cost)).astype(np.int64)
    total = np.asarray(total)
    alive = np.asarray(alive, bool)

    J = len(req)
    N = avail.shape[0]
    placed = np.zeros(J, bool)
    nodes_out = np.full((J, max_nodes), -1, np.int32)
    reason = np.zeros(J, np.int32)

    for j in range(J):
        if not valid[j] or node_num[j] <= 0:
            reason[j] = REASON_CONSTRAINT
            continue
        eligible = alive & part_mask[j]
        if node_num[j] > min(max_nodes, N):
            # exceeds the batch's static gang bound — refused, same reason
            # logic as the solver
            reason[j] = (REASON_RESOURCE if eligible.sum() >= node_num[j]
                         else REASON_CONSTRAINT)
            continue
        feasible = eligible & np.all(req[j][None, :] <= avail, axis=-1)
        if feasible.sum() < node_num[j]:
            reason[j] = (REASON_RESOURCE if eligible.sum() >= node_num[j]
                         else REASON_CONSTRAINT)
            continue
        # ascending cost, ties -> lowest index (stable sort over index order)
        order = np.argsort(np.where(feasible, cost, 2 ** 31 - 1),
                           kind="stable")
        chosen = order[: node_num[j]]
        for n in chosen:
            avail[n] -= req[j]
            cpu_total = max(int(total[n, DIM_CPU]), 1)
            # int32 fixed-point dcost, same float32 op order as
            # quantized_dcost in models/solver.py
            cost[n] += int(np.round(
                np.float32(time_limit[j])
                * np.float32(req[j, DIM_CPU]) * np.float32(COST_SCALE)
                / np.float32(cpu_total)))
        placed[j] = True
        # cost order (ties -> lowest index), matching the solver's top_k
        nodes_out[j, : node_num[j]] = chosen
        reason[j] = REASON_NONE

    return placed, nodes_out, reason, avail.astype(np.int32), cost
