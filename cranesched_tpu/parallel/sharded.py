"""Node-axis-sharded greedy solve: the multi-chip scheduling path.

The reference scales by throwing one big C++ process at the problem (the
cost-ordered node set walk in LocalScheduler::GetNodesAndTrySchedule_,
src/CraneCtld/JobScheduler.cpp:6147-6369, is strictly single-threaded per
scheduling domain).  The TPU-native design instead shards the *node axis*
of every cluster tensor across the device mesh (SURVEY.md §7), so a
100k-node cluster's state lives in D chips' HBM and each placement step is:

1. each shard computes feasibility + masked cost for its own nodes
   (pure local vector work, no communication);
2. each shard proposes its k cheapest feasible nodes (``lax.top_k``);
3. one ``all_gather`` over ICI merges the D*k candidates; every shard
   deterministically selects the same global k winners (ascending cost,
   ties to the lowest global node index — candidates arrive shard-major
   and within-shard ascending, so a stable argsort preserves that order);
4. each shard applies the resource subtraction for the winners it owns
   (scatter with OOB-drop — no communication).

Feasible/eligible *counts* (for the "can this gang ever fit" decision and
the pending-reason) are global ``psum`` reductions.

This mirrors how the per-cycle solve distributes: jobs stay replicated
(the greedy order is inherently sequential), nodes are the long axis.
The collectives per job are O(D * max_nodes) bytes — tiny — so the ICI
cost is latency-bound and amortized by XLA pipelining across scan steps.

Parity contract: bit-identical placements to ``models.solver.solve_greedy``
(asserted in tests/test_sharded_parity.py on an 8-device CPU mesh).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cranesched_tpu.models.solver import (
    COST_INF,
    ClusterState,
    JobBatch,
    Placements,
    apply_placement,
    cheapest_k,
    decide_job,
    job_feasibility,
)

NODE_AXIS = "nodes"


def make_node_mesh(devices=None) -> Mesh:
    """1-D device mesh over which the node axis is sharded."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def shard_cluster_state(state: ClusterState, mesh: Mesh) -> ClusterState:
    """Place the cluster tensors with the node axis sharded over the mesh."""
    row = NamedSharding(mesh, P(NODE_AXIS))
    mat = NamedSharding(mesh, P(NODE_AXIS, None))
    return ClusterState(
        avail=jax.device_put(state.avail, mat),
        total=jax.device_put(state.total, mat),
        alive=jax.device_put(state.alive, row),
        cost=jax.device_put(state.cost, row),
    )


def _place_one_shard(avail, cost, total, alive, req, node_num, time_limit,
                     part_mask, valid, max_nodes: int):
    """One placement step on one node shard (runs under shard_map).

    The per-job math (feasibility, admission decision, resource/cost
    update) is shared with the single-device solver — only the counts
    (psum) and the candidate merge (all_gather) are collective here.
    """
    local_n = avail.shape[0]
    shard = jax.lax.axis_index(NODE_AXIS)
    offset = shard * local_n

    eligible, feasible = job_feasibility(avail, alive, part_mask, req)
    num_feasible = jax.lax.psum(
        jnp.sum(feasible, dtype=jnp.int32), NODE_AXIS)
    num_eligible = jax.lax.psum(
        jnp.sum(eligible, dtype=jnp.int32), NODE_AXIS)
    ok, reason = decide_job(valid, node_num, max_nodes, num_feasible,
                            num_eligible)

    # Local k cheapest feasible nodes.  top_k ties resolve to the lowest
    # local index, matching the single-device solver's tie order.
    k = min(max_nodes, local_n)
    masked_cost = jnp.where(feasible, cost, COST_INF)
    cand_cost, lidx = cheapest_k(masked_cost, k)
    cand_gidx = lidx + offset

    # Merge candidates across shards (ICI all_gather), then select the
    # global k winners.  tiled=False -> [D, k] in shard order; flattening
    # keeps shard-major order so the stable argsort resolves cost ties to
    # the lowest global node index.
    all_cost = jax.lax.all_gather(cand_cost, NODE_AXIS).reshape(-1)
    all_gidx = jax.lax.all_gather(cand_gidx, NODE_AXIS).reshape(-1)
    order = jnp.argsort(all_cost, stable=True)[:max_nodes]
    sel_cost = all_cost[order]
    sel_gidx = all_gidx[order]

    k_mask = jnp.arange(max_nodes) < node_num
    sel = ok & k_mask & (sel_cost < COST_INF)
    chosen = jnp.where(sel, sel_gidx, -1)

    # Apply updates for winners this shard owns.  OOB sentinel + drop mode
    # (negative indices would wrap, so clamp explicitly).
    local = sel_gidx - offset
    owned = sel & (local >= 0) & (local < local_n)
    scatter_idx = jnp.where(owned, local, local_n)  # local_n == OOB
    avail, cost = apply_placement(avail, cost, total, req, time_limit,
                                  scatter_idx, owned)
    return avail, cost, ok, chosen, reason


@functools.partial(jax.jit, static_argnames=("max_nodes", "mesh"))
def solve_greedy_sharded(state: ClusterState, jobs: JobBatch, mesh: Mesh,
                         max_nodes: int = 1
                         ) -> tuple[Placements, ClusterState]:
    """Greedy in-priority-order placement with the node axis sharded.

    Same contract as ``models.solver.solve_greedy``; requires the node count
    to be divisible by the mesh size (callers pad dead nodes, which never
    match).  The returned state keeps its node-sharded layout so successive
    cycles never regather the cluster to one device.
    """
    max_nodes = min(max_nodes, state.num_nodes)

    def shard_fn(avail, total, alive, cost, req, node_num, time_limit,
                 part_mask, valid):
        def step(carry, job):
            a, c = carry
            jreq, jnn, jtl, jpm, jv = job
            a, c, ok, chosen, reason = _place_one_shard(
                a, c, total, alive, jreq, jnn, jtl, jpm, jv, max_nodes)
            return (a, c), (ok, chosen, reason)

        (avail, cost), (placed, nodes, reason) = jax.lax.scan(
            step, (avail, cost),
            (req, node_num, time_limit, part_mask, valid))
        return avail, cost, placed, nodes, reason

    node_row = P(NODE_AXIS)
    node_mat = P(NODE_AXIS, None)
    avail, cost, placed, nodes, reason = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(node_mat, node_mat, node_row, node_row,
                  P(None, None), P(None), P(None), P(None, NODE_AXIS),
                  P(None)),
        out_specs=(node_mat, node_row, P(None), P(None, None), P(None)),
        check_vma=False,
    )(state.avail, state.total, state.alive, state.cost,
      jobs.req, jobs.node_num, jobs.time_limit, jobs.part_mask, jobs.valid)

    new_state = state.replace(avail=avail, cost=cost)
    return Placements(placed=placed, nodes=nodes, reason=reason), new_state
