"""Node-axis-sharded greedy solve: the multi-chip scheduling path.

The reference scales by throwing one big C++ process at the problem (the
cost-ordered node set walk in LocalScheduler::GetNodesAndTrySchedule_,
src/CraneCtld/JobScheduler.cpp:6147-6369, is strictly single-threaded per
scheduling domain).  The TPU-native design instead shards the *node axis*
of every cluster tensor across the device mesh (SURVEY.md §7), so a
100k-node cluster's state lives in D chips' HBM and each placement step is:

1. each shard computes feasibility + masked cost for its own nodes
   (pure local vector work, no communication);
2. each shard proposes its k cheapest feasible nodes (``lax.top_k``);
3. one ``all_gather`` over ICI merges the D*k candidates; every shard
   deterministically selects the same global k winners (ascending cost,
   ties to the lowest global node index — candidates arrive shard-major
   and within-shard ascending, so a stable argsort preserves that order);
4. each shard applies the resource subtraction for the winners it owns
   (scatter with OOB-drop — no communication).

Feasible/eligible *counts* (for the "can this gang ever fit" decision and
the pending-reason) are global ``psum`` reductions.

This mirrors how the per-cycle solve distributes: jobs stay replicated
(the greedy order is inherently sequential), nodes are the long axis.
The collectives per job are O(D * max_nodes) bytes — tiny — so the ICI
cost is latency-bound and amortized by XLA pipelining across scan steps.

Parity contract: bit-identical placements to ``models.solver.solve_greedy``
(asserted in tests/test_sharded_parity.py on an 8-device CPU mesh).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cranesched_tpu.models.solver import (
    COST_INF,
    ClusterState,
    JobBatch,
    Placements,
    apply_placement,
    cheapest_k,
    decide_job,
    job_feasibility,
)
from cranesched_tpu.obs.introspect import instrument_jit as _instrument_jit

NODE_AXIS = "nodes"

# jax moved shard_map out of experimental (and renamed the replication
# check kwarg) around 0.5; support both spellings
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SHARD_MAP_KW = {"check_vma": False}
else:  # pragma: no cover - exercised on older jax only
    from jax.experimental.shard_map import shard_map as _shard_map
    _SHARD_MAP_KW = {"check_rep": False}


def make_node_mesh(devices=None) -> Mesh:
    """1-D device mesh over which the node axis is sharded."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def shard_cluster_state(state: ClusterState, mesh: Mesh) -> ClusterState:
    """Place the cluster tensors with the node axis sharded over the mesh."""
    row = NamedSharding(mesh, P(NODE_AXIS))
    mat = NamedSharding(mesh, P(NODE_AXIS, None))
    return ClusterState(
        avail=jax.device_put(state.avail, mat),
        total=jax.device_put(state.total, mat),
        alive=jax.device_put(state.alive, row),
        cost=jax.device_put(state.cost, row),
    )


def _place_one_shard(avail, cost, total, alive, req, node_num, time_limit,
                     part_mask, valid, max_nodes: int):
    """One placement step on one node shard (runs under shard_map).

    The per-job math (feasibility, admission decision, resource/cost
    update) is shared with the single-device solver — only the counts
    (psum) and the candidate merge (all_gather) are collective here.
    """
    local_n = avail.shape[0]
    shard = jax.lax.axis_index(NODE_AXIS)
    offset = shard * local_n

    eligible, feasible = job_feasibility(avail, alive, part_mask, req)
    num_feasible = jax.lax.psum(
        jnp.sum(feasible, dtype=jnp.int32), NODE_AXIS)
    num_eligible = jax.lax.psum(
        jnp.sum(eligible, dtype=jnp.int32), NODE_AXIS)
    ok, reason = decide_job(valid, node_num, max_nodes, num_feasible,
                            num_eligible)

    # Local k cheapest feasible nodes.  top_k ties resolve to the lowest
    # local index, matching the single-device solver's tie order.
    k = min(max_nodes, local_n)
    masked_cost = jnp.where(feasible, cost, COST_INF)
    cand_cost, lidx = cheapest_k(masked_cost, k)
    cand_gidx = lidx + offset

    # Merge candidates across shards (ICI all_gather), then select the
    # global k winners.  tiled=False -> [D, k] in shard order; flattening
    # keeps shard-major order so the stable argsort resolves cost ties to
    # the lowest global node index.
    all_cost = jax.lax.all_gather(cand_cost, NODE_AXIS).reshape(-1)
    all_gidx = jax.lax.all_gather(cand_gidx, NODE_AXIS).reshape(-1)
    order = jnp.argsort(all_cost, stable=True)[:max_nodes]
    sel_cost = all_cost[order]
    sel_gidx = all_gidx[order]

    k_mask = jnp.arange(max_nodes) < node_num
    sel = ok & k_mask & (sel_cost < COST_INF)
    chosen = jnp.where(sel, sel_gidx, -1)

    # Apply updates for winners this shard owns.  OOB sentinel + drop mode
    # (negative indices would wrap, so clamp explicitly).
    local = sel_gidx - offset
    owned = sel & (local >= 0) & (local < local_n)
    scatter_idx = jnp.where(owned, local, local_n)  # local_n == OOB
    avail, cost = apply_placement(avail, cost, total, req, time_limit,
                                  scatter_idx, owned)
    return avail, cost, ok, chosen, reason


@functools.partial(jax.jit, static_argnames=("max_nodes", "mesh"))
def solve_greedy_sharded(state: ClusterState, jobs: JobBatch, mesh: Mesh,
                         max_nodes: int = 1
                         ) -> tuple[Placements, ClusterState]:
    """Greedy in-priority-order placement with the node axis sharded.

    Same contract as ``models.solver.solve_greedy``; requires the node count
    to be divisible by the mesh size (callers pad dead nodes, which never
    match).  The returned state keeps its node-sharded layout so successive
    cycles never regather the cluster to one device.
    """
    max_nodes = min(max_nodes, state.num_nodes)

    def shard_fn(avail, total, alive, cost, req, node_num, time_limit,
                 part_mask, valid):
        def step(carry, job):
            a, c = carry
            jreq, jnn, jtl, jpm, jv = job
            a, c, ok, chosen, reason = _place_one_shard(
                a, c, total, alive, jreq, jnn, jtl, jpm, jv, max_nodes)
            return (a, c), (ok, chosen, reason)

        (avail, cost), (placed, nodes, reason) = jax.lax.scan(
            step, (avail, cost),
            (req, node_num, time_limit, part_mask, valid))
        return avail, cost, placed, nodes, reason

    node_row = P(NODE_AXIS)
    node_mat = P(NODE_AXIS, None)
    avail, cost, placed, nodes, reason = _shard_map(
        shard_fn, mesh=mesh,
        in_specs=(node_mat, node_mat, node_row, node_row,
                  P(None, None), P(None), P(None), P(None, NODE_AXIS),
                  P(None)),
        out_specs=(node_mat, node_row, P(None), P(None, None), P(None)),
        **_SHARD_MAP_KW,
    )(state.avail, state.total, state.alive, state.cost,
      jobs.req, jobs.node_num, jobs.time_limit, jobs.part_mask, jobs.valid)

    new_state = state.replace(avail=avail, cost=cost)
    return Placements(placed=placed, nodes=nodes, reason=reason), new_state


solve_greedy_sharded = _instrument_jit("solve_greedy_sharded",
                                       solve_greedy_sharded)


@functools.partial(jax.jit, static_argnames=("max_nodes", "mesh",
                                             "num_streams", "stream_len"))
def _solve_sharded_streamed(state: ClusterState, req, node_num,
                            time_limit, valid, job_class, class_masks,
                            stream_of_class, mesh: Mesh, max_nodes: int,
                            num_streams: int, stream_len: int
                            ) -> tuple[Placements, ClusterState]:
    """Factored-eligibility sharded solve with S independent job
    streams per scan step.

    Eligibility arrives as ``job_class[J]`` + ``class_masks[C, N]``
    (the class table is node-sharded alongside the cluster tensors, so
    no [J, N] mask ever exists on any device).  Jobs are regrouped
    stream-major exactly like the Pallas streamed kernel; each scan
    step then places one job from each of the S streams.  Because
    streams own pairwise-disjoint class masks (verified by
    ``plan_streams``), the S selections read pre-step state and their
    updates touch disjoint node sets — bit-identical to the serial
    order.  The payoff is collective BATCHING: one psum of 2*S counts
    and one all_gather of the S*k candidate block per step, instead of
    2 psums + 2 gathers per job — J*4 collectives become (J/S)*2.
    """
    J = req.shape[0]
    R = req.shape[1]
    S = num_streams
    L = stream_len
    C = class_masks.shape[0]
    K = min(max_nodes, state.num_nodes)

    cls = jnp.clip(job_class.astype(jnp.int32), 0, C - 1)
    stream = stream_of_class[cls]                       # [J]
    order = jnp.argsort(stream, stable=True)
    sorted_stream = stream[order]
    slot = (jnp.arange(J, dtype=jnp.int32)
            - jnp.searchsorted(sorted_stream,
                               sorted_stream).astype(jnp.int32))
    lin = sorted_stream * L + slot                      # [J] flat slots

    def scat(x, fill, dtype):
        flat = jnp.full((S * L,) + x.shape[1:], fill, dtype)
        return flat.at[lin].set(x[order].astype(dtype), mode="drop")

    # [S*L, ..] -> [S, L, ..] -> scan-major [L, S, ..]
    req_sl = scat(req, 0, jnp.int32).reshape(S, L, R).transpose(1, 0, 2)
    nn_sl = scat(node_num, 0, jnp.int32).reshape(S, L).T
    tl_sl = scat(time_limit, 0, jnp.int32).reshape(S, L).T
    v_sl = scat(valid, False, jnp.bool_).reshape(S, L).T
    cls_sl = scat(cls, 0, jnp.int32).reshape(S, L).T

    def shard_fn(avail, total, alive, cost, cm, req_x, nn_x, tl_x, cls_x,
                 v_x):
        local_n = avail.shape[0]
        shard = jax.lax.axis_index(NODE_AXIS)
        offset = shard * local_n
        k = min(max_nodes, local_n)

        def step(carry, xs):
            a, c = carry
            jreq, jnn, jtl, jcls, jv = xs

            # --- selection phase: all S streams against PRE-step state
            # (exact: no stream can touch another stream's nodes) ---
            feas_cnt, elig_cnt, cand_cost, cand_gidx = [], [], [], []
            for s in range(S):
                pm = cm[jcls[s]]
                eligible, feasible = job_feasibility(a, alive, pm,
                                                     jreq[s])
                feas_cnt.append(jnp.sum(feasible, dtype=jnp.int32))
                elig_cnt.append(jnp.sum(eligible, dtype=jnp.int32))
                masked_cost = jnp.where(feasible, c, COST_INF)
                cc, lidx = cheapest_k(masked_cost, k)
                cand_cost.append(cc)
                cand_gidx.append(lidx + offset)

            # --- batched collectives: ONE psum, ONE all_gather ---
            counts = jax.lax.psum(
                jnp.stack(feas_cnt + elig_cnt), NODE_AXIS)      # [2S]
            packed = jnp.stack(
                [jnp.stack(cand_cost), jnp.stack(cand_gidx)])   # [2, S, k]
            allp = jax.lax.all_gather(packed, NODE_AXIS)        # [D, 2, S, k]

            # --- decide + apply per stream (disjoint updates) ---
            oks, chosens, reasons = [], [], []
            for s in range(S):
                ok, reason = decide_job(jv[s], jnn[s], max_nodes,
                                        counts[s], counts[S + s])
                ac = allp[:, 0, s, :].reshape(-1)
                ag = allp[:, 1, s, :].reshape(-1)
                sel_order = jnp.argsort(ac, stable=True)[:max_nodes]
                sel_cost = ac[sel_order]
                sel_gidx = ag[sel_order]
                k_mask = jnp.arange(max_nodes) < jnn[s]
                sel = ok & k_mask & (sel_cost < COST_INF)
                chosen = jnp.where(sel, sel_gidx, -1)
                local = sel_gidx - offset
                owned = sel & (local >= 0) & (local < local_n)
                scatter_idx = jnp.where(owned, local, local_n)
                a, c = apply_placement(a, c, total, jreq[s], jtl[s],
                                       scatter_idx, owned)
                oks.append(ok)
                chosens.append(chosen)
                reasons.append(reason)
            return (a, c), (jnp.stack(oks), jnp.stack(chosens),
                            jnp.stack(reasons))

        (avail, cost), (placed, nodes, reason) = jax.lax.scan(
            step, (avail, cost), (req_x, nn_x, tl_x, cls_x, v_x))
        return avail, cost, placed, nodes, reason

    node_row = P(NODE_AXIS)
    node_mat = P(NODE_AXIS, None)
    avail, cost, placed, nodes, reason = _shard_map(
        shard_fn, mesh=mesh,
        in_specs=(node_mat, node_mat, node_row, node_row,
                  P(None, NODE_AXIS), P(None, None, None), P(None, None),
                  P(None, None), P(None, None), P(None, None)),
        out_specs=(node_mat, node_row, P(None, None),
                   P(None, None, None), P(None, None)),
        **_SHARD_MAP_KW,
    )(state.avail, state.total, state.alive, state.cost,
      class_masks, req_sl, nn_sl, tl_sl, cls_sl, v_sl)

    # [L, S, ..] -> [S, L, ..] -> flat, then gather each original job
    inv = jnp.zeros(J, jnp.int32).at[order].set(lin, mode="drop")
    placed_j = placed.transpose(1, 0).reshape(-1)[inv].astype(bool)
    nodes_j = nodes.transpose(1, 0, 2).reshape(S * L, K)[inv]
    reason_j = reason.transpose(1, 0).reshape(-1)[inv]

    new_state = state.replace(avail=avail, cost=cost)
    return (Placements(placed=placed_j, nodes=nodes_j, reason=reason_j),
            new_state)


_solve_sharded_streamed = _instrument_jit("solve_sharded_streamed",
                                          _solve_sharded_streamed)


def solve_greedy_sharded_classes(state: ClusterState, req, node_num,
                                 time_limit, valid, job_class,
                                 class_masks, mesh: Mesh,
                                 max_nodes: int = 1, max_streams: int = 4,
                                 plan=None
                                 ) -> tuple[Placements, ClusterState]:
    """Factored-eligibility sharded solve with auto stream dispatch.

    Accepts eligibility as (job_class, class_masks) — the sharded twin
    of ``solve_greedy_pallas_auto``.  When ``plan_streams`` finds a
    worthwhile class-disjoint packing the S-stream scan runs (batched
    collectives); otherwise the same scan runs with S=1, which is the
    plain serial order.  ``plan`` overrides the planner (the scheduler
    caches it per mask-table epoch).  Parity:
    tests/test_sharded_parity.py."""
    from cranesched_tpu.models.pallas_solver import plan_streams

    J = int(req.shape[0])
    if plan is None:
        # block_jobs=1: stream_len quantizes to ceil(longest/8)*8 —
        # scan steps, not kernel blocks, so no 256-job padding quantum
        plan = plan_streams(job_class, class_masks,
                            max_streams=max_streams, block_jobs=1)
    if plan is None:
        C = int(class_masks.shape[0])
        plan = (jnp.zeros(C, jnp.int32), 1,
                -(-max(J, 1) // 8) * 8)
    stream_of_class, S, L = plan
    return _solve_sharded_streamed(
        state, req, node_num, time_limit, valid, job_class, class_masks,
        stream_of_class, mesh=mesh, max_nodes=max_nodes, num_streams=S,
        stream_len=L)
