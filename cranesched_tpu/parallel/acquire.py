"""Hardened device acquisition: the bounded PJRT handshake.

Four benches in a row (r06-r09) hung acquiring the TPU backend, and
BENCH_r10's flight-recorder diagnosis finally named the culprit:
``backend_init``, wedged inside ``xla_client.initialize_pjrt_plugin``
(the TPU PJRT plugin) — before any compile, before any trace.  This
module makes backend bring-up a *bounded, observable handshake* instead
of an unbounded import side effect:

* :func:`preflight_report` — a stdlib-only snapshot of the environment
  the PJRT plugin is about to trust: ``TPU_*`` env vars, the libtpu
  shared object the plugin will dlopen, accelerator chip visibility
  (``/dev/accel*`` / ``/dev/vfio``), and the ``JAX_PLATFORMS`` routing.
  Collected BEFORE jax is imported, so a wedged plugin can never blind
  it — on a hang, the diagnosis says *why* the handshake had a chance
  to wedge (no chips visible, no libtpu, a stale ``TPU_*`` grpc
  address), not just *that* it did.

* :func:`acquire_backend` — the probe: a stdlib-self-contained
  subprocess stamps the acquisition phases (``env_preflight ->
  jax_import -> backend_init -> device_enum``, then the compile-warm
  phases when ``warm=True``) into an fsync'd heartbeat file
  (obs/flight.py protocol).  The parent enforces a hard budget; on
  expiry it harvests the child's ``faulthandler`` stacks via SIGUSR1,
  kills it, forces ``JAX_PLATFORMS=cpu`` in the CURRENT process, emits
  a typed ``backend_degraded`` event through the caller's sink, and
  returns a structured diagnosis — never a bare timeout.

* :func:`ensure_backend` — the scheduler's boot-path wrapper
  (ctld_main): skip when CPU is already forced, otherwise run the
  handshake (without compile warming) so a wedged plugin degrades the
  daemon to CPU within the budget instead of hanging the first cycle
  under the RPC lock.

``BENCH_ACQUIRE_INJECT_HANG=<phase>`` wedges the named phase on purpose
(the forensics self-test, mirroring ``BENCH_PROBE_INJECT_HANG`` which
is honored as an alias so existing drills keep working).

Metrics: ``crane_backend_acquire_seconds`` (histogram, by outcome) and
``crane_backend_acquire_failures_total`` (counter, by phase).
"""

from __future__ import annotations

import glob
import json
import os
import sys

from cranesched_tpu.obs.flight import PROBE_PHASES, read_heartbeat
from cranesched_tpu.obs.metrics import REGISTRY as _OBS

#: backend bring-up phases owned by this layer (the first four entries
#: of the full heartbeat protocol); the compile-warm tail belongs to
#: the bench probe and only runs with ``warm=True``.
ACQUIRE_PHASES = PROBE_PHASES[:4]
WARM_PHASES = PROBE_PHASES[4:]

#: boot-path budget (seconds) before the CPU fallback; override with
#: CRANE_ACQUIRE_TIMEOUT.  Deliberately smaller than the bench probe's
#: 420 s — a daemon must come up degraded fast, a bench can afford to
#: wait out a slow tunnel.
DEFAULT_BOOT_TIMEOUT_S = 120.0

_MET_ACQ_SECONDS = _OBS.histogram(
    "crane_backend_acquire_seconds",
    "wall time of the bounded PJRT backend-acquisition handshake, "
    "labeled by outcome (ok | timeout | error)")
_MET_ACQ_FAILURES = _OBS.counter(
    "crane_backend_acquire_failures_total",
    "backend acquisitions that timed out or errored, labeled by the "
    "last heartbeat phase reached (where the handshake wedged)")


def _tpu_env() -> dict:
    """Every env var the TPU PJRT plugin reads, values truncated."""
    keys = {k: v for k, v in os.environ.items()
            if k.startswith(("TPU_", "LIBTPU", "PJRT_"))}
    for extra in ("JAX_PLATFORMS", "XLA_FLAGS", "LD_LIBRARY_PATH"):
        if extra in os.environ:
            keys[extra] = os.environ[extra]
    return {k: (v[:120] + "..." if len(v) > 120 else v)
            for k, v in sorted(keys.items())}


def _find_libtpu() -> str:
    """The shared object ``initialize_pjrt_plugin`` will dlopen, if
    discoverable without importing jax."""
    explicit = os.environ.get("TPU_LIBRARY_PATH", "")
    if explicit and os.path.exists(explicit):
        return explicit
    try:
        import importlib.util
        spec = importlib.util.find_spec("libtpu")
        if spec is not None and spec.submodule_search_locations:
            for loc in spec.submodule_search_locations:
                for name in ("libtpu.so", "libtpu.so.1"):
                    cand = os.path.join(loc, name)
                    if os.path.exists(cand):
                        return cand
                return loc  # package present, .so layout unknown
    except Exception:
        pass
    for root in sys.path:
        if not root:
            continue
        cand = os.path.join(root, "libtpu", "libtpu.so")
        if os.path.exists(cand):
            return cand
    return ""


def preflight_report() -> dict:
    """Stdlib-only environment snapshot taken before any jax import —
    the "why could the plugin wedge" half of a hang diagnosis."""
    accel = sorted(glob.glob("/dev/accel*"))
    vfio = sorted(glob.glob("/dev/vfio/*"))
    libtpu = _find_libtpu()
    return {
        "jax_platforms": os.environ.get("JAX_PLATFORMS", "(unset)"),
        "libtpu_path": libtpu or "(not found)",
        "tpu_env": _tpu_env(),
        "chips": {"dev_accel": accel, "dev_vfio": vfio,
                  "visible": len(accel) + len(vfio)},
    }


def _preflight_summary(pf: dict) -> str:
    tpu_keys = [k for k in pf.get("tpu_env", {})
                if k.startswith(("TPU_", "LIBTPU"))]
    chips = pf.get("chips", {})
    return (f"env pre-flight: libtpu={pf.get('libtpu_path')!r}, "
            f"TPU_* vars={tpu_keys or '(none)'}, chip visibility "
            f"dev_accel={len(chips.get('dev_accel', []))} "
            f"dev_vfio={len(chips.get('dev_vfio', []))}, "
            f"JAX_PLATFORMS={pf.get('jax_platforms')!r}")


# The probe child (obs/flight.py heartbeat protocol).  Deliberately
# stdlib-self-contained: importing cranesched_tpu here could pull jax
# via package __init__s BEFORE the jax_import stamp, which would blind
# the one phase the probe most suspects.  A stamp marks the phase's
# START, fsync'd before proceeding, so on a hang the last line on disk
# names the phase it died in.
_ACQUIRE_PROBE_SRC = r"""
import faulthandler, json, os, signal, sys, time

hb_path, stack_path, cache_dir, warm = (
    sys.argv[1], sys.argv[2], sys.argv[3], sys.argv[4] == "1")
hb = open(hb_path, "a", encoding="utf-8")


def stamp(phase, **extra):
    rec = {"t": time.time(), "phase": phase}
    rec.update(extra)
    hb.write(json.dumps(rec) + "\n")
    hb.flush()
    os.fsync(hb.fileno())
    hang = (os.environ.get("BENCH_ACQUIRE_INJECT_HANG", "")
            or os.environ.get("BENCH_PROBE_INJECT_HANG", ""))
    if hang == phase:
        time.sleep(3600.0)


# the parent harvests this on timeout: SIGUSR1 -> all-thread tracebacks
stack_fh = open(stack_path, "w", encoding="utf-8")
faulthandler.register(signal.SIGUSR1, file=stack_fh, all_threads=True)

stamp("env_preflight")
stamp("jax_import")
import jax

cache = {"enabled": False, "hits": 0, "misses": 0, "error": ""}
try:
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    import jax.monitoring as _mon

    def _ev(event, **kw):
        if event.endswith("cache_hits"):
            cache["hits"] += 1
        elif event.endswith("cache_misses"):
            cache["misses"] += 1

    _mon.register_event_listener(_ev)
    cache["enabled"] = True
except Exception as e:
    cache["error"] = "%s: %s" % (type(e).__name__, e)

# backend_init is the PJRT plugin/runtime handshake itself — the phase
# BENCH_r10 caught wedged inside xla_client.initialize_pjrt_plugin
stamp("backend_init")
try:
    from jax.extend import backend as _jxb
    _backend = _jxb.get_backend()
except Exception:
    _backend = None
stamp("device_enum")
ds = jax.devices()
if warm:
    stamp("first_trace")
    import jax.numpy as jnp

    x = jnp.arange(16.0)
    fn = jax.jit(lambda v: (v * 2.0 + 1.0).sum())
    lowered = fn.lower(x)
    stamp("first_compile")
    compiled = lowered.compile()
    stamp("first_execute")
    float(compiled(x))
    stamp("steady_state")
    float(fn(x))
try:
    cache["entries"] = sum(1 for f in os.listdir(cache_dir)
                           if f.endswith("-cache"))
except OSError:
    cache["entries"] = 0
print(json.dumps({"ok": True, "platform": ds[0].platform,
                  "device_count": len(ds), "xla_cache": cache}))
"""


def _force_cpu_here() -> None:
    """Make THIS process unreachable for the wedged plugin: force CPU
    before jax initializes (env var alone does not win over a
    sitecustomize-registered plugin; config.update after import does)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized — nothing to force


def acquire_backend(timeout_s: float, *, warm: bool = True,
                    cache_dir: str | None = None,
                    event_sink=None) -> dict:
    """Probe backend bring-up ONCE in a subprocess with a hard budget;
    fall back to CPU so the caller always makes progress.

    The probe stamps named phases (obs/flight.py PROBE_PHASES) into an
    fsync'd heartbeat file, so a timeout is never bare: the diagnosis
    names the phase it hung in, carries the child's faulthandler stack
    dump (harvested via SIGUSR1 before the kill), and the env
    pre-flight report saying why the plugin had a chance to wedge.
    ``event_sink(type, severity, detail)`` — e.g. a bound
    ``EventLog.emit`` — receives a typed ``backend_degraded`` event on
    any failure.  The returned dict lands verbatim in bench output /
    boot logs: a CPU number must never masquerade as a TPU result
    without saying why."""
    import signal
    import subprocess
    import tempfile
    import time as _time

    preflight = preflight_report()
    workdir = tempfile.mkdtemp(prefix="crane-acquire-")
    hb_path = os.path.join(workdir, "heartbeat.jsonl")
    stack_path = os.path.join(workdir, "stacks.txt")
    if cache_dir is None:
        cache_dir = os.environ.get(
            "BENCH_XLA_CACHE_DIR", os.path.join("profiles", "xla_cache"))
    t0 = _time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _ACQUIRE_PROBE_SRC,
         hb_path, stack_path, cache_dir, "1" if warm else "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    timed_out = False
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        # harvest the child's stacks while it is still wedged: SIGUSR1
        # fires its faulthandler dump, then the kill
        try:
            proc.send_signal(signal.SIGUSR1)
            _time.sleep(2.0)
        except Exception:
            pass
        proc.kill()
        out, err = proc.communicate()
    elapsed = round(_time.monotonic() - t0, 1)
    beats = read_heartbeat(hb_path)
    phases = [b["phase"] for b in beats]
    stamps = [{"phase": b["phase"], "t": b["t"]} for b in beats]
    protocol = (PROBE_PHASES if warm else ACQUIRE_PHASES)
    if not timed_out and proc.returncode == 0:
        doc = {}
        try:
            doc = json.loads(out.strip().splitlines()[-1])
        except (ValueError, IndexError):
            pass
        if doc.get("ok"):
            _MET_ACQ_SECONDS.observe(elapsed, outcome="ok")
            return {"acquired": True,
                    "attempts": [{"outcome": "ok",
                                  "seconds": elapsed}],
                    "platform": doc.get("platform", ""),
                    "device_count": doc.get("device_count", 0),
                    "phases": phases,
                    "phase_stamps": stamps,
                    "preflight": preflight,
                    "xla_cache": doc.get("xla_cache", {})}
    try:
        with open(stack_path, encoding="utf-8") as fh:
            stacks = fh.read().strip()
    except OSError:
        stacks = ""
    configured = os.environ.get("JAX_PLATFORMS", "auto")
    _force_cpu_here()
    last = phases[-1] if phases else "(no heartbeat — died pre-stamp)"
    if timed_out:
        pos = (f"{protocol.index(last) + 1}/{len(protocol)}"
               if last in protocol else "?")
        attempt = {"outcome": "timeout", "seconds": elapsed,
                   "last_phase": last, "phases": phases}
        diagnosis = (
            f"the device-acquisition handshake on platform "
            f"{configured!r} hung in phase {last!r} ({pos} of the "
            f"heartbeat protocol) and did not finish within the "
            f"{timeout_s:.0f} s budget; "
            f"{'an all-thread stack dump was captured' if stacks else 'no stack dump could be harvested'}. "
            f"{_preflight_summary(preflight)}. "
            "Falling back to CPU so the caller still makes progress; "
            "the backend below is therefore NOT a TPU.")
        _MET_ACQ_SECONDS.observe(elapsed, outcome="timeout")
    else:
        attempt = {
            "outcome": f"rc={proc.returncode}", "seconds": elapsed,
            "phases": phases,
            "tail": ((err or out) or "").strip()[-300:]}
        diagnosis = (
            f"the device-acquisition handshake on platform "
            f"{configured!r} exited with {attempt['outcome']} after "
            f"{elapsed} s having reached phase "
            f"{phases[-1] if phases else '(none)'!r} "
            f"({attempt['tail']!r}). {_preflight_summary(preflight)}. "
            "Falling back to CPU so the caller still makes progress; "
            "the backend below is therefore NOT a TPU.")
        _MET_ACQ_SECONDS.observe(elapsed, outcome="error")
    _MET_ACQ_FAILURES.inc(phase=last if last in protocol else "(none)")
    if event_sink is not None:
        try:
            event_sink("backend_degraded", "error",
                       f"acquisition {attempt['outcome']} in phase "
                       f"{last!r} after {elapsed}s; running on CPU "
                       f"fallback ({_preflight_summary(preflight)})")
        except Exception:
            pass  # a broken sink must never mask the fallback itself
    return {"acquired": False, "attempts": [attempt],
            "diagnosis": diagnosis, "phases": phases,
            "phase_stamps": stamps, "preflight": preflight,
            "last_phase": phases[-1] if phases else "",
            "stacks": stacks[-4000:]}


def ensure_backend(timeout_s: float | None = None,
                   event_sink=None) -> dict:
    """The scheduler boot path: make backend bring-up bounded before
    the first cycle can touch jax under the RPC lock.

    CPU already forced -> nothing to probe (the env-forcing half is
    still applied, matching the historic ctld_main behavior).
    Otherwise run
    the acquisition handshake WITHOUT compile warming; on failure the
    process is already degraded to CPU by the time this returns."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("CRANE_ACQUIRE_TIMEOUT",
                                         DEFAULT_BOOT_TIMEOUT_S))
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms == "cpu":
        _force_cpu_here()
        return {"acquired": True, "platform": "cpu", "attempts": [],
                "note": "JAX_PLATFORMS=cpu was pre-set",
                "preflight": preflight_report()}
    return acquire_backend(timeout_s, warm=False,
                           event_sink=event_sink)
