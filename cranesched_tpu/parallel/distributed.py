"""Multi-process pod-slice solve: node slabs across processes.

One process per host is the TPU pod-slice reality (a v4-32 is 4 hosts
x 4 chips; no single PJRT client sees all 16).  The node axis of the
cluster tensors therefore shards twice:

    process p owns the contiguous node slab [sum(n_0..n_{p-1}),
    sum(n_0..n_p)); inside the slab the existing ``shard_map`` solve
    (parallel.sharded) spreads rows over the process's LOCAL devices.

Cross-process merging is hierarchical.  Each scan step of the greedy
solve splits into a *select* and an *apply* half:

1. ``select``: every process computes, per job stream, its slab-level
   feasible/eligible counts and its k cheapest candidates (one local
   psum + one local all_gather over ICI — exactly the single-process
   solver's collectives, confined to the slab);
2. one host-level rendezvous ``Fence`` (rpc.rendezvous, epoch-tagged)
   all-gathers the packed counts + candidate blocks in rank order;
3. ``apply``: every process deterministically merges the P candidate
   lists (stable sort: cost ascending, ties to the lowest global node
   id — rank-major concatenation of per-slab sorted lists makes the
   stable sort resolve ties exactly like the single-process oracle),
   re-derives the same admission decision from the summed counts, and
   scatters the resource subtraction into whichever winner rows its
   slab owns.

Why a host fence and not ``jax.lax.psum`` over a global mesh: the CPU
backend (CI, and any host-only bring-up) cannot run cross-process XLA
computations at all ("Multiprocess computations aren't implemented on
the CPU backend", jaxlib 0.4.x), and on real pods the per-step payload
is O(P * S * max_nodes) bytes — latency-bound either way.  On silicon
with ``jax.distributed`` initialized, ``native_global_mesh()`` returns
a true global mesh instead and callers run ``solve_greedy_sharded*``
over it directly, skipping this module's host loop entirely.

Parity contract: ``solve_greedy_sharded_classes_mp`` is bit-identical
to single-process ``solve_greedy_sharded_classes`` on the concatenated
slabs (tests/test_multihost.py, overlapping and disjoint class
tables).

Metrics: ``crane_mesh_fence_seconds`` (host-barrier latency, by kind)
and ``crane_mesh_solve_seconds`` (wall time of one distributed solve,
by process count).
"""

from __future__ import annotations

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cranesched_tpu.models.solver import (
    COST_INF,
    ClusterState,
    Placements,
    apply_placement,
    cheapest_k,
    decide_job,
    job_feasibility,
)
from cranesched_tpu.obs.metrics import REGISTRY as _OBS
from cranesched_tpu.parallel.sharded import (
    NODE_AXIS,
    _SHARD_MAP_KW,
    _shard_map,
    make_node_mesh,
)
from cranesched_tpu.rpc.rendezvous import RendezvousClient

_MET_FENCE = _OBS.histogram(
    "crane_mesh_fence_seconds",
    "Host-level rendezvous fence latency in multi-process solves")
_MET_SOLVE = _OBS.histogram(
    "crane_mesh_solve_seconds",
    "Wall time of one multi-process sharded solve")

DEFAULT_FENCE_TIMEOUT_S = 120.0

# XLA's CPU collective rendezvous deadlocks when two THREADS of one
# process execute multi-device collective programs concurrently (the
# 8 per-device threads of both runs interleave at the same
# participant barrier).  A real deployment has one solver thread per
# process, so this lock is uncontended; it only serializes the
# in-process multi-rank harnesses (tests, bench's thread stand-in).
# Conversions to numpy happen INSIDE the lock so the program has
# fully retired before the next rank's program launches.
_EXEC_LOCK = threading.Lock()


def native_global_mesh():
    """The fast path for real pod slices: a single global mesh over
    every device of every process, valid only where the runtime can
    execute cross-process XLA computations (TPU/GPU under an
    initialized ``jax.distributed``; the CPU backend cannot).  Callers
    holding one run ``solve_greedy_sharded_classes`` on it directly —
    psum/all_gather ride ICI/DCN and no host fence exists.  Returns
    None when the hierarchical path is required."""
    if jax.process_count() <= 1:
        return None
    if jax.devices()[0].platform == "cpu":
        return None
    return make_node_mesh(jax.devices())


class ProcessMesh:
    """One process's membership in the gang of solver processes.

    Holds the local device mesh (this process's slab is device-sharded
    over it), the slab geometry agreed at bootstrap, and the
    epoch-tagged rendezvous client used for the per-step host fences.
    """

    def __init__(self, rank: int, nprocs: int, client: RendezvousClient,
                 epoch: int, mesh, node_offset: int, slab_nodes: int,
                 total_nodes: int, peers: list[dict],
                 fence_timeout: float = DEFAULT_FENCE_TIMEOUT_S):
        self.rank = rank
        self.nprocs = nprocs
        self.client = client
        self.epoch = epoch
        self.mesh = mesh
        self.node_offset = node_offset
        self.slab_nodes = slab_nodes
        self.total_nodes = total_nodes
        self.peers = peers
        self.fence_timeout = fence_timeout
        self._solve_seq = 0

    @property
    def local_device_count(self) -> int:
        return self.mesh.devices.size

    def describe(self) -> str:
        """``procs x local-devices`` — the MESH column of cstats."""
        return f"{self.nprocs}x{self.local_device_count}"

    def fence(self, name: str, payload: bytes = b"",
              timeout: float | None = None, kind: str = "solve"
              ) -> list[bytes]:
        t0 = time.monotonic()
        try:
            return self.client.fence(
                name, self.rank, self.nprocs, data=payload,
                timeout=self.fence_timeout if timeout is None
                else timeout)
        finally:
            _MET_FENCE.observe(time.monotonic() - t0, kind=kind)

    def next_solve_id(self) -> int:
        self._solve_seq += 1
        return self._solve_seq

    def close(self) -> None:
        self.client.close()


def bootstrap_process_mesh(rank: int, nprocs: int, slab_nodes: int, *,
                           address: str | None = None,
                           token: str | None = None, epoch: int = 1,
                           timeout: float = 60.0, tls=None
                           ) -> ProcessMesh:
    """The jax.distributed-shaped bootstrap over our own rendezvous.

    Every process dials the coordinator (``address`` or
    ``CRANE_RENDEZVOUS``), contributes its slab size and device
    inventory to an epoch-tagged boot fence, and derives the agreed
    slab offsets from the rank-ordered contributions.  A missing rank
    surfaces as the fence's structured ``x/y arrived`` timeout — never
    a silent hang (the whole point of ISSUE 17)."""
    address = address or os.environ.get("CRANE_RENDEZVOUS", "")
    if not address:
        raise ValueError("no coordinator: pass address= or set "
                         "CRANE_RENDEZVOUS")
    if token is None:
        token = os.environ.get("CRANE_RENDEZVOUS_TOKEN", "")
    client = RendezvousClient(address, token=token, tls=tls,
                              epoch=epoch)
    mesh = make_node_mesh()
    info = {"slab": int(slab_nodes),
            "devices": int(mesh.devices.size),
            "platform": jax.devices()[0].platform}
    t0 = time.monotonic()
    try:
        datas = client.fence(f"mesh/boot/{epoch}", rank, nprocs,
                             data=json.dumps(info).encode(),
                             timeout=timeout)
    finally:
        _MET_FENCE.observe(time.monotonic() - t0, kind="boot")
    peers = [json.loads(d.decode()) for d in datas]
    slabs = [int(p["slab"]) for p in peers]
    return ProcessMesh(
        rank=rank, nprocs=nprocs, client=client, epoch=epoch, mesh=mesh,
        node_offset=int(sum(slabs[:rank])), slab_nodes=int(slabs[rank]),
        total_nodes=int(sum(slabs)), peers=peers)


# ---- the select/apply split of one scan step ----
#
# Both halves compile ONCE per solve (every step has identical [S,...]
# shapes); the host loop between them is the fence.

def _select_step(avail, alive, cost, cm, jreq, jcls, *, mesh, k_slab):
    S = jreq.shape[0]

    def shard_fn(a, al, c, cm_l, jreq_x, jcls_x):
        local_n = a.shape[0]
        offset = jax.lax.axis_index(NODE_AXIS) * local_n
        k = min(k_slab, local_n)
        f_cnt, e_cnt, cc_l, cg_l = [], [], [], []
        for s in range(S):
            pm = cm_l[jcls_x[s]]
            eligible, feasible = job_feasibility(a, al, pm, jreq_x[s])
            f_cnt.append(jnp.sum(feasible, dtype=jnp.int32))
            e_cnt.append(jnp.sum(eligible, dtype=jnp.int32))
            masked = jnp.where(feasible, c, COST_INF)
            cc, lidx = cheapest_k(masked, k)
            cc_l.append(cc)
            cg_l.append(lidx + offset)
        # ONE local psum + ONE local all_gather per step, same
        # batching as the single-process streamed solver
        counts = jax.lax.psum(jnp.stack(f_cnt + e_cnt), NODE_AXIS)
        packed = jnp.stack([jnp.stack(cc_l), jnp.stack(cg_l)])
        allp = jax.lax.all_gather(packed, NODE_AXIS)     # [D, 2, S, k]
        sl_cost, sl_gidx = [], []
        for s in range(S):
            flat_c = allp[:, 0, s, :].reshape(-1)
            flat_g = allp[:, 1, s, :].reshape(-1)
            o = jnp.argsort(flat_c, stable=True)[:k_slab]
            sl_cost.append(flat_c[o])
            sl_gidx.append(flat_g[o])
        return counts, jnp.stack(sl_cost), jnp.stack(sl_gidx)

    node_row = P(NODE_AXIS)
    node_mat = P(NODE_AXIS, None)
    return _shard_map(
        shard_fn, mesh=mesh,
        in_specs=(node_mat, node_row, node_row, P(None, NODE_AXIS),
                  P(None, None), P(None)),
        out_specs=(P(None), P(None, None), P(None, None)),
        **_SHARD_MAP_KW,
    )(avail, alive, cost, cm, jreq, jcls)


_select_step = jax.jit(_select_step,
                       static_argnames=("mesh", "k_slab"))


def _apply_step(avail, cost, total, jreq, jnn, jtl, jv, counts,
                sel_cost, sel_gidx, slab_offset, *, mesh, max_nodes):
    S = jreq.shape[0]

    def shard_fn(a, c, t, jreq_x, jnn_x, jtl_x, jv_x, counts_x,
                 sc_x, sg_x, off_x):
        local_n = a.shape[0]
        offset = off_x + jax.lax.axis_index(NODE_AXIS) * local_n
        oks, chosens, reasons = [], [], []
        for s in range(S):
            ok, reason = decide_job(jv_x[s], jnn_x[s], max_nodes,
                                    counts_x[s], counts_x[S + s])
            k_mask = jnp.arange(max_nodes) < jnn_x[s]
            sel = ok & k_mask & (sc_x[s] < COST_INF)
            chosen = jnp.where(sel, sg_x[s], -1)
            local = sg_x[s] - offset
            owned = sel & (local >= 0) & (local < local_n)
            scatter_idx = jnp.where(owned, local, local_n)
            a, c = apply_placement(a, c, t, jreq_x[s], jtl_x[s],
                                   scatter_idx, owned)
            oks.append(ok)
            chosens.append(chosen)
            reasons.append(reason)
        return (a, c, jnp.stack(oks), jnp.stack(chosens),
                jnp.stack(reasons))

    node_row = P(NODE_AXIS)
    node_mat = P(NODE_AXIS, None)
    return _shard_map(
        shard_fn, mesh=mesh,
        in_specs=(node_mat, node_row, node_mat, P(None, None), P(None),
                  P(None), P(None), P(None), P(None, None),
                  P(None, None), P()),
        out_specs=(node_mat, node_row, P(None), P(None, None),
                   P(None)),
        **_SHARD_MAP_KW,
    )(avail, cost, total, jreq, jnn, jtl, jv, counts, sel_cost,
      sel_gidx, slab_offset)


_apply_step = jax.jit(_apply_step,
                      static_argnames=("mesh", "max_nodes"))


def _pack(counts, cc, cg) -> bytes:
    hdr = np.asarray([cc.shape[0], cc.shape[1]], np.int32)
    return b"".join(np.ascontiguousarray(x, "<i4").tobytes()
                    for x in (hdr, counts, cc.reshape(-1),
                              cg.reshape(-1)))


def _unpack(buf: bytes):
    a = np.frombuffer(buf, "<i4")
    s, k = int(a[0]), int(a[1])
    counts = a[2:2 + 2 * s]
    cc = a[2 + 2 * s:2 + 2 * s + s * k].reshape(s, k)
    cg = a[2 + 2 * s + s * k:2 + 2 * (s + s * k)].reshape(s, k)
    return counts, cc, cg


def solve_greedy_sharded_classes_mp(pmesh: ProcessMesh,
                                    state: ClusterState, req, node_num,
                                    time_limit, valid, job_class,
                                    class_masks, max_nodes: int = 1,
                                    plan=None
                                    ) -> tuple[Placements, ClusterState]:
    """Greedy class-table solve across the process mesh.

    ``state``/``class_masks`` hold only THIS process's node slab (the
    job tensors stay replicated, as in the single-process solver).
    Same contract and bit-identical results as running
    ``solve_greedy_sharded_classes`` over the concatenated slabs.

    ``plan`` must be identical on every rank when given (it fixes the
    fence count and payload shapes); the default is the serial S=1
    plan, which depends only on replicated job data and therefore
    always agrees.  Multi-stream plans from ``plan_streams`` are legal
    only when computed from the GLOBAL class table — a slab-local plan
    can disagree across ranks about class disjointness.
    """
    if int(state.num_nodes) != pmesh.slab_nodes:
        raise ValueError(
            f"state has {int(state.num_nodes)} nodes but this rank's "
            f"slab is {pmesh.slab_nodes}")
    if max_nodes > pmesh.total_nodes:
        raise ValueError(f"max_nodes {max_nodes} exceeds the "
                         f"{pmesh.total_nodes}-node cluster")
    J = int(req.shape[0])
    R = int(req.shape[1])
    C = int(class_masks.shape[0])
    if J == 0:
        return (Placements(
            placed=jnp.zeros((0,), bool),
            nodes=jnp.zeros((0, max_nodes), jnp.int32),
            reason=jnp.zeros((0,), jnp.int32)), state)
    if plan is None:
        plan = (np.zeros(C, np.int32), 1, -(-J // 8) * 8)
    stream_of_class, S, L = plan

    # stream-major regrouping, the host-side twin of the jnp version in
    # _solve_sharded_streamed (replicated inputs -> identical on every
    # rank)
    cls = np.clip(np.asarray(job_class, np.int32), 0, C - 1)
    stream = np.asarray(stream_of_class, np.int32)[cls]
    order = np.argsort(stream, kind="stable")
    sorted_stream = stream[order]
    slot = (np.arange(J, dtype=np.int32)
            - np.searchsorted(sorted_stream,
                              sorted_stream).astype(np.int32))
    lin = sorted_stream * L + slot

    def scat(x, fill, dtype):
        flat = np.full((S * L,) + np.asarray(x).shape[1:], fill, dtype)
        flat[lin] = np.asarray(x)[order]
        return flat

    req_sl = scat(req, 0, np.int32).reshape(S, L, R).transpose(1, 0, 2)
    nn_sl = scat(node_num, 0, np.int32).reshape(S, L).T
    tl_sl = scat(time_limit, 0, np.int32).reshape(S, L).T
    v_sl = scat(valid, False, np.bool_).reshape(S, L).T
    cls_sl = scat(cls, 0, np.int32).reshape(S, L).T

    k_slab = min(max_nodes, pmesh.slab_nodes)
    sid = pmesh.next_solve_id()
    avail, cost = state.avail, state.cost
    placed_sl = np.zeros((L, S), bool)
    nodes_sl = np.zeros((L, S, max_nodes), np.int32)
    reason_sl = np.zeros((L, S), np.int32)
    t0 = time.monotonic()
    for step in range(L):
        with _EXEC_LOCK:
            counts, cc, cg = _select_step(
                avail, state.alive, cost, class_masks,
                jnp.asarray(req_sl[step]), jnp.asarray(cls_sl[step]),
                mesh=pmesh.mesh, k_slab=k_slab)
            counts, cc, cg = (np.asarray(counts), np.asarray(cc),
                              np.asarray(cg))
        payload = _pack(counts, cc, cg + pmesh.node_offset)

        datas = pmesh.fence(f"solve/{pmesh.epoch}/{sid}/{step}",
                            payload)

        parts = [_unpack(d) for d in datas]   # rank order
        counts_g = np.sum([p[0] for p in parts], axis=0,
                          dtype=np.int64).astype(np.int32)
        sel_cost = np.full((S, max_nodes), COST_INF, np.int32)
        sel_gidx = np.full((S, max_nodes), -1, np.int32)
        for s in range(S):
            all_c = np.concatenate([p[1][s] for p in parts])
            all_g = np.concatenate([p[2][s] for p in parts])
            o = np.argsort(all_c, kind="stable")[:max_nodes]
            sel_cost[s, :o.size] = all_c[o]
            sel_gidx[s, :o.size] = all_g[o]

        with _EXEC_LOCK:
            avail, cost, placed, chosen, reason = _apply_step(
                avail, cost, state.total, jnp.asarray(req_sl[step]),
                jnp.asarray(nn_sl[step]), jnp.asarray(tl_sl[step]),
                jnp.asarray(v_sl[step]), jnp.asarray(counts_g),
                jnp.asarray(sel_cost), jnp.asarray(sel_gidx),
                jnp.int32(pmesh.node_offset), mesh=pmesh.mesh,
                max_nodes=max_nodes)
            placed_sl[step] = np.asarray(placed)
            nodes_sl[step] = np.asarray(chosen)
            reason_sl[step] = np.asarray(reason)
    _MET_SOLVE.observe(time.monotonic() - t0, procs=str(pmesh.nprocs))

    inv = np.zeros(J, np.int64)
    inv[order] = lin
    placed_j = placed_sl.transpose(1, 0).reshape(-1)[inv]
    nodes_j = nodes_sl.transpose(1, 0, 2).reshape(S * L, max_nodes)[inv]
    reason_j = reason_sl.transpose(1, 0).reshape(-1)[inv]

    new_state = state.replace(avail=avail, cost=cost)
    return (Placements(placed=jnp.asarray(placed_j),
                       nodes=jnp.asarray(nodes_j),
                       reason=jnp.asarray(reason_j)),
            new_state)
