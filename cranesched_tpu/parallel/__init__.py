"""Mesh/sharding layer: multi-device scheduling solves.

The node axis of the cluster tensors is sharded over the device mesh with
``shard_map``; cross-shard decisions (which k nodes are globally cheapest)
travel over ICI as ``all_gather``/``psum`` collectives.  See
``parallel.sharded`` for the design notes.
"""

# Lazy exports: parallel.acquire must be importable WITHOUT pulling
# jax into the process (the acquisition probe's whole point is deciding
# whether jax backend bring-up is safe), and sharded.py imports jax at
# module scope.
_SHARDED = ("make_node_mesh", "shard_cluster_state",
            "solve_greedy_sharded", "solve_greedy_sharded_classes")
_DISTRIBUTED = ("bootstrap_process_mesh", "ProcessMesh",
                "solve_greedy_sharded_classes_mp")
_ACQUIRE = ("acquire_backend", "ensure_backend", "preflight_report")

__all__ = [*_SHARDED, *_DISTRIBUTED, *_ACQUIRE]


def __getattr__(name):
    import importlib
    if name in _SHARDED:
        mod = importlib.import_module("cranesched_tpu.parallel.sharded")
    elif name in _DISTRIBUTED:
        mod = importlib.import_module(
            "cranesched_tpu.parallel.distributed")
    elif name in _ACQUIRE:
        mod = importlib.import_module("cranesched_tpu.parallel.acquire")
    else:
        raise AttributeError(name)
    return getattr(mod, name)
