"""Mesh/sharding layer: multi-device scheduling solves.

The node axis of the cluster tensors is sharded over the device mesh with
``shard_map``; cross-shard decisions (which k nodes are globally cheapest)
travel over ICI as ``all_gather``/``psum`` collectives.  See
``parallel.sharded`` for the design notes.
"""

from cranesched_tpu.parallel.sharded import (
    make_node_mesh,
    shard_cluster_state,
    solve_greedy_sharded,
)

__all__ = [
    "make_node_mesh",
    "shard_cluster_state",
    "solve_greedy_sharded",
]
