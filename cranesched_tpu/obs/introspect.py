"""JIT-compile telemetry, device-memory gauges, and on-demand profiler
capture windows.

ROADMAP item 1 rests on a claim nothing used to measure: that the
bucketed-padding contract (``_bucket`` in ctld/scheduler.py pads every
batch dimension to a power of two) keeps the steady-state cycle at ZERO
fresh XLA compiles.  This module makes that claim observable:

* :func:`instrument_jit` wraps each jit entry point (models/solver.py
  and the pallas/sharded/donating twins) with a cache-size observer.
  ``jax.jit`` callables expose ``_cache_size()``; if the cache grew
  across a call, that call paid a trace+compile — we count it
  (``crane_jit_compiles_total{fn}``) and attribute the call's wall time
  to ``crane_jit_compile_seconds{fn}``.  The probe is two dict-len
  reads per call (~1 µs) — cheap enough to leave on always.
* :func:`sample_device_memory` reads
  ``jax.local_devices()[0].memory_stats()`` into the
  ``crane_device_bytes_live`` / ``crane_device_peak_bytes`` /
  ``crane_device_buffers_live`` gauges, with a CPU-safe fallback
  (backends without allocator stats report bytes=-1, buffers still
  counted via ``jax.live_arrays``).
* :class:`ProfilerWindow` arms an N-cycle ``jax.profiler`` capture from
  an RPC (``CaptureProfile``); the scheduler ticks it at cycle
  boundaries and the trace lands under ``profiles/``.

The compile counters are process-global (the jit caches they observe
are), but per-cycle attribution is delta-based: the scheduler snapshots
:func:`total_compiles` at cycle start and records the delta in the
cycle trace (``recompiles``), emitting a ``recompile_steady`` event
when a warm cycle pays one.  All bookkeeping self-time is accumulated
in :func:`self_time_s` so the bench can prove the introspection plane
itself costs < 2% of a cycle.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from cranesched_tpu.obs.metrics import REGISTRY as _OBS

log = logging.getLogger("obs.introspect")

_MET_COMPILES = _OBS.counter(
    "crane_jit_compiles_total",
    "fresh XLA traces+compiles paid by a jit entry point, by fn")
_MET_COMPILE_SECONDS = _OBS.histogram(
    "crane_jit_compile_seconds",
    "wall time of calls that paid a fresh compile, by fn")
_MET_DEV_BYTES = _OBS.gauge(
    "crane_device_bytes_live",
    "bytes in use on device 0 (-1 when the backend has no stats)")
_MET_DEV_PEAK = _OBS.gauge(
    "crane_device_peak_bytes",
    "peak bytes in use on device 0 (-1 when unavailable)")
_MET_DEV_BUFFERS = _OBS.gauge(
    "crane_device_buffers_live",
    "live jax arrays in the process")

_lock = threading.Lock()
_total_compiles = 0
_self_time = 0.0  # seconds spent inside introspection bookkeeping


def total_compiles() -> int:
    """Process-wide count of observed fresh compiles (cycle-delta base)."""
    with _lock:
        return _total_compiles


def self_time_s() -> float:
    """Cumulative seconds of introspection overhead (observer probes +
    memory sampling) — the numerator of the bench's overhead share."""
    with _lock:
        return _self_time


def _note(n: int, dt: float) -> None:
    global _total_compiles
    with _lock:
        _total_compiles += n


def _add_self_time(dt: float) -> None:
    global _self_time
    with _lock:
        _self_time += dt


def instrument_jit(name: str, jitted: Callable) -> Callable:
    """Wrap a ``jax.jit`` callable with the compile observer.

    The wrapper preserves the jit object's surface that callers rely
    on: ``__wrapped__`` still reaches the plain-python function (so
    donating twins can re-jit it), and ``lower`` / ``clear_cache`` /
    ``_cache_size`` pass through.  Backends or jax versions without
    ``_cache_size`` degrade to a pass-through call (no counting, no
    breakage)."""
    cell = _MET_COMPILES.labels(fn=name)
    hcell = _MET_COMPILE_SECONDS.labels(fn=name)
    probe = getattr(jitted, "_cache_size", None)

    def wrapper(*args, **kwargs):
        if probe is None:
            return jitted(*args, **kwargs)
        p0 = time.perf_counter()
        try:
            before = probe()
        except Exception:  # pragma: no cover - defensive vs jax internals
            return jitted(*args, **kwargs)
        t0 = time.perf_counter()
        _add_self_time(t0 - p0)
        out = jitted(*args, **kwargs)
        t1 = time.perf_counter()
        try:
            grew = probe() - before
        except Exception:  # pragma: no cover
            grew = 0
        if grew > 0:
            cell.inc(grew)
            hcell.observe(t1 - t0)
            _note(grew, t1 - t0)
            log.debug("jit compile: %s (+%d entries, %.3fs)",
                      name, grew, t1 - t0)
        _add_self_time(time.perf_counter() - t1)
        return out

    wrapper.__name__ = f"observed_{name}"
    wrapper.__qualname__ = wrapper.__name__
    wrapper.__doc__ = getattr(jitted, "__doc__", None)
    # the plain python fn, NOT the jit object: donating twins re-jit it
    wrapper.__wrapped__ = getattr(jitted, "__wrapped__", jitted)
    wrapper._observed_jit = jitted
    for attr in ("lower", "clear_cache", "_cache_size", "trace"):
        member = getattr(jitted, attr, None)
        if member is not None:
            setattr(wrapper, attr, member)
    return wrapper


def sample_device_memory(peak_reset: bool = False) -> dict:
    """Device-0 allocator stats as a small dict, CPU-safe.

    Returns ``{"bytes": int, "peak_bytes": int, "buffers": int}``;
    bytes/peak are -1 when the backend exposes no ``memory_stats()``
    (the stock CPU client).  ``buffers`` counts live jax arrays in the
    process, which works on every backend."""
    t0 = time.perf_counter()
    bytes_live = peak = -1
    buffers = -1
    try:
        import jax
        try:
            devs = jax.local_devices()
            stats = devs[0].memory_stats() if devs else None
        except Exception:
            stats = None
        if stats:
            bytes_live = int(stats.get("bytes_in_use", -1))
            peak = int(stats.get("peak_bytes_in_use", -1))
        try:
            buffers = len(jax.live_arrays())
        except Exception:
            buffers = -1
    except Exception:  # jax itself unavailable/broken
        pass
    _MET_DEV_BYTES.set(bytes_live)
    _MET_DEV_PEAK.set(peak)
    if buffers >= 0:
        _MET_DEV_BUFFERS.set(buffers)
    _add_self_time(time.perf_counter() - t0)
    return {"bytes": bytes_live, "peak_bytes": peak, "buffers": buffers}


class ProfilerWindow:
    """RPC-armed ``jax.profiler`` capture spanning N scheduling cycles.

    ``request(cycles, out_dir)`` arms the window; the scheduler calls
    :meth:`tick` once per cycle (cheap no-op while disarmed).  The
    first tick after arming starts the trace; after ``cycles`` more
    ticks the trace stops and the capture directory is recorded in
    :attr:`last_capture`.  Never raises into the cycle loop."""

    def __init__(self, base_dir: str = "profiles",
                 event_sink: Optional[Callable] = None,
                 namespace: "str | Callable[[], str] | None" = None):
        self.base_dir = base_dir
        self.event_sink = event_sink
        # shard id (str, or callable resolved at request time — the
        # scheduler learns its shard name AFTER construction when the
        # fed plane attaches): federated shards often share one
        # filesystem, and two shards arming in the same instant must
        # not write traces into the same capture dir
        self.namespace = namespace
        self._lock = threading.Lock()
        self._armed = 0          # cycles requested, 0 = disarmed
        self._remaining = 0      # cycles left in an active capture
        self._active_dir = ""
        self._capture_seq = 0    # per-process uniquifier
        self.last_capture = ""
        self.last_error = ""
        self.captures_done = 0

    def _namespace(self) -> str:
        ns = self.namespace
        if callable(ns):
            try:
                ns = ns()
            except Exception:
                ns = ""
        return str(ns) if ns else ""

    def request(self, cycles: int, out_dir: str = "") -> tuple:
        """Arm a capture.  Returns (ok, dir-or-error)."""
        cycles = int(cycles)
        if cycles <= 0:
            return False, "cycles must be > 0"
        with self._lock:
            if self._armed or self._remaining:
                return False, "capture already in progress"
            self._capture_seq += 1
            ns = self._namespace()
            tag = (f"capture-{ns}-" if ns else "capture-")
            d = out_dir or os.path.join(
                self.base_dir,
                "%s%d-%d-%d" % (tag, int(time.time() * 1000),
                                os.getpid(), self._capture_seq))
            self._armed = cycles
            self._active_dir = d
        return True, d

    def tick(self) -> None:
        """Cycle-boundary hook: start / count down / stop the trace."""
        with self._lock:
            armed, remaining, d = (self._armed, self._remaining,
                                   self._active_dir)
        if not armed and not remaining:
            return
        if armed:
            try:
                os.makedirs(d, exist_ok=True)
                import jax
                jax.profiler.start_trace(d)
                with self._lock:
                    self._remaining = self._armed
                    self._armed = 0
                if self.event_sink is not None:
                    self.event_sink("profile_capture", "info",
                                    detail="started: %s" % d)
            except Exception as e:  # never break the cycle loop
                with self._lock:
                    self._armed = 0
                    self._active_dir = ""
                    self.last_error = str(e)
                log.warning("profiler capture failed to start: %s", e)
            return
        with self._lock:
            self._remaining -= 1
            done = self._remaining <= 0
        if done:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:
                with self._lock:
                    self.last_error = str(e)
                log.warning("profiler capture failed to stop: %s", e)
            with self._lock:
                self.last_capture = self._active_dir
                self._active_dir = ""
                self._remaining = 0
                self.captures_done += 1
            if self.event_sink is not None:
                self.event_sink("profile_capture", "info",
                                detail="written: %s" % self.last_capture)

    def status(self) -> dict:
        with self._lock:
            return {"armed": self._armed, "remaining": self._remaining,
                    "active_dir": self._active_dir,
                    "last_capture": self.last_capture,
                    "last_error": self.last_error,
                    "captures_done": self.captures_done}
