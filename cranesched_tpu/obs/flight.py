"""Stall forensics: the always-on flight recorder, the probe heartbeat
protocol, and the persistent XLA compilation cache wiring.

Four benches in a row (r06-r09) died the same way: the TPU probe timed
out and left ZERO forensics — "jax.devices() did not return within the
budget" names neither the phase that hung (import? backend init? first
compile?) nor the stack it hung on.  This module makes every stall —
probe-side or cycle-side — land with a phase attribution and an
all-thread stack dump:

* :class:`FlightRecorder` — a bounded ring of recent phase stamps (the
  scheduler stamps cycle_begin/prelude/commit/dispatch/cycle_end per
  cycle; ~6 appends, microseconds) plus a stall sentry the cycle loop
  arms around every cycle.  If the deadline passes while armed, the
  sentry captures ``sys._current_frames()`` for every thread into
  ``last_stall`` alongside the ring tail — the "what was the scheduler
  doing when it stopped" answer, without attaching a debugger to a
  wedged daemon.  All bookkeeping self-time is accumulated so the bench
  can prove the recorder costs <= 1% of a cycle.

* The heartbeat protocol — :class:`Heartbeat` writes one fsync'd JSON
  line per named phase (``PROBE_PHASES``: env preflight -> jax import
  -> backend init -> device enum -> first trace -> first compile ->
  first execute -> steady state);
  :func:`read_heartbeat` parses the file tolerantly (a probe killed
  mid-write leaves a torn last line, which is dropped, never raised
  on).  bench.py's TPU probe subprocess stamps these so the parent's
  timeout handler can say WHICH phase hung and harvest the child's
  ``faulthandler`` stack dump into the BENCH_*.json diagnosis.  The
  acquisition half of the protocol (and the probe subprocess itself)
  lives in parallel/acquire.py.

* :func:`enable_xla_cache` — points ``jax_compilation_cache_dir`` at a
  persistent directory (default ``profiles/xla_cache/``) with the size
  and compile-time floors dropped so every executable is cached, and
  registers a ``jax.monitoring`` listener that counts
  ``/jax/compilation_cache/cache_hits`` / ``cache_misses`` into
  ``crane_xla_cache_*``.  A hung first-compile is the leading stall
  suspect; a warm cache across probe runs removes the compile from the
  critical path entirely — and the hit/miss counters prove whether it
  actually did.

jax is imported only inside :func:`enable_xla_cache` — the recorder and
heartbeat halves must work in processes that are themselves trying to
find out whether importing jax hangs.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Optional

from cranesched_tpu.obs.metrics import REGISTRY as _OBS

#: the probe subprocess's named phases, in order.  A stamp marks the
#: phase's START — on a timeout, the last stamp names where it hung.
#: The first four are the acquisition handshake (owned by
#: parallel/acquire.py: env pre-flight, jax import, the PJRT
#: plugin/runtime init that BENCH_r10 caught wedged, device
#: enumeration); the tail is the bench probe's compile warm-up.
PROBE_PHASES = ("env_preflight", "jax_import", "backend_init",
                "device_enum", "first_trace", "first_compile",
                "first_execute", "steady_state")

_MET_STAMPS = _OBS.counter(
    "crane_flight_stamps_total",
    "phase stamps appended to the flight-recorder ring")
_MET_STALLS = _OBS.counter(
    "crane_flight_stalls_total",
    "stall-sentry firings (armed deadline passed; stacks captured)")
_MET_XLA_HITS = _OBS.counter(
    "crane_xla_cache_hits_total",
    "persistent XLA compilation cache hits")
_MET_XLA_MISSES = _OBS.counter(
    "crane_xla_cache_misses_total",
    "persistent XLA compilation cache misses (fresh compiles cached)")
_MET_XLA_ENTRIES = _OBS.gauge(
    "crane_xla_cache_entries",
    "executables in the persistent XLA cache directory")

_STAMPS_CELL = _MET_STAMPS.labels()


def dump_all_stacks() -> dict[str, list[str]]:
    """Formatted stack of every live thread, keyed ``name (tid)``.

    Pure-Python ``sys._current_frames`` — works on a RUNNING process
    (the sentry's case), unlike ``faulthandler`` which wants a file and
    C-level signal safety (the probe child's case)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: dict[str, list[str]] = {}
    for tid, frame in sys._current_frames().items():
        key = f"{names.get(tid, '?')} ({tid})"
        out[key] = [ln.rstrip("\n")
                    for ln in traceback.format_stack(frame)]
    return out


class FlightRecorder:
    """Bounded ring of phase stamps + an armable stall sentry.

    The scheduler owns one instance and stamps its cycle phases; the
    server's cycle loop arms the sentry before each cycle and disarms
    after.  A deadline that passes while armed fires ONCE: the sentry
    snapshots every thread's stack plus the ring tail into
    :attr:`last_stall`, bumps ``crane_flight_stalls_total``, emits a
    ``flight_stall`` event through ``event_sink``, and disarms (the
    next cycle re-arms).  Nothing here ever raises into the loop."""

    def __init__(self, capacity: int = 256,
                 event_sink: Optional[Callable] = None):
        self.capacity = max(int(capacity), 16)
        self.event_sink = event_sink
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.self_time_s = 0.0
        self.stalls_total = 0
        self.last_stall: dict | None = None
        # sentry state: deadline on the monotonic clock, None = disarmed
        self._deadline: float | None = None
        self._label = ""
        self._cond = threading.Condition(self._lock)
        self._sentry: threading.Thread | None = None
        self._closed = False

    # -- the hot path --

    def stamp(self, phase: str, detail: str = "",
              t: float | None = None) -> None:
        """Append one phase stamp (wall time, phase, detail)."""
        t0 = time.perf_counter()
        rec = {"t": time.time() if t is None else t, "phase": phase}
        if detail:
            rec["detail"] = detail
        with self._lock:
            self._ring.append(rec)
        _STAMPS_CELL.inc()
        self.self_time_s += time.perf_counter() - t0

    # -- the stall sentry --

    def arm(self, timeout_s: float, label: str = "cycle") -> None:
        """Start (or reset) the deadline; lazily spawns the sentry."""
        if timeout_s <= 0:
            return
        with self._cond:
            self._deadline = time.monotonic() + timeout_s
            self._label = label
            if self._sentry is None:
                self._sentry = threading.Thread(
                    target=self._sentry_loop, daemon=True,
                    name="flight-sentry")
                self._sentry.start()
            self._cond.notify()

    def disarm(self) -> None:
        with self._cond:
            self._deadline = None
            self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._deadline = None
            self._cond.notify()

    def _sentry_loop(self) -> None:
        while True:
            with self._cond:
                if self._closed:
                    return
                if self._deadline is None:
                    self._cond.wait()
                    continue
                wait = self._deadline - time.monotonic()
                if wait > 0:
                    self._cond.wait(wait)
                    continue
                # expired while still armed: fire once and disarm
                label = self._label
                self._deadline = None
            try:
                self._record_stall(label)
            except Exception:  # never kill the sentry
                pass

    def _record_stall(self, label: str) -> None:
        stacks = dump_all_stacks()
        with self._lock:
            phases = list(self._ring)[-16:]
        stall = {"time": time.time(), "label": label,
                 "phases": phases, "stacks": stacks}
        with self._lock:
            self.last_stall = stall
            self.stalls_total += 1
        _MET_STALLS.inc()
        if self.event_sink is not None:
            last = phases[-1]["phase"] if phases else "(no stamps)"
            self.event_sink(
                "flight_stall", "error",
                detail=f"{label} stalled; last phase {last}; "
                       f"{len(stacks)} thread stacks captured")

    # -- reading --

    def report(self, tail: int = 64) -> dict:
        """JSON-friendly dump for QueryStats / cflight."""
        with self._lock:
            return {"phases": list(self._ring)[-tail:],
                    "stalls_total": self.stalls_total,
                    "last_stall": self.last_stall,
                    "self_time_s": round(self.self_time_s, 6),
                    "armed": self._deadline is not None}


# ---------------------------------------------------------------------------
# the probe heartbeat protocol (bench.py TPU probe <-> parent)
# ---------------------------------------------------------------------------


class Heartbeat:
    """fsync'd phase stamps: one JSON line per stamp, durable before
    the writer proceeds — a probe killed mid-phase leaves its last
    stamp on disk, which is the whole point."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    def stamp(self, phase: str, detail: str = "") -> None:
        rec = {"t": time.time(), "phase": phase}
        if detail:
            rec["detail"] = detail
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        try:
            self._fh.close()
        except Exception:
            pass


def read_heartbeat(path: str) -> list[dict]:
    """Parse a heartbeat file; missing file -> [], torn last line
    dropped (the writer died mid-write — exactly the case this exists
    for)."""
    out: list[dict] = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail
                if isinstance(rec, dict) and "phase" in rec:
                    out.append(rec)
    except OSError:
        return []
    return out


# ---------------------------------------------------------------------------
# persistent XLA compilation cache
# ---------------------------------------------------------------------------

_xla_lock = threading.Lock()
_xla_state = {"enabled": False, "dir": "", "hits": 0, "misses": 0,
              "error": ""}
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_cache_event(event: str, **kw) -> None:
    if event == _HIT_EVENT:
        with _xla_lock:
            _xla_state["hits"] += 1
        _MET_XLA_HITS.inc()
    elif event == _MISS_EVENT:
        with _xla_lock:
            _xla_state["misses"] += 1
        _MET_XLA_MISSES.inc()


def enable_xla_cache(cache_dir: str = "") -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``
    (default ``profiles/xla_cache/`` under the cwd) and start counting
    hits/misses.  Idempotent; returns False (with the error recorded in
    :func:`xla_cache_stats`) when jax is unavailable or too old —
    callers degrade to uncached compiles, never crash."""
    cache_dir = cache_dir or os.path.join("profiles", "xla_cache")
    with _xla_lock:
        if _xla_state["enabled"] and _xla_state["dir"] == cache_dir:
            return True
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache EVERYTHING: the probe's first compile is exactly the
        # small-and-fast executable the default floors would skip
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        import jax.monitoring as _mon
        with _xla_lock:
            if not _xla_state["enabled"]:
                _mon.register_event_listener(_on_cache_event)
            _xla_state["enabled"] = True
            _xla_state["dir"] = cache_dir
            _xla_state["error"] = ""
        return True
    except Exception as e:
        with _xla_lock:
            _xla_state["error"] = f"{type(e).__name__}: {e}"
        return False


def xla_cache_stats() -> dict:
    """Hit/miss counters + on-disk entry count (JSON-friendly)."""
    with _xla_lock:
        st = dict(_xla_state)
    entries = 0
    if st["dir"]:
        try:
            entries = sum(1 for fn in os.listdir(st["dir"])
                          if fn.endswith("-cache"))
        except OSError:
            entries = 0
    _MET_XLA_ENTRIES.set(entries)
    total = st["hits"] + st["misses"]
    return {"enabled": st["enabled"], "dir": st["dir"],
            "hits": st["hits"], "misses": st["misses"],
            "entries": entries,
            "hit_rate": round(st["hits"] / total, 4) if total else 0.0,
            "error": st["error"]}
