"""Structured cluster event log: a bounded ring of typed, severity-
tagged events.

Where the cycle trace answers "how long did cycle N take" and the job
timeline answers "where did job J spend its latency", the event log
answers "what HAPPENED": a node flapped, a deposed leader's push was
fenced, the watchdog ate a cycle crash, an SLO started burning, a job
was preempted or requeued, a steady-state cycle paid a recompile.  Each
event is a small dict with a monotonically increasing sequence number
so clients (``cevents``) and the HA follower can cursor over it.

Design points:

* Per-process instances, NOT a module singleton: tests (and the HA
  harness) run a leader and a standby ctld in one process, and each
  must keep its own ring.  The scheduler owns the ctld instance.
* The ring is bounded (``capacity``): emission is O(1) append under a
  lock; ``since()`` filters are O(ring).  Nothing here is on the solve
  hot path — the busiest emitter is preemption, which is already a
  WAL-write-sized operation.
* Follower replication does NOT go through the WAL (the WAL replay
  path is job-records-only by contract).  Instead the leader's ring is
  cursored by ``after_event_seq`` piggybacked on ``HaFetchWal``;
  :meth:`ingest` adopts replicated events on the follower, assigning
  LOCAL seq numbers but remembering the leader's seq as the cursor
  (``remote_seq``) so a promoted follower keeps emitting without a seq
  collision.

Event types (severity in parens) — the closed vocabulary the tests and
docs assert on lives in :data:`EVENT_TYPES`:

    node_up (info)            craned registered / came back
    node_down (warning)       ping timeout or explicit down
    node_flap (warning)       node_up within FLAP_WINDOW of a down
    node_drain / node_undrain / node_poweroff / node_wake (info)
    fencing_rejection (error) a craned refused a push from a stale epoch
    watchdog_crash (error)    a scheduling cycle raised and was contained
    failover (critical)       this ctld promoted itself to leader
    slo_breach (error)        an SLO edge crossed its burn threshold
    slo_clear (info)          the breach condition cleared
    preemption (warning)      a running job was evicted for a higher one
    requeue (info)            a job went back to pending
    recompile_steady (warning) a warm cycle paid a fresh jit compile
    profile_capture (info)    a profiler window started/stopped
    fed_lease_granted (info)  this shard leased nodes to the arbiter
    fed_lease_revoked (warning) a lease expired/aborted and was dropped
    fed_forward (info)        a misrouted submit was forwarded
    fed_arbiter_commit (info) a cross-partition gang fully confirmed
    fed_arbiter_abort (warning) a partially-confirmed gang was undone
    flight_stall (error)      the flight-recorder stall sentry fired
                              (cycle deadline passed; stacks captured)
    cgroup_adopt_fallback (warning) PAM adoption granted access without
                              cgroup containment (cgroupfs unavailable)
"""

from __future__ import annotations

import itertools
import threading
import time as _time
from collections import deque

from cranesched_tpu.obs.metrics import REGISTRY as _OBS

SEVERITIES = ("debug", "info", "warning", "error", "critical")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

EVENT_TYPES = frozenset({
    "node_up", "node_down", "node_flap", "node_drain", "node_undrain",
    "node_poweroff", "node_wake", "fencing_rejection", "watchdog_crash",
    "failover", "slo_breach", "slo_clear", "preemption", "requeue",
    "recompile_steady", "profile_capture",
    # federated control plane (fed/): lease lifecycle on the shard,
    # misrouted-submit forwarding, arbiter two-phase outcomes
    "fed_lease_granted", "fed_lease_revoked", "fed_forward",
    "fed_arbiter_commit", "fed_arbiter_abort",
    # stall forensics (obs/flight.py): the armed cycle deadline passed
    # and the sentry captured all-thread stacks into last_stall
    "flight_stall",
    # craned PAM adoption fell back past cgroup containment (the
    # best-effort gap in craned/daemon.py, surfaced so drills can
    # assert on it instead of grepping logs)
    "cgroup_adopt_fallback",
})

#: a node_up this many seconds after a node_down counts as a flap
FLAP_WINDOW = 300.0

_MET_EVENTS = _OBS.counter(
    "crane_events_total",
    "structured cluster events emitted, by type and severity")


def severity_rank(severity: str) -> int:
    """Ordinal for severity filtering; unknown severities rank lowest."""
    return _SEV_RANK.get(severity, -1)


class EventLog:
    """Bounded, thread-safe ring of cluster events."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._last_seq = 0
        #: highest LEADER seq ingested via replication (follower cursor)
        self.remote_seq = 0
        # node -> last node_down time, for flap detection
        self._down_at: dict[str, float] = {}

    # -- emission --

    def emit(self, type: str, severity: str = "info", *, node: str = "",
             job_id: int = 0, detail: str = "", time: float = 0.0) -> dict:
        """Append one event; returns the stored record (with its seq)."""
        if severity not in _SEV_RANK:
            severity = "info"
        rec = {
            "seq": 0,  # assigned under the lock below
            "time": float(time) if time else _time.time(),
            "type": str(type),
            "severity": severity,
            "node": str(node),
            "job_id": int(job_id),
            "detail": str(detail),
        }
        with self._lock:
            rec["seq"] = next(self._seq)
            self._last_seq = rec["seq"]
            self._ring.append(rec)
        _MET_EVENTS.inc(type=rec["type"], severity=severity)
        return rec

    def emit_node_transition(self, event: str, node: str,
                             detail: str = "", now: float = 0.0) -> dict:
        """Node lifecycle emission with flap detection: a ``node_up``
        within :data:`FLAP_WINDOW` seconds of the node's last
        ``node_down`` additionally emits a ``node_flap`` warning."""
        now = float(now) if now else _time.time()
        event = (event if event.startswith("node_") else f"node_{event}")
        sev = "warning" if event == "node_down" else "info"
        rec = self.emit(event, severity=sev, node=node, detail=detail,
                        time=now)
        if rec["type"] == "node_down":
            with self._lock:
                self._down_at[node] = now
        elif rec["type"] == "node_up":
            with self._lock:
                down = self._down_at.pop(node, None)
            if down is not None and now - down <= FLAP_WINDOW:
                self.emit("node_flap", severity="warning", node=node,
                          detail="up %.1fs after down" % (now - down),
                          time=now)
        return rec

    def ingest(self, rec: dict) -> bool:
        """Adopt one REPLICATED event (follower side).  The leader's seq
        becomes the replication cursor; the stored copy gets a local
        seq so post-promotion emissions stay monotonic.  Returns False
        for duplicates (at-least-once fetches)."""
        origin = int(rec.get("seq", 0))
        with self._lock:
            if origin and origin <= self.remote_seq:
                return False
            local = dict(rec)
            local["seq"] = next(self._seq)
            self._last_seq = local["seq"]
            if origin:
                self.remote_seq = origin
            self._ring.append(local)
        return True

    # -- queries --

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._last_seq

    def since(self, after_seq: int = 0, severity: str = "",
              since_time: float = 0.0, type: str = "",
              limit: int = 0) -> list:
        """Events after ``after_seq``, optionally filtered by minimum
        severity, start time, and exact type; oldest first, capped at
        ``limit`` NEWEST matches when limit > 0."""
        min_rank = severity_rank(severity) if severity else -1
        with self._lock:
            out = [dict(r) for r in self._ring
                   if r["seq"] > after_seq
                   and severity_rank(r["severity"]) >= min_rank
                   and r["time"] >= since_time
                   and (not type or r["type"] == type)]
        if limit > 0 and len(out) > limit:
            out = out[-limit:]
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"events": len(self._ring), "last_seq": self._last_seq,
                    "capacity": self.capacity,
                    "remote_seq": self.remote_seq}
