"""Observability layer: dependency-free metrics, cycle tracing, per-job
lifecycle tracing with SLOs, and the scheduler watchdog.

- ``metrics.py``   process-wide registry of counters / gauges /
                   histograms with Prometheus text exposition and a
                   stdlib HTTP endpoint (no prometheus_client dep).
- ``trace.py``     bounded ring of structured per-cycle traces plus the
                   jax.profiler span helper used around solve closures.
- ``jobtrace.py``  event-sourced per-job timelines (one span per
                   lifecycle edge, ctld + craned clock domains) and the
                   derived latency histograms / exemplars.
- ``slo.py``       sliding-window p50/p99 targets over trace edges with
                   multi-window burn-rate gauges and a breach counter.

See ARCHITECTURE.md ("Observability" and "Per-job tracing and SLOs")
for the metric naming scheme and the timeline schema.
"""

from cranesched_tpu.obs.jobtrace import (  # noqa: F401
    SPAN_EDGES,
    JobTraceRecorder,
    render_waterfall,
)
from cranesched_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    serve_metrics,
)
from cranesched_tpu.obs.slo import (  # noqa: F401
    SloEngine,
    SloSpec,
)
from cranesched_tpu.obs.trace import (  # noqa: F401
    CycleTraceRing,
    solve_span,
)
