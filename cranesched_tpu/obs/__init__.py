"""Observability layer: dependency-free metrics, cycle tracing, and the
scheduler watchdog (round 6).

- ``metrics.py``  process-wide registry of counters / gauges /
                  histograms with Prometheus text exposition and a
                  stdlib HTTP endpoint (no prometheus_client dep).
- ``trace.py``    bounded ring of structured per-cycle traces plus the
                  jax.profiler span helper used around solve closures.

See ARCHITECTURE.md ("Observability") for the metric naming scheme and
the cycle-trace schema.
"""

from cranesched_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    serve_metrics,
)
from cranesched_tpu.obs.trace import (  # noqa: F401
    CycleTraceRing,
    solve_span,
)
