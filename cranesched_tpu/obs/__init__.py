"""Observability layer: dependency-free metrics, cycle tracing, per-job
lifecycle tracing with SLOs, and the scheduler watchdog.

- ``metrics.py``   process-wide registry of counters / gauges /
                   histograms with Prometheus text exposition and a
                   stdlib HTTP endpoint (no prometheus_client dep).
- ``trace.py``     bounded ring of structured per-cycle traces plus the
                   jax.profiler span helper used around solve closures.
- ``jobtrace.py``  event-sourced per-job timelines (one span per
                   lifecycle edge, ctld + craned clock domains) and the
                   derived latency histograms / exemplars.
- ``slo.py``       sliding-window p50/p99 targets over trace edges with
                   multi-window burn-rate gauges and a breach counter.
- ``flight.py``    stall forensics: always-on flight recorder (phase
                   ring + stall sentry with all-thread stack dumps),
                   the fsync'd probe heartbeat protocol, and the
                   persistent XLA compilation cache with hit/miss
                   counters.
- ``fedobs.py``    federation-wide merge: scatter-gather metric
                   aggregation and the cluster-level SLO engine over
                   per-shard summaries (exact burn-rate merge).

See ARCHITECTURE.md ("Observability" and "Per-job tracing and SLOs")
for the metric naming scheme and the timeline schema.
"""

from cranesched_tpu.obs.fedobs import (  # noqa: F401
    ClusterSlo,
    cluster_doc,
    merge_metric_snapshots,
    merge_slo_tables,
)
from cranesched_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    Heartbeat,
    PROBE_PHASES,
    dump_all_stacks,
    enable_xla_cache,
    read_heartbeat,
    xla_cache_stats,
)
from cranesched_tpu.obs.jobtrace import (  # noqa: F401
    FED_EDGES,
    SPAN_EDGES,
    JobTraceRecorder,
    render_waterfall,
)
from cranesched_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    serve_metrics,
)
from cranesched_tpu.obs.slo import (  # noqa: F401
    SloEngine,
    SloSpec,
)
from cranesched_tpu.obs.trace import (  # noqa: F401
    CycleTraceRing,
    solve_span,
)
